"""State-integrity primitives: the checksummed record codec, load-time
corruption screening, and content digests (state-integrity PR tentpole).

The HA stack (PRs 5/6/11) made every mutation journal-first, but the
journal bytes themselves were trusted blindly: one corrupted mid-file
record silently truncated every acknowledged bind behind it. This module
is the shared trust boundary all durable record streams go through:

* **Codec** — :func:`seal` stamps a record with a CRC32 over its
  canonical JSON (sorted keys, compact separators, ``crc`` excluded);
  :func:`verify` recomputes it. Every journal-store ``append``/
  ``rewrite`` seals, every ``load`` verifies — the koordlint
  ``store-integrity`` pass enforces that any class exposing the store
  protocol participates (or carries a written exemption).
* **Screening** — :func:`screen_records` classifies a loaded stream:
  a torn FINAL entry is a crash mid-append (unacknowledged — dropped,
  as before); an unverifiable MID-STREAM record is media corruption and
  is QUARANTINED (counted, surfaced, every verifiable record after it
  kept); duplicated seqs (a crash-retried append) are deduplicated; a
  seq GAP (a write hole) is counted and degrades the ``journal_integrity``
  health row without losing any surviving record.
* **Digests** — :func:`payload_digest` (canonical-JSON CRC, used by the
  checkpoint recovery image) and :func:`array_digest` (shape/dtype/bytes
  CRC over array pytrees, used by the resident-state scrubber and the
  recovery cross-check).

Legacy tolerance: records without a ``crc`` field (pre-codec journals)
load read-only — they are counted (``legacy``) but never quarantined, so
an in-place upgrade replays old journals unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Iterable, List, Optional, Sequence, Tuple

#: reserved codec field on every sealed record
CRC_FIELD = "crc"


def _canonical_payload(record: dict) -> bytes:
    """Canonical byte form the CRC covers: sorted-key compact JSON of
    everything except the ``crc`` field itself. Canonicalization (not
    the store's wire form) makes the checksum stable across a JSON
    round-trip and across dict insertion orders."""
    return json.dumps(
        {k: v for k, v in record.items() if k != CRC_FIELD},
        sort_keys=True,
        separators=(",", ":"),
    ).encode("utf-8")


def record_crc(record: dict) -> str:
    return format(zlib.crc32(_canonical_payload(record)) & 0xFFFFFFFF, "08x")


def seal(record: dict) -> dict:
    """Copy of ``record`` stamped with its content CRC. Idempotent: a
    record already carrying a correct ``crc`` re-seals to itself (a
    rewrite of loaded records must not re-checksum drifted content —
    an UNVERIFIABLE record never reaches a rewrite; screening dropped
    it at load)."""
    out = dict(record)
    out[CRC_FIELD] = record_crc(out)
    return out


def verify(record: dict) -> Optional[bool]:
    """True/False for a sealed record; None for a legacy (pre-codec)
    record carrying no ``crc`` field."""
    stamped = record.get(CRC_FIELD)
    if stamped is None:
        return None
    return stamped == record_crc(record)


def seal_records(records: Iterable[dict]) -> List[dict]:
    return [seal(r) for r in records]


# ---------------------------------------------------------------------------
# Load-time screening
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class IntegrityReport:
    """What one store load found: the degraded/ok evidence behind the
    ``journal_integrity`` health row, the
    ``journal_corrupt_records_total{store}`` counter and ``fsck``."""

    store: str = ""
    total: int = 0          #: entries seen (parse failures included)
    kept: int = 0           #: records that survived screening
    legacy: int = 0         #: kept records with no crc (pre-codec)
    corrupt: int = 0        #: quarantined entries (parse/CRC failures)
    dup_seq: int = 0        #: crash-retry duplicates deduplicated
    seq_gaps: int = 0       #: write holes (missing seq numbers)
    torn_tail: bool = False  #: unparseable FINAL entry (crash mid-append)
    #: human-readable description per quarantined entry, in stream order
    quarantined: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        """Clean load: nothing quarantined, no write holes. A torn tail
        and legacy records are NOT integrity failures (the former is an
        unacknowledged append, the latter a tolerated old format)."""
        return self.corrupt == 0 and self.seq_gaps == 0

    def detail(self) -> str:
        return (
            f"corrupt={self.corrupt} seq_gaps={self.seq_gaps} "
            f"dup_seq={self.dup_seq} legacy={self.legacy} "
            f"kept={self.kept}/{self.total}"
        )

    def merge(self, other: "IntegrityReport") -> None:
        self.total += other.total
        self.kept += other.kept
        self.legacy += other.legacy
        self.corrupt += other.corrupt
        self.dup_seq += other.dup_seq
        self.seq_gaps += other.seq_gaps
        self.torn_tail = self.torn_tail or other.torn_tail
        self.quarantined.extend(other.quarantined)


def screen_records(
    entries: Sequence[Tuple[Optional[dict], Optional[str]]],
    store: str = "",
    known_missing_seqs: Optional[Iterable[int]] = None,
) -> Tuple[List[dict], List[Tuple[int, Optional[str]]], IntegrityReport]:
    """Screen one loaded record stream.

    ``entries`` is the stream in storage order: ``(record, raw)`` pairs
    where ``record`` is None for an entry that failed to parse and
    ``raw`` is the storage form to quarantine (None for in-memory
    stores). Returns ``(kept, quarantine, report)`` — ``kept`` the
    surviving records in order, ``quarantine`` the ``(position, raw)``
    entries a sidecar should absorb.

    Classification rules (the tentpole's core distinction):

    * an unparseable FINAL entry is a torn tail — a crash mid-append
      whose bytes were never acknowledged; dropped, not corruption;
    * any other unverifiable entry (parse failure mid-stream, or a CRC
      mismatch anywhere) is media corruption — quarantined, counted,
      and every verifiable record after it is KEPT;
    * a repeated seq with identical payload is a crash-retried append —
      deduplicated to the first copy; a repeated seq with DIFFERENT
      payload quarantines the later copy;
    * a missing seq (gap) is a write hole — counted; nothing to
      quarantine, but the load is not clean.

    ``known_missing_seqs`` names seqs whose absence is ALREADY explained
    (a store's previously quarantined records) — they close their hole
    in the gap math instead of double-reporting one corruption as a
    corrupt record AND a write hole.
    """
    rep = IntegrityReport(store=store, total=len(entries))
    kept: List[dict] = []
    quarantine: List[Tuple[int, Optional[str]]] = []
    last = len(entries) - 1
    #: seq -> record payload for gap/dup math; quarantined and
    #: previously-quarantined seqs participate (their absence from the
    #: KEPT stream is explained corruption, not a write hole) but never
    #: reach `kept`
    seen_seq: dict = {}
    for s in known_missing_seqs or ():
        if isinstance(s, int):
            seen_seq.setdefault(s, None)
    #: quarantined entries whose seq is UNKNOWABLE (unparseable bytes):
    #: each physically occupied a seq, so each explains one hole — the
    #: gap math must not report the same corruption twice (once as a
    #: corrupt record, again as a write hole)
    no_seq_quarantined = 0
    for pos, (record, raw) in enumerate(entries):
        if record is None:
            if pos == last:
                rep.torn_tail = True
                continue
            rep.corrupt += 1
            rep.quarantined.append(f"entry {pos}: unparseable mid-stream")
            quarantine.append((pos, raw))
            no_seq_quarantined += 1
            continue
        ok = verify(record)
        if ok is False:
            rep.corrupt += 1
            rep.quarantined.append(
                f"entry {pos}: crc mismatch "
                f"(op={record.get('op', '?')} seq={record.get('seq', '?')})"
            )
            quarantine.append((pos, raw))
            if isinstance(record.get("seq"), int):
                seen_seq.setdefault(record["seq"], None)
            continue
        if ok is None:
            rep.legacy += 1
        if record.get("op") == "seq_tombstone":
            # a repair tool's marker: these seqs are EXPLAINED missing
            # (their records were quarantined and rewritten away) — they
            # close their holes in the gap math
            for s in record.get("seqs", ()):
                if isinstance(s, int):
                    seen_seq.setdefault(s, None)
        seq = record.get("seq")
        if isinstance(seq, int):
            prev = seen_seq.get(seq)
            if seq in seen_seq and prev is None:
                # seq known only as quarantined/missing: this verifiable
                # copy stands alone (no payload to compare) — keep it
                seen_seq[seq] = record
                kept.append(record)
                continue
            if prev is not None:
                if _canonical_payload(prev) == _canonical_payload(record):
                    rep.dup_seq += 1
                    continue
                rep.corrupt += 1
                rep.quarantined.append(
                    f"entry {pos}: seq {seq} duplicated with divergent "
                    "payload"
                )
                quarantine.append((pos, raw))
                continue
            seen_seq[seq] = record
        kept.append(record)
    seqs = sorted(s for s in seen_seq if isinstance(s, int))
    for a, b in zip(seqs, seqs[1:]):
        if b > a + 1:
            rep.seq_gaps += b - a - 1
    rep.seq_gaps = max(0, rep.seq_gaps - no_seq_quarantined)
    rep.kept = len(kept)
    return kept, quarantine, rep


# ---------------------------------------------------------------------------
# Content digests
# ---------------------------------------------------------------------------


def payload_digest(obj) -> str:
    """Digest of an arbitrary JSON-serializable payload (the checkpoint
    recovery image): canonical-JSON CRC32 hex. Cheap enough to compute
    on every compaction, strong enough to catch a partially-applied or
    bit-rotted image that still parses."""
    return format(
        zlib.crc32(
            json.dumps(obj, sort_keys=True, separators=(",", ":")).encode(
                "utf-8"
            )
        )
        & 0xFFFFFFFF,
        "08x",
    )


def array_digest(arrays: Iterable) -> str:
    """Digest over an ordered collection of arrays (shape + dtype +
    bytes): the bit-exact fingerprint the anti-entropy scrubber and the
    recovery cross-check compare between the device-resident tables and
    a fresh host lowering."""
    import numpy as np

    crc = 0
    for a in arrays:
        if a is None:
            crc = zlib.crc32(b"none", crc)
            continue
        host = np.ascontiguousarray(np.asarray(a))
        crc = zlib.crc32(str((host.shape, host.dtype.str)).encode(), crc)
        crc = zlib.crc32(host.tobytes(), crc)
    return format(crc & 0xFFFFFFFF, "08x")
