"""Scalar sequential reference scheduler — the golden model.

A pure-numpy, one-pod-at-a-time re-implementation of the reference's
scheduling semantics (Filter → Score → Reserve, upstream ``scheduleOne`` with
LoadAware Filter ``load_aware.go:290-313`` and Score ``load_aware.go:387-406``).
It is intentionally architecture-faithful to the reference — a per-pod loop
over all nodes — which makes it both the correctness oracle for the batched
TPU solver (SURVEY §4 "golden tests … vs a scalar re-implementation") and the
measured stand-in baseline for bench.py (BASELINE.md: no published numbers,
baselines must be measured).
"""

from __future__ import annotations

import numpy as np

EPS = 1e-3


def _usage_percent(used: np.ndarray, allocatable: np.ndarray) -> np.ndarray:
    """Rounded integer percent, the reference's threshold-check unit
    (``filterNodeUsage``: int64(math.Round(used/total*100)))."""
    pct = np.zeros_like(used)
    np.divide(used * 100.0, allocatable, out=pct, where=allocatable > 0)
    return np.floor(pct + 0.5)


def sequential_assign(
    pod_req: np.ndarray,          # [P, D]
    pod_estimate: np.ndarray,     # [P, D]
    pod_priority: np.ndarray,     # [P]
    pod_is_prod: np.ndarray,      # [P] bool
    allocatable: np.ndarray,      # [N, D]
    requested0: np.ndarray,       # [N, D]
    estimated_used0: np.ndarray,  # [N, D]
    prod_used0: np.ndarray,       # [N, D]
    metric_fresh: np.ndarray,     # [N] bool
    schedulable: np.ndarray,      # [N] bool
    usage_thresholds: np.ndarray,  # [D] percent, 0 disables
    prod_thresholds: np.ndarray,   # [D]
    score_weights: np.ndarray,     # [D]
) -> np.ndarray:
    """Returns [P] node index per pod (-1 unschedulable), committing capacity
    sequentially in (-priority, arrival) order."""
    p, _ = pod_req.shape
    requested = requested0.copy()
    est_used = estimated_used0.copy()
    prod_used = prod_used0.copy()
    assignment = np.full(p, -1, np.int64)
    order = np.lexsort((np.arange(p), -pod_priority))
    wsum = score_weights.sum() + 1e-9
    thr_on = usage_thresholds > 0
    prod_thr_on = prod_thresholds > 0

    for i in order:
        req, est = pod_req[i], pod_estimate[i]
        fit = np.all(requested + req <= allocatable + EPS, axis=1)
        feas = fit & schedulable
        if thr_on.any():
            pct = _usage_percent(est_used + est, allocatable)
            over = thr_on[None, :] & (pct > usage_thresholds)
            feas &= ~(metric_fresh & over.any(axis=1))
        if pod_is_prod[i] and prod_thr_on.any():
            pct = _usage_percent(prod_used + est, allocatable)
            over = prod_thr_on[None, :] & (pct > prod_thresholds)
            feas &= ~(metric_fresh & over.any(axis=1))
        if not feas.any():
            continue
        after = est_used + est
        free = np.maximum(allocatable - after, 0.0)
        # integer-floor score semantics (reference leastUsedScore /
        # loadAwareSchedulingScorer int64 divisions); expired metric → 0
        per_dim = np.floor(
            np.where(allocatable > 0, free * 100.0 / (allocatable + 1e-9), 0.0)
        )
        score = np.floor((per_dim * score_weights).sum(axis=1) / wsum)
        score = np.where(metric_fresh, score, 0.0)
        score[~feas] = -np.inf
        best = int(np.argmax(score))
        assignment[i] = best
        requested[best] += req
        est_used[best] += est
        if pod_is_prod[i]:
            prod_used[best] += est
    return assignment


def validate_assignment(
    assignment: np.ndarray,
    pod_req: np.ndarray,
    allocatable: np.ndarray,
    requested0: np.ndarray,
    schedulable: np.ndarray,
) -> None:
    """Assert no node is over-committed and no pod landed on an unschedulable
    node — the invariant any solver output must satisfy regardless of order."""
    n, d = allocatable.shape
    consumed = requested0.copy()
    placed = assignment >= 0
    np.add.at(consumed, assignment[placed], pod_req[placed])
    over = consumed > allocatable + 1e-2
    assert not over.any(), f"overcommitted nodes: {np.argwhere(over)[:10]}"
    assert schedulable[assignment[placed]].all(), "pod on unschedulable node"
