"""Synthetic cluster/workload generator (the rebuild's stand-in for the
reference's kind-based e2e rig, ``test/kind-conf.yaml`` — but at the 10k-node
/ 100k-pod scale from BASELINE.json that kind cannot reach)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from ..api import extension as ext
from ..api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)

#: (cpu milli, memory MiB) node shapes, weighted toward 64-core boxes
NODE_SHAPES = (
    (32_000, 128 * 1024),
    (64_000, 256 * 1024),
    (96_000, 384 * 1024),
)


@dataclasses.dataclass
class GenConfig:
    n_nodes: int = 1000
    n_pods: int = 10_000
    seed: int = 0
    prod_fraction: float = 0.3       # rest are batch (BE) pods
    base_util: float = 0.35          # initial reported node utilization
    util_spread: float = 0.2
    gang_fraction: float = 0.0       # fraction of pods grouped into gangs
    gang_size: int = 4


def gen_nodes(cfg: GenConfig) -> Tuple[List[Node], List[NodeMetric]]:
    rng = np.random.default_rng(cfg.seed)
    shapes = rng.integers(0, len(NODE_SHAPES), cfg.n_nodes)
    nodes, metrics = [], []
    for i in range(cfg.n_nodes):
        cpu, mem = NODE_SHAPES[int(shapes[i])]
        name = f"node-{i:05d}"
        nodes.append(
            Node(
                meta=ObjectMeta(name=name, namespace=""),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
                ),
            )
        )
        util = float(
            np.clip(
                cfg.base_util + rng.normal(0, cfg.util_spread / 2), 0.02, 0.9
            )
        )
        usage = {ext.RES_CPU: cpu * util, ext.RES_MEMORY: mem * util * 0.8}
        metrics.append(
            NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                node_usage=ResourceMetric(usage=dict(usage)),
                prod_usage=ResourceMetric(
                    usage={k: v * 0.7 for k, v in usage.items()}
                ),
                aggregated={
                    "p95": ResourceMetric(
                        usage={k: v * 1.1 for k, v in usage.items()}
                    )
                },
            )
        )
    return nodes, metrics


def gen_pods(cfg: GenConfig) -> List[Pod]:
    rng = np.random.default_rng(cfg.seed + 1)
    pods: List[Pod] = []
    gang_count = 0
    for i in range(cfg.n_pods):
        is_prod = rng.random() < cfg.prod_fraction
        cpu = int(rng.choice([500, 1000, 2000, 4000], p=[0.4, 0.3, 0.2, 0.1]))
        mem = cpu * int(rng.choice([2, 4, 8])) // 1  # MiB per milli-core ratio
        prio = int(rng.integers(9000, 9999) if is_prod else rng.integers(5000, 5999))
        labels = {}
        if cfg.gang_fraction > 0 and rng.random() < cfg.gang_fraction:
            labels[ext.LABEL_GANG_NAME] = f"gang-{gang_count // cfg.gang_size}"
            gang_count += 1
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"pod-{i:06d}", namespace="sim", labels=labels),
                spec=PodSpec(
                    requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
                    priority=prio,
                ),
            )
        )
    return pods


# --------------------------------------------------------------------------
# Region-scale fleet generation (first-class multichip PR): 100k–1M nodes.
#
# At this scale the per-node Python object path above is the bottleneck
# (1M ``Node`` dataclasses + dict allocatables take minutes and GBs before
# the solver sees a single row), so the fleet generator is COLUMNAR: pure
# numpy arrays laid out exactly like the solver's device tables, organized
# as region-sized contiguous cohorts with per-region shape mixes and
# utilization skews — real fleets are heterogeneous BETWEEN regions, not
# just within one. ``gen_region_nodes`` materializes any single cohort as
# objects (bit-consistent with the columns) for snapshot-based paths.

#: heterogeneous fleet shape table: (cpu milli, memory MiB, mix weight) —
#: small edge boxes through fat-memory accelerator hosts
FLEET_SHAPES = (
    (16_000, 64 * 1024, 0.15),
    (32_000, 128 * 1024, 0.25),
    (64_000, 256 * 1024, 0.30),
    (96_000, 384 * 1024, 0.15),
    (128_000, 512 * 1024, 0.10),
    (96_000, 768 * 1024, 0.05),
)


@dataclasses.dataclass
class FleetConfig:
    n_nodes: int = 100_000
    n_regions: int = 8               # region-sized contiguous cohorts
    seed: int = 0
    base_util: float = 0.35
    util_spread: float = 0.2
    region_util_skew: float = 0.08   # ± tilt of base_util across regions
    unschedulable_fraction: float = 0.01  # cordoned / draining nodes


def gen_fleet_arrays(cfg: FleetConfig) -> dict:
    """Vectorized fleet columns — no per-node Python objects.

    Returns ``allocatable``/``estimated_used``/``prod_used`` ([N, 2]
    float32, cpu-milli + memory-MiB), ``metric_fresh``/``schedulable``
    ([N] bool), ``region`` ([N] int16), ``shape_id`` ([N] int8) and
    ``region_bounds`` ([R+1] int64 cohort slice boundaries). 1M nodes
    generate in well under a second."""
    rng = np.random.default_rng(cfg.seed)
    n, r_count = cfg.n_nodes, max(1, cfg.n_regions)
    bounds = np.linspace(0, n, r_count + 1).astype(np.int64)
    mix = np.asarray([s[2] for s in FLEET_SHAPES], np.float64)
    mix /= mix.sum()
    shape_id = np.empty(n, np.int8)
    region = np.empty(n, np.int16)
    util = np.empty(n, np.float32)
    for r in range(r_count):
        lo, hi = int(bounds[r]), int(bounds[r + 1])
        m = hi - lo
        region[lo:hi] = r
        # per-region shape mix: a dirichlet draw concentrated on the
        # global mix, so every region is plausible but none identical
        tilt = rng.dirichlet(mix * 24.0)
        shape_id[lo:hi] = rng.choice(len(FLEET_SHAPES), size=m, p=tilt)
        off = 0.0 if r_count == 1 else (2.0 * r / (r_count - 1) - 1.0)
        base = cfg.base_util + off * cfg.region_util_skew
        util[lo:hi] = np.clip(
            base + rng.normal(0, cfg.util_spread / 2, m), 0.02, 0.9
        )
    shapes = np.asarray(
        [(c, m) for c, m, _w in FLEET_SHAPES], np.float32
    )
    alloc = shapes[shape_id]
    usage = alloc * util[:, None]
    usage[:, 1] *= 0.8                      # memory runs cooler
    est = usage * 1.1                       # p95 aggregate, like gen_nodes
    return {
        "allocatable": alloc,
        "estimated_used": est.astype(np.float32),
        "prod_used": (usage * 0.7).astype(np.float32),
        "metric_fresh": np.ones(n, bool),
        "schedulable": rng.random(n) >= cfg.unschedulable_fraction,
        "region": region,
        "shape_id": shape_id,
        "region_bounds": bounds,
    }


def fleet_node_state(cfg: FleetConfig):
    """``ops.solver.NodeState`` over the generated fleet columns — the
    direct on-device table for solver-stream benchmarks at scales where
    a host ``ClusterSnapshot`` (one dict per node) is the wrong tool."""
    from ..ops.solver import NodeState

    f = gen_fleet_arrays(cfg)
    return NodeState.create(
        allocatable=f["allocatable"],
        estimated_used=f["estimated_used"],
        prod_used=f["prod_used"],
        metric_fresh=f["metric_fresh"],
        schedulable=f["schedulable"],
    )


def gen_region_nodes(
    cfg: FleetConfig, region: int, arrays: Optional[dict] = None
) -> Tuple[List[Node], List[NodeMetric]]:
    """Materialize ONE region cohort as Node/NodeMetric objects,
    bit-consistent with :func:`gen_fleet_arrays` (same seed, same
    columns) — for snapshot-based paths that want a region-sized slice
    of the fleet without paying the full object cost."""
    f = arrays if arrays is not None else gen_fleet_arrays(cfg)
    lo = int(f["region_bounds"][region])
    hi = int(f["region_bounds"][region + 1])
    nodes, metrics = [], []
    for i in range(lo, hi):
        cpu, mem = (float(v) for v in f["allocatable"][i])
        name = f"r{region:02d}-node-{i:07d}"
        nodes.append(
            Node(
                meta=ObjectMeta(name=name, namespace=""),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
                ),
            )
        )
        usage = {
            ext.RES_CPU: float(f["estimated_used"][i, 0] / 1.1),
            ext.RES_MEMORY: float(f["estimated_used"][i, 1] / 1.1),
        }
        metrics.append(
            NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                node_usage=ResourceMetric(usage=dict(usage)),
                prod_usage=ResourceMetric(
                    usage={k: v * 0.7 for k, v in usage.items()}
                ),
                aggregated={
                    "p95": ResourceMetric(
                        usage={k: v * 1.1 for k, v in usage.items()}
                    )
                },
            )
        )
    return nodes, metrics


def gen_fleet_pod_arrays(
    cfg: FleetConfig, n_pods: int, seed_offset: int = 1
) -> dict:
    """Columnar pod population to match the fleet: ``requests``/
    ``estimate`` [P, 2] float32, ``priority`` [P] int32, ``is_prod``
    [P] bool. Same request mix as :func:`gen_pods`, vectorized."""
    rng = np.random.default_rng(cfg.seed + seed_offset)
    cpu = rng.choice(
        [500.0, 1000.0, 2000.0, 4000.0], size=n_pods,
        p=[0.4, 0.3, 0.2, 0.1],
    ).astype(np.float32)
    mem = cpu * rng.choice([2.0, 4.0, 8.0], size=n_pods).astype(np.float32)
    is_prod = rng.random(n_pods) < 0.3
    priority = np.where(
        is_prod,
        rng.integers(9000, 9999, n_pods),
        rng.integers(5000, 5999, n_pods),
    ).astype(np.int32)
    req = np.stack([cpu, mem], axis=1)
    return {
        "requests": req,
        "estimate": req,
        "priority": priority,
        "is_prod": is_prod,
    }
