"""Synthetic cluster/workload generator (the rebuild's stand-in for the
reference's kind-based e2e rig, ``test/kind-conf.yaml`` — but at the 10k-node
/ 100k-pod scale from BASELINE.json that kind cannot reach)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from ..api import extension as ext
from ..api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)

#: (cpu milli, memory MiB) node shapes, weighted toward 64-core boxes
NODE_SHAPES = (
    (32_000, 128 * 1024),
    (64_000, 256 * 1024),
    (96_000, 384 * 1024),
)


@dataclasses.dataclass
class GenConfig:
    n_nodes: int = 1000
    n_pods: int = 10_000
    seed: int = 0
    prod_fraction: float = 0.3       # rest are batch (BE) pods
    base_util: float = 0.35          # initial reported node utilization
    util_spread: float = 0.2
    gang_fraction: float = 0.0       # fraction of pods grouped into gangs
    gang_size: int = 4


def gen_nodes(cfg: GenConfig) -> Tuple[List[Node], List[NodeMetric]]:
    rng = np.random.default_rng(cfg.seed)
    shapes = rng.integers(0, len(NODE_SHAPES), cfg.n_nodes)
    nodes, metrics = [], []
    for i in range(cfg.n_nodes):
        cpu, mem = NODE_SHAPES[int(shapes[i])]
        name = f"node-{i:05d}"
        nodes.append(
            Node(
                meta=ObjectMeta(name=name, namespace=""),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
                ),
            )
        )
        util = float(
            np.clip(
                cfg.base_util + rng.normal(0, cfg.util_spread / 2), 0.02, 0.9
            )
        )
        usage = {ext.RES_CPU: cpu * util, ext.RES_MEMORY: mem * util * 0.8}
        metrics.append(
            NodeMetric(
                meta=ObjectMeta(name=name, namespace=""),
                node_usage=ResourceMetric(usage=dict(usage)),
                prod_usage=ResourceMetric(
                    usage={k: v * 0.7 for k, v in usage.items()}
                ),
                aggregated={
                    "p95": ResourceMetric(
                        usage={k: v * 1.1 for k, v in usage.items()}
                    )
                },
            )
        )
    return nodes, metrics


def gen_pods(cfg: GenConfig) -> List[Pod]:
    rng = np.random.default_rng(cfg.seed + 1)
    pods: List[Pod] = []
    gang_count = 0
    for i in range(cfg.n_pods):
        is_prod = rng.random() < cfg.prod_fraction
        cpu = int(rng.choice([500, 1000, 2000, 4000], p=[0.4, 0.3, 0.2, 0.1]))
        mem = cpu * int(rng.choice([2, 4, 8])) // 1  # MiB per milli-core ratio
        prio = int(rng.integers(9000, 9999) if is_prod else rng.integers(5000, 5999))
        labels = {}
        if cfg.gang_fraction > 0 and rng.random() < cfg.gang_fraction:
            labels[ext.LABEL_GANG_NAME] = f"gang-{gang_count // cfg.gang_size}"
            gang_count += 1
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"pod-{i:06d}", namespace="sim", labels=labels),
                spec=PodSpec(
                    requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
                    priority=prio,
                ),
            )
        )
    return pods
