"""Long-lived cross-component loop: the §3.3 feedback cycle in one process.

Composes, per simulated tick (15 s):

  koordlet  — per-node usage samples land in a MetricCache; at each
              report interval the window aggregate becomes a NodeMetric
              status report (states_nodemetric.go:212 analog)
  manager   — NodeMetricController accepts the report; the snapshot
              ingests it; NodeResourceController recomputes
              kubernetes.io/batch-* capacity from the prod peak
  scheduler — newly arrived Spark pods (mutated BE by the colocation
              profile webhook) are batch-scheduled against batch capacity
  koordlet  — runtimehooks derive the cgroup plan for each new bind;
              qosmanager computes the BE suppression allowance
  reservations — a rolling prod Reservation holds warm capacity; owner
              pods consume it through the fast path; dead owners are
              reconciled and TTL'd reservations expire via the
              controller sweep (plugins/reservation/controller analog)
  descheduler — LowNodeLoad classifies nodes each report interval and
              soft-evicts BE pods from debounced-hot nodes

Pods complete after a few ticks and release capacity; prod load follows a
sinusoid so batch capacity breathes. Invariants checked every tick:

  * snapshot accounting never drifts: requested == Σ live assumes
  * batch-cpu requested never exceeds batch allocatable on any node
  * suppression allowance shrinks when prod crosses the threshold
  * reservation ledger: allocated == Σ live owner requests

Entry points: ``python -m koordinator_tpu.cmd.koord_sim`` (binary),
``examples/longrun_loop.py`` (narrated demo),
``tests/test_longrun_loop.py`` (asserted invariants).
"""

from __future__ import annotations

import math


def run_loop(
    minutes: float = 10.0,
    tick_s: float = 15.0,
    n_nodes: int = 6,
    seed: int = 0,
    verbose: bool = False,
    chaos_ticks: tuple = (),
    trace: bool = True,
):
    """Drive the loop for ``minutes`` of simulated time; returns stats.

    All cluster state flows through a :class:`ClusterStateHub`'s informers
    (nodes, metrics, pods, reservations) — the scheduler never sees a
    direct setter. ``chaos_ticks``: ticks at which every open watch is
    severed (apiserver restart); the informers must re-list and the
    tick's invariants still hold (``stats["relists"]`` counts the
    recoveries)."""
    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.extension import QoSClass
    from koordinator_tpu.api.types import (
        ClusterColocationProfile,
        Node,
        NodeMetric,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceMetric,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.koordlet import qosmanager, runtimehooks
    from koordinator_tpu.koordlet.metriccache import MetricCache
    from koordinator_tpu.manager.nodemetric import NodeMetricController
    from koordinator_tpu.manager.noderesource import (
        ColocationStrategy,
        NodeResourceController,
    )
    from koordinator_tpu.manager.profile import ProfileMutator
    from koordinator_tpu.manager.validating import validate_pod
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    ALLOC_CPU, ALLOC_MEM = 64_000.0, 256 * 1024.0
    REPORT_EVERY = 4          # ticks between NodeMetric reports (60 s)
    BE_LIFETIME = 8           # ticks a BE pod runs before completing
    rng = np.random.default_rng(seed)

    snap = ClusterSnapshot()
    caches = {f"n{i}": MetricCache(capacity_per_series=512) for i in range(n_nodes)}
    nm_ctrl = NodeMetricController()
    nr_ctrl = NodeResourceController(snap, ColocationStrategy(reserve_ratio=0.1))
    mutator = ProfileMutator()
    mutator.upsert(
        ClusterColocationProfile(
            meta=ObjectMeta(name="colocation-spark"),
            selector={"koordinator.sh/enable-colocation": "true"},
            qos_class=QoSClass.BE,
            priority=5500,
            scheduler_name="koord-scheduler",
            resource_translation={
                ext.RES_CPU: ext.RES_BATCH_CPU,
                ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
            },
        )
    )
    # defer_preemption: quota-preemption victims are NOMINATED and routed
    # through the descheduler's PodMigrationJob machinery below — the
    # preemptor lands the cycle after the arbitrated eviction
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=128, defer_preemption=True
    )
    sched.extender.monitor.stop_background()
    # cycle tracing on by default: the final stats carry the per-stage
    # wall-time breakdown (snapshot/lower/solve/commit/postfilter) for
    # BENCH artifacts. Tracing adds the solve-stage block_until_ready
    # fence, so pass trace=False when the loop's own wall time is the
    # number under study; the span ring is bounded (65536), so very long
    # runs undercount stage_ms for the earliest cycles.
    sched.extender.tracer.enabled = trace
    from koordinator_tpu.api.types import Reservation, ReservationOwner
    from koordinator_tpu.descheduler.evictor import SoftEvictor
    from koordinator_tpu.descheduler.low_node_load import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
        ReservationPhase,
    )

    # the sim clock: wall-anchored, advancing by simulated time — every
    # reservation timestamp and sweep comparison uses the same domain
    import time as _time

    _wall0 = _time.time()
    sim_tick = [0]

    def sim_clock() -> float:
        return _wall0 + sim_tick[0] * tick_s

    rm = ReservationManager(
        sched, gc_duration_s=6 * tick_s, clock=sim_clock
    )

    # ---- the informer hub: every piece of cluster state below flows
    # through LIST+WATCH into the scheduler's components (pkg/client
    # analog made load-bearing — VERDICT r2 weak #3) ----
    from koordinator_tpu.runtime.statehub import ClusterStateHub

    hub = ClusterStateHub()
    hub.wire_scheduler(sched, reservations=rm)
    hub.start()
    for i in range(n_nodes):
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: ALLOC_CPU, ext.RES_MEMORY: ALLOC_MEM}
                ),
            ),
        )
    assert hub.wait_synced()

    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            high_thresholds={ext.RES_CPU: 70.0},
            low_thresholds={ext.RES_CPU: 50.0},
            anomaly_condition_count=1,
        ),
    )
    soft_evictor = SoftEvictor()

    # ---- quota preemption → migration integration (VERDICT r2 #7):
    # a saturated "frontend" quota, mid-priority web pods holding it, and
    # periodic high-priority api pods whose arrival must evict a victim
    # via PodMigrationJob and land the NEXT cycle ----
    from koordinator_tpu.api.types import ElasticQuota, MigrationMode
    from koordinator_tpu.descheduler.migration import MigrationController

    hub.publish(
        hub.quotas,
        ElasticQuota(
            meta=ObjectMeta(name="frontend"),
            min={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536},
            max={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536},
        ),
    )
    assert hub.wait_synced()

    def _preemption_evict(victim: Pod, reason: str) -> bool:
        # the actual eviction is the pod DELETE on the API server; every
        # component releases through the informer fan-out. A victim that
        # vanished since nomination (completed meanwhile) is a FAILED
        # eviction, not a silent success.
        return hub.delete(hub.pods, victim) is not None

    mig_ctrl = MigrationController(rm, _preemption_evict)
    web_live: list = []       # mid-priority quota holders
    preempt_retry: list = []  # high-prio preemptors awaiting their cycle

    def _quota_pod(name: str, prio: int, app: str) -> Pod:
        return Pod(
            meta=ObjectMeta(
                name=name,
                namespace="frontend",
                labels={"app": app, ext.LABEL_QUOTA_NAME: "frontend"},
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
                priority=prio,
            ),
        )

    bc = snap.config.resources.index(ext.RES_BATCH_CPU)
    rows = [snap.node_id(f"n{i}") for i in range(n_nodes)]

    def prod_util(node_i: int, t: float) -> float:
        """Sinusoidal prod load, phase-shifted per node, 20%..75%."""
        phase = 2 * math.pi * (t / (minutes * 60.0) + node_i / n_nodes)
        return 0.475 + 0.275 * math.sin(phase)

    live: list = []      # (pod, node, done_tick)
    stats = {
        "ticks": 0,
        "arrived": 0,
        "bound": 0,
        "completed": 0,
        "unschedulable": 0,
        "reports": 0,
        "suppressions": 0,
        "min_batch_cap": float("inf"),
        "max_batch_cap": 0.0,
    }
    stats.update(
        reservations_created=0,
        reservations_consumed=0,
        reservations_expired=0,
        reservations_drifted=0,
        reservations_gced=0,
        soft_evicted=0,
        preemption_nominations=0,
        preemption_jobs=0,
        preemptors_landed=0,
    )
    n_ticks = int(minutes * 60.0 / tick_s)
    pod_seq = 0
    resv_seq = 0
    svc_seq = 0
    svc_live: list = []   # (pod, done_tick)
    stats["watch_disconnects"] = 0
    for tick in range(n_ticks):
        sim_tick[0] = tick
        now = 1000.0 + tick * tick_s
        stats["ticks"] += 1

        if tick in chaos_ticks:
            # apiserver restart: every open watch dies mid-loop; the
            # informers re-list and the world re-converges below
            hub.disconnect()
            stats["watch_disconnects"] += 1

        # ---- koordlet collection: usage samples into each node's cache ----
        utils = {}
        for i in range(n_nodes):
            name = f"n{i}"
            u = prod_util(i, tick * tick_s) + float(rng.normal(0, 0.01))
            u = min(max(u, 0.05), 0.95)
            utils[name] = u
            caches[name].append("node_cpu", name, now, ALLOC_CPU * u)
            caches[name].append("node_mem", name, now, ALLOC_MEM * u * 0.8)

        # ---- report interval: window aggregate → NodeMetric status ----
        if tick % REPORT_EVERY == 0:
            for i in range(n_nodes):
                name = f"n{i}"
                agg_c = caches[name].aggregate("node_cpu", name, now - 300, now + 1)
                agg_m = caches[name].aggregate("node_mem", name, now - 300, now + 1)
                report = NodeMetric(
                    meta=ObjectMeta(name=name),
                    node_usage=ResourceMetric(
                        usage={
                            ext.RES_CPU: agg_c.percentiles.get("p95", agg_c.avg),
                            ext.RES_MEMORY: agg_m.percentiles.get("p95", agg_m.avg),
                        }
                    ),
                    prod_usage=ResourceMetric(
                        usage={
                            ext.RES_CPU: agg_c.avg,
                            ext.RES_MEMORY: agg_m.avg,
                        }
                    ),
                    update_time=now,
                )
                nm_ctrl.observe(report)       # the CRD status write
                hub.publish(hub.node_metrics, report)
                stats["reports"] += 1
            assert hub.wait_synced()          # metrics visible to consumers
            # ---- manager: batch capacity from the fresh prod peak ----
            published = nr_ctrl.reconcile()
            assert set(published) == {f"n{i}" for i in range(n_nodes)}
            # quota controller status sync (controller.go syncHandler):
            # runtime/request stamped onto the quota objects each sweep
            if sched.quotas.quota_count:
                assert "frontend" in sched.quotas.sync_status()

        caps = snap.nodes.allocatable[rows, bc]
        stats["min_batch_cap"] = min(stats["min_batch_cap"], float(caps.min()))
        stats["max_batch_cap"] = max(stats["max_batch_cap"], float(caps.max()))

        over_before = np.maximum(
            snap.nodes.requested[rows, bc]
            - snap.nodes.allocatable[rows, bc],
            0.0,
        )

        # ---- workload arrival: Spark pods through the admission chain ----
        arriving = []
        for _ in range(int(rng.integers(1, 4))):
            pod_seq += 1
            pod = Pod(
                meta=ObjectMeta(
                    name=f"spark-{pod_seq:05d}",
                    namespace="spark",
                    labels={"koordinator.sh/enable-colocation": "true"},
                ),
                spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}),
            )
            pod = mutator.mutate(pod)
            assert validate_pod(pod) == []
            arriving.append(pod)
        stats["arrived"] += len(arriving)

        # ---- reservations: rolling warm capacity for prod services ----
        if tick % 12 == 0:
            resv_seq += 1
            hub.publish(
                hub.reservations,
                Reservation(
                    meta=ObjectMeta(name=f"svc-hold-{resv_seq}"),
                    requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
                    owners=[ReservationOwner(label_selector={"app": "svc"})],
                    allocate_once=False,
                    ttl_s=10 * tick_s,
                ),
            )
            assert hub.wait_synced()   # the Reservation CR reached the manager
            if rm.schedule_pending():
                stats["reservations_created"] += 1
        if tick % 12 == 4 and any(
            r.phase == ReservationPhase.AVAILABLE for r in rm.list()
        ):
            # an owner pod arrives and consumes from the reservation;
            # it dies young (owner drift) half the time
            svc_seq += 1
            svc = Pod(
                meta=ObjectMeta(
                    name=f"svc-{svc_seq:04d}",
                    labels={"app": "svc"},
                ),
                spec=PodSpec(
                    requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                    priority=9500,
                ),
            )
            svc_out = sched.schedule([svc])
            if svc_out.bound:
                stats["reservations_consumed"] += 1
                lifetime = 3 if svc_seq % 2 else 14
                bound_svc = svc_out.bound[0][0]
                bound_svc.spec.node_name = svc_out.bound[0][1]
                hub.publish(hub.pods, bound_svc)   # the bind API write
                svc_live.append((bound_svc, tick + lifetime))

        out = sched.schedule(arriving)
        stats["bound"] += len(out.bound)
        stats["unschedulable"] += len(out.unschedulable)
        for pod, node in out.bound:
            pod.spec.node_name = node  # the bind writes spec.nodeName
            hub.publish(hub.pods, pod)  # observed back via the informer
            plan = runtimehooks.pod_plan(pod)
            assert "bvt" in str(plan)
            live.append((pod, node, tick + BE_LIFETIME))

        # ---- quota preemption leg: web pods hold the saturated quota;
        # a high-priority api pod's arrival nominates a victim, the
        # PodMigrationJob controller evicts it (EvictDirectly → pod
        # DELETE → informer fan-out), and the api pod lands NEXT tick ----
        quota_arrivals: list = []
        if tick in (1, 21):
            quota_arrivals.extend(
                _quota_pod(f"web-{tick}-{j}", 7000, "web") for j in range(2)
            )
        if tick in (6, 26):
            quota_arrivals.append(_quota_pod(f"api-{tick}", 9500, "api"))
        if quota_arrivals or preempt_retry:
            retry_uids = {p.meta.uid for p in preempt_retry}
            qout = sched.schedule(quota_arrivals + preempt_retry)
            for pod, node in qout.bound:
                pod.spec.node_name = node
                hub.publish(hub.pods, pod)
                if (
                    pod.meta.uid in retry_uids
                    and pod.meta.labels.get("app") == "api"
                ):
                    # a high-priority preemptor landed the cycle AFTER
                    # its victim's migration-job eviction
                    stats["preemptors_landed"] += 1
                if pod.meta.labels.get("app") == "web":
                    web_live.append(pod)
            stats["preemption_nominations"] += len(qout.preempted)
            jobs_before = len(mig_ctrl.jobs)
            for victim in qout.preempted:
                # every nominated victim must be a live quota holder —
                # preemption may never nominate arbitrary pods
                assert any(
                    p.meta.uid == victim.meta.uid for p in web_live
                ), victim.meta.name
                mig_ctrl.submit(victim, MigrationMode.EVICT_DIRECTLY)
            stats["preemption_jobs"] += len(mig_ctrl.jobs) - jobs_before
            # only high-priority api pods re-queue: an unschedulable web
            # pod cannot preempt higher-priority holders and would churn
            # the solver every remaining tick for nothing
            preempt_retry = [
                p
                for p in qout.unschedulable
                if p.meta.labels.get("app") == "api"
            ]
        # the migration controller reconciles EVERY tick like a real
        # controller — jobs the arbitrator left pending are retried even
        # on ticks with no new nominations
        if mig_ctrl.jobs:
            mig_ctrl.reconcile(now=sim_clock())
            assert hub.wait_synced()   # evictions landed everywhere
            alive_keys, _rv = hub.pods.list()
            web_live = [
                p
                for p in web_live
                if f"{p.meta.namespace}/{p.meta.name}" in alive_keys
            ]

        # ---- qosmanager: suppression on the hottest node ----
        hot = max(utils, key=lambda k: utils[k])
        be_used = 4000.0 * sum(1 for _, n, _ in live if n == hot)
        dec = qosmanager.cpu_suppress(
            node_allocatable_milli=ALLOC_CPU,
            node_used_milli=utils[hot] * ALLOC_CPU + be_used,
            be_used_milli=be_used,
            threshold_percent=65.0,
        )
        if be_used and dec.be_allowance_milli < be_used:
            stats["suppressions"] += 1

        # ---- completion: pod DELETE events release capacity through the
        # informer (snapshot charge, quota, numa/devices, bound-node map,
        # operating-pod reservations — the full RemovePod fan-out) ----
        still = []
        for pod, node, done in live:
            if done <= tick:
                hub.delete(hub.pods, pod)
                stats["completed"] += 1
            else:
                still.append((pod, node, done))
        live = still
        # svc owners complete/die the same way; the controller sweep then
        # reconciles the drift and expires TTL'd reservations
        svc_still = []
        for pod, done in svc_live:
            if done <= tick:
                hub.delete(hub.pods, pod)
            else:
                svc_still.append((pod, done))
        svc_live = svc_still
        assert hub.wait_synced()    # deletes applied before the sweep
        sweep = rm.sync()
        stats["reservations_expired"] += len(sweep["expired"])
        stats["reservations_drifted"] += len(sweep["drifted"])
        stats["reservations_gced"] += len(sweep["deleted"])

        # ---- descheduler: LowNodeLoad soft-evicts from debounced-hot ----
        if tick % REPORT_EVERY == 0:
            cls = lnl.classify()
            evicted_uids = set()
            for victim in lnl.select_victims([p for p, _, _ in live], cls):
                if soft_evictor.evict(victim, "node overutilized"):
                    stats["soft_evicted"] += 1
                    evicted_uids.add(victim.meta.uid)
            if evicted_uids:
                # the workload controller reacts to the soft-eviction mark
                # by replacing the pod: early-complete it next tick so the
                # descheduling leg actually moves cluster state
                live = [
                    (p, n, min(d, tick + 1) if p.meta.uid in evicted_uids else d)
                    for p, n, d in live
                ]

        # reservation ledger invariant: allocated == Σ live owner requests
        for r in rm.list():
            if r.phase != ReservationPhase.AVAILABLE:
                continue
            ledger = rm.owner_ledger(r.meta.name)
            want_cpu = sum(
                ledger.get(uid, {}).get(ext.RES_CPU, 0.0)
                for uid in r.current_owners
            )
            assert abs(r.allocated.get(ext.RES_CPU, 0.0) - want_cpu) < 1e-3

        # ---- invariants ----
        # 1. accounting: requested equals the sum of live assumes
        want = np.zeros_like(snap.nodes.requested)
        for uid, ap in snap._assumed.items():
            want[ap.node_idx] += ap.request
        np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
        # 2. batch capacity formula holds on every node (within one
        #    report interval of staleness)
        # 3. scheduling never INCREASES batch overcommit: a fast prod
        #    rise may legally strand already-bound BE pods above the
        #    shrunken capacity (that is what suppression/descheduling
        #    resolve), but the scheduler must never place new pods into
        #    the gap
        over = snap.nodes.requested[rows, bc] - snap.nodes.allocatable[rows, bc]
        assert (over <= over_before + 1e-3).all(), (over, over_before)

        if verbose and tick % REPORT_EVERY == 0:
            print(
                f"t={now - 1000:6.0f}s live={len(live):3d} "
                f"batch_cap=[{caps.min():7.0f}..{caps.max():7.0f}] "
                f"bound={stats['bound']} unsched={stats['unschedulable']} "
                f"suppr={stats['suppressions']}"
            )

    stats["live_at_end"] = len(live)
    stats["relists"] = hub.relists()
    hub.stop()
    if stats["min_batch_cap"] == float("inf"):
        stats["min_batch_cap"] = 0.0  # zero-tick run: keep JSON standard
    # per-stage wall-time breakdown over every scheduling cycle the loop
    # ran (depth ≤ 1: the cycle span and its four tiling stages; nested
    # retry stages excluded so totals stay exclusive), plus the count of
    # rejection records for attribution completeness checks
    tracer = sched.extender.tracer
    stats["stage_ms"] = {
        name: round(total * 1000.0, 3)
        for name, total in sorted(tracer.stage_totals(max_depth=1).items())
    }
    stats["rejection_records"] = len(sched.extender.rejections.records())
    return stats


# ---------------------------------------------------------------------------
# Chaos soak (robustness PR tentpole cap)
# ---------------------------------------------------------------------------


def assert_resident_state_converged(sched) -> None:
    """The device-resident NodeState must be BIT-EXACT against a
    from-scratch host lowering — after rollbacks, resyncs, fallback
    cycles and HA takeovers, a missed dirty mark anywhere shows up here
    as a stale row (same contract as ``tests/test_resident_state.py``;
    the implementation lives with the recovery path that depends on it)."""
    from koordinator_tpu.runtime.recovery import assert_resident_bitexact

    assert_resident_bitexact(sched)


def _sweep_decisions(records, context: str):
    """Decision-observatory soak sweep (decision-observatory PR) over
    one store's collected records, sorted by ``seq``:

    * **gap-free per-controller tick sequences** — a takeover adopted
      the dead writer's tail and continued its ``cseq``, so no hole
      marks where a kill landed;
    * **recompute-replay clean** — every recorded action reproduces
      bit-exactly from its JSON-round-tripped input snapshot through
      the same pure ``decide`` the controller ran (the offline
      counterfactual-replay contract, asserted in-soak so a drifting
      snapshot is caught where it was written).

    Returns the canonical trace (:func:`~koordinator_tpu.obs.decisions.
    decision_trace`: wall times and shadow annotations dropped, so
    same-seed runs with and without a shadow attached compare
    bit-identical).
    """
    import json as _json

    from koordinator_tpu.obs.decisions import (
        controller_gaps,
        decision_trace,
    )
    from koordinator_tpu.runtime.containment import CrashLoopGovernor
    from koordinator_tpu.runtime.elastic import TopologyController
    from koordinator_tpu.runtime.overload import (
        AdmissionController,
        BrownoutController,
        CircuitBreaker,
    )
    from koordinator_tpu.scheduler.pipeline import _DepthController

    deciders = {
        "depth": _DepthController.decide,
        "brownout": BrownoutController.decide,
        "admission": AdmissionController.decide,
        "breaker": CircuitBreaker.decide,
        "topology": TopologyController.decide,
        "crashloop": CrashLoopGovernor.decide,
    }
    gaps = controller_gaps(records)
    assert not gaps, (
        f"{context}: per-controller decision sequences have holes "
        f"(a controller's decisions were lost): {gaps}"
    )
    drifted = []
    for rec in records:
        decide = deciders.get(str(rec.get("controller")))
        if decide is None:
            continue
        action, _state = decide(_json.loads(_json.dumps(rec["inputs"])))
        if action != rec["action"]:
            drifted.append(
                (rec.get("controller"), rec.get("seq"),
                 rec["action"], action)
            )
    assert not drifted, (
        f"{context}: {len(drifted)} recorded decision(s) fail recompute "
        f"replay — decide() is impure or the snapshot is incomplete; "
        f"first 3: {drifted[:3]}"
    )
    return decision_trace(records)


def run_chaos_soak(
    cycles: int = 200,
    seed: int = 0,
    n_nodes: int = 24,
    max_arrivals: int = 12,
    drain_limit: int = 60,
    use_channel: bool = True,
    verbose: bool = False,
    ha: bool = False,
    shards: int = 0,
    incarnations: int = 3,
    shadow: bool = False,
) -> dict:
    """Longrun chaos soak: hundreds of scheduling cycles under a seeded
    random fault schedule, asserting the failure-domain invariants the
    hardening promises:

    * **no pod is ever placed twice** (each uid binds exactly once);
    * **quota is never exceeded** (leaf used ≤ max every cycle);
    * **resident state reconverges exactly** (bit-exact vs a full host
      re-lower at the end — rollbacks and fallbacks leave no stale row);
    * **every pod eventually places** (failed cycles only defer);
    * **same seed ⇒ same fault trace** (the returned ``fault_trace``).

    Fault domains exercised per the schedule: RPC drops on the snapshot
    channel (one-shot drops healed by the client RetryPolicy, persistent
    drops creating generation gaps healed by the full-resync protocol),
    watch disconnects (informer re-list), solver dispatch failures
    (fallback ladder + re-promotion), NaN row corruption (numeric
    quarantine), a solve-latency spike against the per-cycle deadline
    (batch degrade), exactly one mid-commit crash (Reserve journal
    rollback), and — scheduling runs through the cross-cycle
    :class:`~koordinator_tpu.scheduler.pipeline.CyclePipeline` (perf
    PR 4) — prepare-worker stalls/deaths (``pipeline.worker_stall``),
    which must degrade the cycle to the serial path and recover, never
    wedge the drain.

    ``ha=True`` (failover PR) adds the high-availability failure domain
    on top, with its events drawn from a THIRD seeded stream so every
    historical schedule stays bit-identical: scheduling runs under a
    :class:`~koordinator_tpu.runtime.ha.LeaderCoordinator` (lease
    election + epoch fence + write-ahead bind journal), ``leader.lost``
    flaps force mid-pipeline handoffs (speculation discarded, trailing
    commit fenced), and exactly one ``scheduler.crash_restart`` — armed
    together with a second mid-commit ``commit.crash`` — kills the
    scheduler process outright: snapshot, device-resident state, quota
    ledgers and in-flight pipeline all die; a fresh instance re-wires
    the statehub, waits out the dead leader's lease, and takes over
    through journal replay with per-takeover bit-exact resident-state
    verification. Additional HA invariants: every journal-acknowledged
    binding survives the crash (zero lost), no pod is ever placed twice
    across incarnations, and the leaderless gap only defers.

    ``shards=S`` (PR 6, horizontally partitioned control plane) selects
    the MULTI-SHARD arm instead: ``incarnations`` (3+) concurrently-live
    :class:`~koordinator_tpu.runtime.shards.ShardedScheduler` instances
    partition node ownership across S shards — per-shard leases, epochs
    and journals, rendezvous multi-standby election, voluntary shard
    handoffs on membership change, leader flaps, and one kill-restart
    mid-schedule whose lost-ack window is recovered per shard — keeping
    zero-duplicate / zero-lost-acknowledged / per-shard bit-exact
    resident-state asserts green with same-seed-same-trace determinism.
    """
    if shards:
        return _run_sharded_soak(
            cycles=cycles,
            seed=seed,
            n_nodes=n_nodes,
            max_arrivals=max_arrivals,
            drain_limit=drain_limit,
            verbose=verbose,
            shards=shards,
            incarnations=incarnations,
            shadow=shadow,
        )
    import random as _random

    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        ElasticQuota,
        Node,
        NodeMetric,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceMetric,
    )
    from koordinator_tpu.chaos import FaultInjector
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
        ScheduleOutcome,
    )
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )
    from koordinator_tpu.utils.retry import RetryPolicy

    ALLOC_CPU, ALLOC_MEM = 32_000.0, 128 * 1024.0
    POD_CPU, POD_MEM = 2_000.0, 4_096.0
    LIFETIME = 6            # cycles a pod runs before completing
    rng = _random.Random(seed)
    # separate seeded stream for fault points added AFTER the original
    # schedule shipped: drawing them from `rng` would shift every
    # downstream draw and silently re-roll the whole historical schedule
    rng_pipe = _random.Random(seed ^ 0x9E3779B9)
    # third stream for the HA failure domain (failover PR), same rule
    rng_ha = _random.Random(seed ^ 0x51F15EED)

    chaos = FaultInjector(seed=seed)
    # solver observatory (devprof PR): the compile/retrace ledger rides
    # the whole soak. Warmup and the scheduled structural faults (bucket
    # degrade, surge, crash-restart) legitimately compile new shapes;
    # once they are behind us the steady-state contract is RETRACE-FREE
    # — a steady retrace means a shape/flag leak on the hot solve path.
    # The leak sentinel samples live device arrays across incarnation
    # boundaries (ha crash-restart): monotone growth fails.
    from koordinator_tpu.obs.devprof import CompileLedger, LeakSentinel

    ledger = CompileLedger().install()
    leaks = LeakSentinel(tolerance_bytes=4 << 20)
    snap = ClusterSnapshot()
    # preemption off: the soak's contract is that every pod binds exactly
    # once and stays bound until completion — an evicted victim would be
    # a legitimate second placement, muddying the duplicate-bind invariant
    gqm = GroupQuotaManager(snap.config, enable_preemption=False)
    # max sized so steady-state quota throughput (max/LIFETIME per cycle)
    # covers the ~arrivals/5 quota-labeled arrival rate — bursts still
    # hit QUOTA_EXHAUSTED transiently, but the backlog stays drainable
    q_pods = max(6, (2 * max_arrivals * LIFETIME) // 5)
    quota_max = {
        ext.RES_CPU: q_pods * POD_CPU,
        ext.RES_MEMORY: q_pods * POD_MEM,
    }
    quota_min = {ext.RES_CPU: 2 * POD_CPU, ext.RES_MEMORY: 2 * POD_MEM}
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="soak-team"),
            min=dict(quota_min),
            max=dict(quota_max),
        )
    )
    # scheduling flows through the cross-cycle pipeline: decisions lag
    # one cycle (solve in flight while the previous commit trails), the
    # prepare worker is a live failure domain, and every invariant below
    # must keep holding through stalls and degradations
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    # HA primitives (failover PR): the fence and journal STORE outlive
    # any one scheduler incarnation — they are the durable substrate the
    # crash-restart leg rebuilds from
    fence = journal_store = None
    if ha:
        from koordinator_tpu.core.journal import (
            BindJournal,
            EpochFence,
            MemoryJournalStore,
        )

        fence = EpochFence()
        journal_store = MemoryJournalStore()
    # decision observatory (decision-observatory PR): like the bind
    # journal's store, the decision STORE outlives any one scheduler
    # incarnation — the crash-restart leg's fresh instance adopts the
    # dead writer's decision tail from it, keeping per-controller tick
    # sequences gap-free across the kill (swept at the end). Capacity
    # sized so no soak-length record stream is ever ring-evicted: the
    # end sweep replays the COMPLETE decision history.
    from koordinator_tpu.core.journal import (
        MemoryJournalStore as _DecisionStore,
    )
    from koordinator_tpu.obs.decisions import DecisionLedger

    decision_store = _DecisionStore()
    decision_gen = [0]   # bumped per instance: per-incarnation identity
    shadow_registry = None
    if shadow:
        # the bit-exactness arm: an ALWAYS-diverging shadow consults on
        # every depth record; same-seed scheduling must stay
        # bit-identical with it attached (a shadow can never act)
        from koordinator_tpu.obs.shadow import (
            AlwaysDivergeShadow,
            ShadowRegistry,
        )

        shadow_registry = ShadowRegistry()
        shadow_registry.attach("depth", AlwaysDivergeShadow())

    def _make_instance(snapshot, quotas):
        """One scheduler 'process': BatchScheduler + CyclePipeline.
        Called once at start and again after every crash-restart."""
        s = BatchScheduler(
            snapshot,
            LoadAwareArgs(usage_thresholds={}),
            quotas=quotas,
            batch_bucket=16,
            chaos=chaos,
            cycle_deadline_s=0.6,
            fallback_repromote_after=3,
            fetch_timeout_s=2.0,
            journal=BindJournal(journal_store) if ha else None,
            fence=fence,
            # state-integrity PR: the anti-entropy scrubber audits a
            # rotating resident-row window every cycle tail — the
            # resident.bit_flip arm below must be DETECTED and healed
            # by it, and a clean soak proves the audit itself never
            # perturbs scheduling (same-seed-same-trace still holds)
            scrub_rows=8,
        )
        s.extender.monitor.stop_background()
        r = s.extender.registry
        chaos.bind_counter(r.get("fault_injected_total"))
        # decision observatory: a per-incarnation ledger over the shared
        # soak-lifetime store — a restarted instance's ledger adopts its
        # predecessor's tail at construction, so the depth controller's
        # tick sequence continues gap-free across the kill
        dl = DecisionLedger(
            decision_store,
            capacity=4096,
            incarnation=f"soak-gen{decision_gen[0]}",
        )
        decision_gen[0] += 1
        if shadow_registry is not None:
            dl.attach_shadow(shadow_registry)
        s.attach_decision_ledger(dl)
        # generous prepare deadline: a chaos-KILLED worker is detected
        # promptly via thread death (collect returns early), so the
        # timeout only bounds a genuinely slow prepare — a tight value
        # makes the stall/health accounting flake under host contention.
        # depth=2 (open-the-gates PR): the plain arm runs the DEEP
        # pipeline — two speculative solves in flight, quota-bearing
        # cycles riding the opened gates — so every invariant below also
        # proves the chain-of-validations discipline under chaos. The HA
        # arm stays at depth 1: its crash-window calibration (the surge
        # fed exactly one cycle before the kill so journaled-but-unacked
        # binds land in the crash commit) is lag-1 by design, and the
        # depth>1 discard-chain behavior has its own dedicated arms in
        # tests/test_pipelined_stream.py.
        depth = 1 if ha else 2
        return s, CyclePipeline(s, prepare_timeout_s=10.0, depth=depth), r

    sched, pipe, reg = _make_instance(snap, gqm)

    hub = ClusterStateHub(
        chaos=chaos, health=sched.extender.health, error_registry=reg
    )
    hub.wire_scheduler(sched)
    hub.start()
    for i in range(n_nodes):
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=f"n{i:03d}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: ALLOC_CPU,
                        ext.RES_MEMORY: ALLOC_MEM,
                    }
                ),
            ),
        )
    assert hub.wait_synced()

    # shadow solver sidecar over a real loopback gRPC channel: the soak
    # mirrors its world over Sync deltas; dropped deltas create genuine
    # generation gaps the resync protocol must heal
    service = client = server = None
    live_synced: dict = {}   # uid -> (node, requests) mirrored to sidecar
    revision = 0
    q_idx = gqm.index_of("soak-team")
    quota_max_vec = snap.config.res_vector(quota_max)
    if use_channel:
        from koordinator_tpu.runtime.snapshot_channel import (
            SolverClient,
            SolverService,
            serve,
        )

        service = SolverService()
        service.scheduler.extender.monitor.stop_background()
        server, port = serve(service)
        client = SolverClient(
            f"127.0.0.1:{port}",
            timeout_s=5.0,
            retry=RetryPolicy(
                max_attempts=3, base_delay_s=0.005, max_delay_s=0.02,
                jitter=0.0,
            ),
            chaos=chaos,
            retry_counter=reg.get("retry_attempts_total"),
        )
        cfg = snap.config

        def _vec(rl):
            from koordinator_tpu.runtime.proto import snapshot_pb2 as pb

            return pb.ResourceVector(
                values=[float(x) for x in cfg.res_vector(rl)]
            )

        def full_state_fn():
            from koordinator_tpu.runtime.proto import snapshot_pb2 as pb

            full = pb.SnapshotDelta()
            for i in range(n_nodes):
                full.node_upserts.add(
                    name=f"n{i:03d}",
                    allocatable=_vec(
                        {ext.RES_CPU: ALLOC_CPU, ext.RES_MEMORY: ALLOC_MEM}
                    ),
                )
            for uid, (node, requests) in live_synced.items():
                full.pod_assumed.add(
                    uid=uid, node=node, requests=_vec(requests)
                )
            return full

    stats = {
        "cycles": 0,
        "arrived": 0,
        "placed": 0,
        "completed": 0,
        "sync_lost": 0,
        "resyncs": 0,
        "deferred_cycles": 0,
        "faults": {},
        "takeovers": 0,
        "crash_restarts": 0,
        "recovered_bindings": 0,
        "cycles_without_leader": 0,
        #: state-integrity PR: corruption-domain evidence — scrub
        #: divergences healed (folded across incarnations), checkpoint
        #: usage/fallback on the post-crash recovery, and the journal
        #: store's quarantine ledger (stamped at the end)
        "scrub_divergence": {},
        "recovery_used_checkpoint": 0,
        "checkpoint_fallbacks": 0,
        #: adaptive-depth PR: the controller's per-cycle choice (plain
        #: arm runs max depth 2 — the trace must flex 2→1 under the
        #: fault-window churn and recover to 2 in the quiet tail).
        #: Deterministic: the controller draws no randomness, so the
        #: same seed yields the same trace (determinism arm compares it)
        "depth_trace": [],
    }
    placed: dict = {}        # uid -> node, forever (duplicate guard)
    live: list = []          # (pod, node, done_cycle)
    pending: list = []       # pods awaiting placement (retries ride along)
    pod_seq = 0
    crash_cycle = max(2, cycles // 3)
    deadline_cycle = max(3, cycles // 2)
    # chaos-coverage (koordlint chaos-coverage pass): the remaining
    # MAIN-THREAD fault domains ride fixed-cycle arms — no rng stream is
    # consumed, so every historical seeded schedule stays bit-identical.
    # (Points that fire on background threads — informer, fetch worker —
    # stay out: they would race the same-seed fault-trace order, and are
    # exempted to their dedicated fault tests instead.)
    ladder_cycle = max(1, cycles // 4)       # full fallback ladder
    sync_delay_cycle = max(1, cycles // 6)   # channel latency injection
    # open-the-gates PR: corrupt one chained quota/NUMA/device carry at
    # consume — the discard-and-redispatch path under full soak load
    carry_mismatch_cycle = max(3, (2 * cycles) // 7)
    stale_commit_cycle = max(2, cycles // 5)     # ha: fenced commit
    journal_fault_cycle = max(4, (2 * cycles) // 5)  # ha: append refusal
    # state-integrity PR (corruption fault domain, fixed cycles — no rng
    # draws, historical schedules stay bit-identical): one resident-table
    # bit flip the scrubber must detect+heal, and — HA only, the arms
    # need a journal — one mid-stream corrupt record (quarantined, zero
    # acked binds lost), one seq write hole, and a checkpoint image whose
    # digest the post-crash recovery must reject (full-replay fallback)
    bit_flip_cycle = max(2, (3 * cycles) // 8)
    corrupt_record_cycle = max(3, (4 * cycles) // 9)
    seq_gap_cycle = max(4, (5 * cycles) // 11)
    # HA leg (failover PR): one scheduled kill-restart well after the
    # other fault domains have fired, leader flaps from the rng_ha stream
    restart_cycle = max(6, (3 * cycles) // 5) if ha else None
    checkpoint_cycle = (restart_cycle - 2) if ha else None
    ckpt_written = [False]
    # retrace-free steady state starts once every scheduled structural
    # fault (deadline surge/degrade, crash-restart) is behind + slack
    # for the degrade to re-promote
    steady_cycle = max(deadline_cycle, restart_cycle or 0, crash_cycle) + 8

    # ---- HA coordinator: lease election + epoch fence + recovery ----
    coord = None
    incarnation = 0
    lost_pods: list = []     # decided-or-inflight pods orphaned by a crash
    recovered_sync: list = []  # journal-recovered binds awaiting sidecar sync
    if ha:
        from koordinator_tpu.runtime.ha import LeaderCoordinator
        from koordinator_tpu.utils.leaderelection import (
            InMemoryLeaseLock,
            LeaderElector,
        )

        lease_lock = InMemoryLeaseLock()
        sim_cycle = [0]

        def _lease_now() -> float:
            return float(sim_cycle[0])

        def _make_coordinator():
            # a fresh identity per incarnation: the dead process cannot
            # renew, so the new one waits out the old lease (a real
            # failover gap of ~lease_duration cycles) before taking over
            elector = LeaderElector(
                lease_lock,
                f"soak-gen{incarnation}",
                lease_duration=3.0,
                renew_deadline=2.0,
                retry_period=0.5,
                now_fn=_lease_now,
                sleep_fn=lambda _dt: None,
            )
            return LeaderCoordinator(
                sched,
                elector,
                fence,
                sched.bind_journal,
                hub=hub,
                pipeline=pipe,
                chaos=chaos,
            )

        coord = _make_coordinator()

    def _crash_restart(orphans):
        """Kill the scheduler process: snapshot, device-resident state,
        quota ledgers, pipeline and watches all die; only the statehub
        (apiserver), lease lock, fence and journal store survive. A
        fresh incarnation re-wires and will take over once the dead
        leader's lease expires."""
        nonlocal snap, gqm, sched, pipe, reg, coord, q_idx
        nonlocal incarnation, lost_pods
        stats["crash_restarts"] += 1
        _fold_scrub()   # the dying incarnation's audit ledger
        pipe.close()   # resource hygiene only — all state is discarded
        hub.detach_consumers()
        lost_pods = [p for p in orphans if p.meta.uid not in placed]
        incarnation += 1
        snap = ClusterSnapshot()
        gqm = GroupQuotaManager(snap.config, enable_preemption=False)
        gqm.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="soak-team"),
                min=dict(quota_min),
                max=dict(quota_max),
            )
        )
        sched, pipe, reg = _make_instance(snap, gqm)
        q_idx = gqm.index_of("soak-team")
        hub.health = sched.extender.health
        hub.error_registry = reg
        hub.wire_scheduler(sched)
        hub.start()
        coord = _make_coordinator()
        # incarnation boundary: the dead process's resident arrays must
        # actually die (leak-detector arm)
        leaks.sample(f"restart-{incarnation}")

    def _fold_scrub():
        """Fold the current incarnation's anti-entropy audit ledger into
        the run stats (the per-scheduler report dies with its process)."""
        for table, n in sched._scrub_report["divergence"].items():
            stats["scrub_divergence"][table] = (
                stats["scrub_divergence"].get(table, 0) + int(n)
            )

    def _sync_cycle_delta(new_bound, forgotten):
        """Mirror this cycle's bindings/completions to the sidecar; a
        persistently-dropped delta is LOST (revision still advances) and
        the next successful sync heals through the resync protocol."""
        nonlocal revision
        if client is None:
            return
        from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
        from koordinator_tpu.runtime.snapshot_channel import ChannelError

        revision += 1
        delta = pb.SnapshotDelta(revision=revision)
        for pod, node in new_bound:
            delta.pod_assumed.add(
                uid=pod.meta.uid, node=node, requests=_vec(pod.spec.requests)
            )
        for uid in forgotten:
            delta.pod_forgotten.append(uid)
        # fold this delta into the authoritative ledger FIRST: when the
        # server demands a resync, the full re-list must describe the
        # world INCLUDING the rejected delta's content (a full state
        # built from the pre-delta ledger would silently drop this
        # cycle's changes while still advancing the revision)
        for pod, node in new_bound:
            live_synced[pod.meta.uid] = (node, dict(pod.spec.requests))
        for uid in forgotten:
            live_synced.pop(uid, None)
        def counting_full_state():
            # sync_with_resync asks for the full world only when the
            # server reported a generation gap — count the heal here
            stats["resyncs"] += 1
            return full_state_fn()

        try:
            client.sync_with_resync(delta, counting_full_state)
        except ChannelError:
            # delta lost in transit: the sidecar now has a generation
            # gap; the next successful sync heals it via the full
            # re-list above (live_synced stays the authoritative ledger)
            stats["sync_lost"] += 1

    total_cycles = cycles + drain_limit
    for cycle in range(total_cycles):
        stats["cycles"] += 1
        if cycle == steady_cycle:
            ledger.mark_steady()
            leaks.sample("steady")
        arriving = []
        if cycle < cycles:
            # ---- seeded fault schedule (arrivals stop at `cycles`;
            # the drain tail runs fault-free so the backlog clears) ----
            r = rng.random()
            if r < 0.15:
                chaos.arm("channel.sync.drop", times=1)          # retry heals
            elif r < 0.20:
                chaos.arm("channel.sync.drop", times=10)         # delta lost
            if rng.random() < 0.10:
                hub.disconnect()                                  # watch sever
            if rng.random() < 0.06:
                chaos.arm(
                    "solver.dispatch", error=RuntimeError, times=1
                )                                                 # ladder demote
            if rng.random() < 0.05:
                chaos.arm("solver.nan_rows", times=1)             # quarantine
            if rng_pipe.random() < 0.08:
                chaos.arm("pipeline.worker_stall", times=1)       # serial degrade
            if ha and rng_ha.random() < 0.05:
                chaos.arm("leader.lost", times=1)                 # leader flap
            if cycle == ladder_cycle:
                # both device levels fail in one cycle: level 0 demotes
                # to the per-chunk path, whose own armed fault demotes to
                # the numpy host reference — the full ladder under soak
                chaos.arm("solver.dispatch", error=RuntimeError, times=1)
                chaos.arm(
                    "solver.dispatch_chunk", error=RuntimeError, times=1
                )
            if use_channel and cycle == sync_delay_cycle:
                chaos.arm("channel.sync.delay", latency_s=0.01, times=1)
            if cycle == carry_mismatch_cycle:
                # fixed-cycle arm, probability 1: fires without drawing
                # from any rng stream, so historical seeded schedules
                # stay bit-identical (same rule as the other fixed arms)
                chaos.arm("pipeline.carry_mismatch", times=1)
            if ha and cycle == stale_commit_cycle:
                chaos.arm("leader.stale_commit", times=1)  # fenced, no retry charge
            if ha and cycle == journal_fault_cycle:
                # journal-before-mutate: the refused append rejects the
                # chunk un-mutated (JOURNAL_WRITE_FAILED), pods retry
                chaos.arm("journal.write_fail", times=1)
            if cycle == bit_flip_cycle:
                # one resident cell rots on device; the cycle-tail
                # scrub window owning the flipped row detects and heals
                # it (end-state bit-exactness re-proves the heal)
                chaos.arm("resident.bit_flip", times=1)
            if ha and cycle == corrupt_record_cycle:
                # media rot on an ACKED journal record (fires at the
                # next intent append): load-time screening quarantines
                # exactly that record and keeps every verifiable record
                # after it — the zero-lost-ack assert at the end is the
                # proof silent truncation is gone
                chaos.arm("journal.corrupt_record", times=1)
            if ha and cycle == seq_gap_cycle:
                chaos.arm("journal.seq_gap", times=1)
            if (
                ha
                and checkpoint_cycle is not None
                and checkpoint_cycle <= cycle < restart_cycle
                and not ckpt_written[0]
                and coord.leading
            ):
                # a checkpoint recovery image lands before the kill
                # (first LED cycle in the window, so a leader flap at
                # the nominal cycle cannot skip it); the digest
                # mismatch armed at the kill cycle below then forces
                # the takeover's recovery to fall back to the
                # full-history replay (same world, one counted
                # fallback)
                sched.bind_journal.append_checkpoint(
                    epoch=sched._fence_epoch
                )
                ckpt_written[0] = True
            if cycle == crash_cycle:
                chaos.arm("commit.crash", error=RuntimeError, times=1)
            if ha and cycle == restart_cycle:
                # mid-commit crash-restart: this cycle's trailing commit
                # crashes (journal abort) AND the process dies right
                # after the commit stage — the lost-ack window
                chaos.arm("commit.crash", error=RuntimeError, times=1)
                chaos.arm("scheduler.crash_restart", times=1)
                if ckpt_written[0]:
                    # armed AT the kill so the next checkpoint-bearing
                    # recovery — the post-crash takeover — consumes it
                    chaos.arm("checkpoint.digest_mismatch", times=1)
            surge = 0
            if cycle == deadline_cycle:
                # solve-latency spike + a surge so the cycle spans
                # multiple chunks: the per-cycle deadline must defer the
                # tail instead of wedging
                chaos.arm("solver.dispatch", latency_s=1.0, times=1)
                surge = 3 * sched.batch_bucket
            if ha and restart_cycle is not None and cycle == restart_cycle - 1:
                # multi-chunk batch for the crash cycle's trailing
                # commit: the armed commit.crash rolls ONE chunk back
                # (mid-commit abort) while later chunks COMMIT — their
                # journaled-but-never-acknowledged binds are exactly
                # what the takeover must recover, not re-place
                surge += 2 * sched.batch_bucket
            for _ in range(rng.randint(1, max_arrivals) + surge):
                pod_seq += 1
                labels = {}
                if pod_seq % 5 == 0:
                    labels[ext.LABEL_QUOTA_NAME] = "soak-team"
                arriving.append(
                    Pod(
                        meta=ObjectMeta(
                            name=f"soak-{pod_seq:05d}", labels=labels
                        ),
                        spec=PodSpec(
                            requests={
                                ext.RES_CPU: POD_CPU,
                                ext.RES_MEMORY: POD_MEM,
                            },
                            priority=9000 if pod_seq % 3 else 5500,
                        ),
                    )
                )
            stats["arrived"] += len(arriving)
        pending.extend(arriving)

        # ---- HA: election step + crash-orphan reconciliation ----
        leading = True
        if coord is not None:
            sim_cycle[0] = cycle
            was_leading = coord.leading
            leading, drained = coord.tick()
            if leading and not was_leading:
                stats["takeovers"] += 1
                if client is not None:
                    client.set_epoch(fence.current())
            if drained is not None:
                # mid-pipeline handoff flush: with the grant revoked the
                # fence rejects every chunk, so the in-flight batch comes
                # back unschedulable for the next leader (bound handled
                # defensively — possible only if the fence still held)
                for pod, node in drained.bound:
                    assert pod.meta.uid not in placed, pod.meta.name
                    placed[pod.meta.uid] = node
                    pod.spec.node_name = node
                    hub.publish(hub.pods, pod)
                    live.append((pod, node, cycle + LIFETIME))
                    recovered_sync.append((pod, node))
                    stats["placed"] += 1
                pending.extend(drained.unschedulable)
            if leading and lost_pods:
                # reconcile the crash's orphans against the journal:
                # an ACKNOWLEDGED (journaled) binding is recovered —
                # never re-placed — everything else re-enters the backlog
                rec = coord.last_recovery
                bindings = rec.bindings if rec is not None else {}
                if rec is not None:
                    # state-integrity PR: the post-crash recovery's
                    # checkpoint verdict (used, or digest-fallback)
                    stats["recovery_used_checkpoint"] += int(
                        rec.used_checkpoint
                    )
                    stats["checkpoint_fallbacks"] += int(
                        rec.checkpoint_fallback
                    )
                for pod in lost_pods:
                    node = bindings.get(pod.meta.uid)
                    if node is not None and pod.meta.uid not in placed:
                        placed[pod.meta.uid] = node
                        pod.spec.node_name = node
                        hub.publish(hub.pods, pod)
                        live.append((pod, node, cycle + LIFETIME))
                        recovered_sync.append((pod, node))
                        stats["placed"] += 1
                        stats["recovered_bindings"] += 1
                    elif pod.meta.uid not in placed:
                        pending.append(pod)
                lost_pods = []

        if (
            not pending
            and not pipe.inflight
            and not lost_pods
            and cycle >= cycles
        ):
            break

        # pipelined feed: this batch's solve goes in flight, the
        # PREVIOUS batch's trailing commit lands — its outcome is what
        # the bookkeeping below sees (one-cycle lag; invariants are
        # lag-agnostic: they compare live accounting, not batch identity)
        fed_this_cycle = False
        if coord is not None and not leading:
            # leaderless gap (waiting out the dead leader's lease, or a
            # flap mid-recovery): no scheduling authority — the backlog
            # carries over untouched
            stats["cycles_without_leader"] += 1
            out = ScheduleOutcome(bound=[], unschedulable=list(pending))
            pending = []
        else:
            fed = list(pending)
            pending = []
            out = pipe.feed(fed)
            fed_this_cycle = True
            if out is None:
                out = ScheduleOutcome(bound=[], unschedulable=[])
        if (
            coord is not None
            and fed_this_cycle
            and chaos.fire("scheduler.crash_restart")
        ):
            # the process dies AFTER the trailing commit journaled its
            # binds but BEFORE the bind API writes go out: the driver
            # never observes `out` (decided-but-unacknowledged), and the
            # freshly fed batch dies in flight — both sets become the
            # takeover's reconciliation problem
            # depth>1: SEVERAL batches can be inside the pipeline —
            # orphan them all, not just the last fed
            orphans = (
                [p for p, _n in out.bound]
                + list(out.unschedulable)
                + pipe.inflight_pods()
            )
            out = ScheduleOutcome(bound=[], unschedulable=[])
            _crash_restart(orphans)
        stats["depth_trace"].append(pipe.last_adaptive_depth)
        new_bound = []
        for pod, node in out.bound:
            # INVARIANT: a pod binds exactly once, ever
            assert pod.meta.uid not in placed, (
                f"pod {pod.meta.name} placed twice: "
                f"{placed[pod.meta.uid]} then {node}"
            )
            placed[pod.meta.uid] = node
            pod.spec.node_name = node
            hub.publish(hub.pods, pod)
            live.append((pod, node, cycle + LIFETIME))
            new_bound.append((pod, node))
        stats["placed"] += len(new_bound)
        if fed_this_cycle and sched._cycle_deadline_hit:
            stats["deferred_cycles"] += 1
        pending = list(out.unschedulable)

        # ---- completions release capacity through the informer ----
        forgotten = []
        still = []
        for pod, node, done in live:
            if done <= cycle:
                hub.delete(hub.pods, pod)
                forgotten.append(pod.meta.uid)
                stats["completed"] += 1
            else:
                still.append((pod, node, done))
        live = still
        assert hub.wait_synced()

        if recovered_sync:
            # journal-recovered / handoff-drained binds reach the sidecar
            # with the next delta, like any other bind write
            new_bound = recovered_sync + new_bound
            recovered_sync = []
        _sync_cycle_delta(new_bound, forgotten)

        # ---- per-cycle invariants ----
        # quota never exceeded (leaf used ≤ max, chaos or not)
        if q_idx is not None and q_idx < gqm.used.shape[0]:
            assert np.all(gqm.used[q_idx] <= quota_max_vec + 1e-3), (
                gqm.used[q_idx],
                quota_max_vec,
            )
        # snapshot accounting never drifts (rollbacks included)
        want = np.zeros_like(snap.nodes.requested)
        for uid, ap in snap._assumed.items():
            want[ap.node_idx] += ap.request
        np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
        if verbose and cycle % 25 == 0:
            print(
                f"cycle={cycle:4d} pending={len(pending):3d} "
                f"placed={stats['placed']} lost_syncs={stats['sync_lost']} "
                f"fallback_level={sched._fallback_level}"
            )

    # drain the pipeline's in-flight tail (loop exhaustion may leave up
    # to `depth` batches mid-flight; a break can't — its condition
    # requires an empty pipeline) and account each exactly like an
    # in-loop cycle
    while pipe.inflight:
        final = pipe.flush()
        if final is None:
            continue
        final_bound = []
        for pod, node in final.bound:
            assert pod.meta.uid not in placed, (
                f"pod {pod.meta.name} placed twice"
            )
            placed[pod.meta.uid] = node
            pod.spec.node_name = node
            hub.publish(hub.pods, pod)
            final_bound.append((pod, node))
        stats["placed"] += len(final_bound)
        pending.extend(final.unschedulable)
        assert hub.wait_synced()
        _sync_cycle_delta(final_bound, [])
    # adaptive-depth recovery leg (open the last gates PR): a FIXED
    # quiet tail — no arrivals, no faults, no rng-stream draws — after
    # the drain. The depth controller must re-deepen to the configured
    # max once the churn evidence goes quiet ("a quiet drain deepens"),
    # and the trace records it for the soak's 2→1→2 assertion.
    from koordinator_tpu.scheduler.pipeline import _DepthController

    for _ in range(2 * _DepthController.QUIET_FEEDS):
        pipe.feed([])
        stats["depth_trace"].append(pipe.last_adaptive_depth)
    pipe.close()

    # ---- end-state assertions ----
    # every pod that ever arrived eventually placed
    assert not pending, f"{len(pending)} pods never placed"
    assert stats["placed"] == stats["arrived"] == len(placed)
    # resident device state reconverged bit-exactly vs a full re-lower
    assert_resident_state_converged(sched)
    # capture the fault ledger BEFORE disarming for the final heal
    stats["faults"] = chaos.fired_counts()
    stats["fault_trace"] = list(chaos.trace)
    chaos.disarm()
    # decision observatory (decision-observatory PR): every controller
    # decision the soak took is on the shared store. The sweep asserts
    # gap-free per-controller sequences (the HA kill's successor adopted
    # the dead writer's tail) and recompute-replay cleanliness, and the
    # canonical trace rides the stats for the same-seed bit-exactness
    # arms (wall times and shadow annotations dropped by construction,
    # so a shadow-attached run compares bit-identical)
    dec_records = sorted(
        decision_store.load(), key=lambda r: r.get("seq", 0)
    )
    assert dec_records, "the soak recorded no controller decisions"
    stats["decision_trace"] = _sweep_decisions(
        dec_records, context="chaos-soak decisions"
    )
    stats["decisions_total"] = len(dec_records)
    # proof the shadow arm really consulted: divergence annotations on
    # the RAW records (decision_trace drops them — that is the point)
    stats["shadow_divergences"] = sum(
        1 for r in dec_records if r.get("shadow", {}).get("diverged")
    )
    if stats["crash_restarts"]:
        # the kill really produced an adopted tail: the store carries
        # records stamped by more than one writer incarnation
        writers = {r.get("incarnation") for r in dec_records}
        assert len(writers) >= 2, (
            f"crash-restart fired but the decision store shows a "
            f"single writer: {writers}"
        )
    # the sidecar's world re-converged through the resync protocol
    if client is not None:
        _sync_cycle_delta([], [])   # fault-free final heal
        side = service.snapshot
        assert side.node_count == snap.node_count
        # compare committed capacity per node name
        for i in range(n_nodes):
            name = f"n{i:03d}"
            si, mi = side.node_id(name), snap.node_id(name)
            np.testing.assert_allclose(
                side.nodes.requested[si],
                snap.nodes.requested[mi],
                atol=1e-3,
            )
        client.close()
        server.stop(grace=None)
    # informer re-list recovery is WALL-CLOCK backoff on background
    # threads: once the fault schedule stops, give the streams their
    # bounded window BEFORE hub.stop() freezes the health rows — the
    # invariant is that every subsystem RECOVERS, not that it happened
    # to recover inside however long this host took to run the drain
    import time as _walltime

    deadline = _walltime.monotonic() + 10.0
    while (
        not sched.extender.health.ok()
        and _walltime.monotonic() < deadline
    ):
        _walltime.sleep(0.05)
    hub.stop()
    if coord is not None:
        from koordinator_tpu.core.journal import BindJournal as _BJ

        # zero lost acknowledged bindings: every journal-live bind (acked
        # binds minus forgets, across ALL incarnations) must have landed
        # in the driver's placed ledger exactly once
        ha_rep = _BJ(journal_store).replay()
        lost_acked = [u for u in ha_rep.live if u not in placed]
        assert not lost_acked, (
            f"{len(lost_acked)} journal-acknowledged bindings lost "
            f"across takeovers"
        )
        # state-integrity PR: the corruption arms really fired and were
        # CONTAINED — the corrupt record quarantined (zero acked binds
        # lost is asserted just above, THROUGH the corruption), the
        # write hole counted, and the store's live stream still replays
        integ = journal_store.integrity_total
        stats["journal_corrupt_quarantined"] = integ.corrupt
        stats["journal_seq_gaps"] = integ.seq_gaps
        # the post-corruption journal, quarantined records included, so
        # the fsck acceptance test can round-trip EXACTLY what this
        # soak's stores ended up holding
        stats["journal_dump"] = [
            dict(r) for r in journal_store._records
        ] + [dict(r) for r in journal_store.quarantined]
        stats["journal_live"] = sorted(ha_rep.live)
        if cycles > corrupt_record_cycle:
            assert integ.corrupt >= 1, (
                "journal.corrupt_record armed but nothing was quarantined"
            )
        if cycles > seq_gap_cycle:
            assert integ.seq_gaps >= 1, (
                "journal.seq_gap armed but no write hole was detected"
            )
        if coord.leading:
            assert sched._fence_epoch == fence.current() > 0
        stats["leader_epoch_final"] = fence.current()
        stats["journal_records"] = len(journal_store.load())
        stats["journal_open_intents"] = ha_rep.open_intents
        stats["fenced_commits_total"] = reg.get(
            "leader_fenced_commits_total"
        ).value()
    # ---- solver-observatory arm (devprof PR) ----
    try:
        leaks.sample("end")
        leak_problems = leaks.problems()
        assert not leak_problems, leak_problems
        stats["leak_samples"] = list(leaks.samples)
        stats["solver_traces_total"] = ledger.total_traces()
        stats["steady_retraces"] = ledger.steady_retraces()
        if cycles >= 30:
            # short determinism pairs may not reach a meaningful steady
            # window; the fast subset and acceptance soaks must be
            # retrace-free once warm (compile ledger tentpole assertion)
            assert stats["steady_retraces"] == 0, (
                f"{stats['steady_retraces']} steady-state retrace(s): "
                f"{ledger.steady_causes()}"
            )
    finally:
        # a failing assert must not leave the ledger installed in the
        # process-global hook registry for the rest of the test session
        ledger.uninstall()
    _fold_scrub()
    if cycles > bit_flip_cycle:
        # the injected resident bit flip was DETECTED (divergence
        # attributed to the nodes table) — and HEALED: the end-state
        # bit-exactness assert above ran on the same resident tables
        assert stats["scrub_divergence"].get("nodes", 0) >= 1, (
            "resident.bit_flip armed but the scrubber saw no divergence"
        )
    stats["fallback_level_final"] = sched._fallback_level
    stats["health_ok"] = sched.extender.health.ok()
    stats["health_detail"] = {
        k: v
        for k, v in sched.extender.health.snapshot().items()
        if not v["ok"]
    }
    stats["metrics"] = {
        "retry_attempts_channel_sync": reg.get(
            "retry_attempts_total"
        ).value(site="channel.sync"),
        "commit_rollbacks_total": reg.get("commit_rollbacks_total").value(),
        "cycle_deadline_exceeded_total": reg.get(
            "cycle_deadline_exceeded_total"
        ).value(),
        "solver_fallback_l1": reg.get("solver_fallback_total").value(
            level="1"
        ),
    }
    return stats


#: (stats key, registry metric) — containment counters the gray-failure
#: soak folds across incarnations (every restart builds a fresh
#: scheduler registry, so per-incarnation values must be accumulated
#: at the kill and again at the end)
_CONTAINMENT_COUNTERS = (
    ("poison_quarantined_total", "poison_quarantined_total"),
    ("bisect_probes_total", "poison_bisect_probes_total"),
    ("crash_backoffs_total", "crash_loop_backoffs_total"),
)


def run_gray_failure_soak(
    cycles: int = 40,
    seed: int = 0,
    n_nodes: int = 12,
    max_arrivals: int = 6,
    drain_limit: int = 40,
    verbose: bool = False,
) -> dict:
    """Gray-failure containment soak (gray-failure containment PR):
    wrong-but-alive failure modes under a deterministic fixed-cycle
    schedule, asserting the containment invariants end to end:

    * **poison-batch quarantine** — two labeled poison pods arrive at
      ``poison_cycle`` with ``solver.poison_batch`` armed: every ladder
      level crashes, the bisection isolates EXACTLY the poison set,
      blames it on the sealed quarantine ledger, and everything else in
      the batch still places; every later cycle rejects the blamed pods
      at the gate without lowering them (the fire count freezes at the
      isolation cycle);
    * **blame survives the kill** — a kill-restart after the quarantine
      proves the successor adopts blame BEFORE replaying its queue: the
      replayed poison pods are gate-rejected, never re-lowered, so the
      successor does not re-crash (``solver.poison_batch`` never fires
      again) and zero-dup / zero-lost-ack hold across the takeover;
    * **crash-loop governor** — the kill plus ``scheduler.boot_crash``
      (armed ``times=2``) produce K=3 rapid deaths on the shared crash
      ledger: the third death decides exponential boot backoff
      (snapshot-once → pure decide → DecisionLedger ``crashloop``
      records, swept gap-free and recompute-replayed at the end), the
      backed-off candidate does not even contend, and the eventual
      takeover boots DEGRADED (ladder pre-demoted, bisection armed);
    * **informer staleness watchdog** — ``informer.silent_stall`` mutes
      every tracker fan-out for a window while the driver keeps
      publishing: the watchdog's rv-lag check flips the
      ``snapshot_freshness`` health row, the scheduler's captured
      ``_cycle_stale`` goes true, and the descheduler refuses whole
      reconcile passes (the submitted eviction stays PENDING) while
      plain placement continues; disarm + re-list heal everything and
      the eviction then proceeds;
    * **same seed ⇒ same fault trace** (the returned ``fault_trace``).
    """
    import random as _random

    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.chaos import FaultInjector
    from koordinator_tpu.core.journal import (
        BindJournal,
        EpochFence,
        MemoryJournalStore,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.descheduler.migration import (
        MigrationController,
        MigrationMode,
        MigrationPhase,
    )
    from koordinator_tpu.obs.decisions import DecisionLedger
    from koordinator_tpu.runtime.containment import (
        POISON_LABEL,
        CrashLoopGovernor,
        QuarantineLedger,
        StalenessWatchdog,
        spec_fingerprint,
    )
    from koordinator_tpu.runtime.ha import LeaderCoordinator
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.utils.leaderelection import (
        InMemoryLeaseLock,
        LeaderElector,
    )

    if cycles < 30:
        raise ValueError(
            "the gray-failure schedule needs >= 30 cycles to order its "
            "poison / kill / crash-loop / stall phases"
        )

    ALLOC_CPU, ALLOC_MEM = 32_000.0, 128 * 1024.0
    POD_CPU, POD_MEM = 2_000.0, 4_096.0
    LIFETIME = 6
    K_DEATHS = 3             # governor threshold: kill + 2 boot crashes
    rng = _random.Random(seed)
    chaos = FaultInjector(seed=seed)

    # ---- fixed-cycle schedule (no rng draws — the determinism rule) ----
    poison_cycle = cycles // 5
    restart_cycle = max(poison_cycle + 6, (2 * cycles) // 5 + 2)
    stall_cycle = max(restart_cycle + 10, (7 * cycles) // 10)
    stall_end = stall_cycle + 5

    # ---- durable substrate: outlives every scheduler incarnation ----
    fence = EpochFence()
    journal_store = MemoryJournalStore(name="bind")
    quarantine_store = MemoryJournalStore(name="quarantine")
    crash_store = MemoryJournalStore(name="crashloop")
    decision_store = MemoryJournalStore(name="decisions")
    lease_lock = InMemoryLeaseLock()
    sim_cycle = [0]

    def _sim_now() -> float:
        # one shared virtual clock: lease election, the crash-loop
        # governor and the staleness watchdog all tick in cycle units
        return float(sim_cycle[0])

    gen = [0]

    def _make_instance():
        """One scheduler 'process' plus its containment organs. Called
        at start and again after the kill-restart."""
        snapshot = ClusterSnapshot()
        s = BatchScheduler(
            snapshot,
            LoadAwareArgs(usage_thresholds={}),
            batch_bucket=16,
            chaos=chaos,
            fallback_repromote_after=3,
            journal=BindJournal(journal_store),
            fence=fence,
        )
        s.extender.monitor.stop_background()
        r = s.extender.registry
        chaos.bind_counter(r.get("fault_injected_total"))
        dl = DecisionLedger(
            decision_store,
            capacity=4096,
            incarnation=f"gray-gen{gen[0]}",
        )
        s.attach_decision_ledger(dl)
        quar = QuarantineLedger(
            store=quarantine_store,
            incarnation=f"gray-gen{gen[0]}",
            registry=r,
        )
        gv = CrashLoopGovernor(
            store=crash_store,
            k=K_DEATHS,
            horizon_s=10.0,
            base_backoff_s=2.0,
            max_backoff_s=8.0,
            clock=_sim_now,
            decisions=dl,
            registry=r,
            incarnation=f"gray-gen{gen[0]}",
        )
        wdog = StalenessWatchdog(
            horizon_s=2.0, clock=_sim_now,
            health=s.extender.health, registry=r,
        )
        # the scheduler captures the verdict once per cycle into
        # _cycle_stale (koordlint staleness-snapshot capture site)
        s.staleness = wdog.stale
        gen[0] += 1
        return snapshot, s, r, quar, gv, wdog

    snap, sched, reg, quar, gov, wd = _make_instance()

    hub = ClusterStateHub(
        chaos=chaos, health=sched.extender.health, error_registry=reg
    )
    hub.wire_scheduler(sched)
    hub.start()
    wd.watch_hub(hub)
    for i in range(n_nodes):
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=f"n{i:03d}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: ALLOC_CPU,
                        ext.RES_MEMORY: ALLOC_MEM,
                    }
                ),
            ),
        )
    assert hub.wait_synced()

    def _make_coordinator():
        elector = LeaderElector(
            lease_lock,
            f"gray-gen{gen[0] - 1}",
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            now_fn=_sim_now,
            sleep_fn=lambda _dt: None,
        )
        return LeaderCoordinator(
            sched,
            elector,
            fence,
            sched.bind_journal,
            hub=hub,
            chaos=chaos,
            quarantine=quar,
            governor=gov,
        )

    coord = _make_coordinator()

    # descheduler leg: one synthetic victim submitted once the stall is
    # DETECTED (submitting earlier would evict before staleness gates it).
    # The job is EVICT_DIRECTLY so the (empty) reservation manager never
    # schedules anything — the leg under test is the stale-evidence gate.
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
    )

    evictions: list = []
    mig = MigrationController(
        reservations=ReservationManager(sched, clock=_sim_now),
        evict_fn=lambda pod, reason: evictions.append(pod.meta.uid)
        or True,
        freshness=lambda: wd_ref[0].stale(),
    )
    victim = Pod(
        meta=ObjectMeta(name="victim-hot"),
        spec=PodSpec(
            requests={ext.RES_CPU: POD_CPU, ext.RES_MEMORY: POD_MEM}
        ),
    )
    victim_job = None
    wd_ref = [wd]   # rebound on restart: the live watchdog gates evictions

    stats = {
        "cycles": 0,
        "arrived": 0,
        "placed": 0,
        "completed": 0,
        "takeovers": 0,
        "crash_restarts": 0,
        "cycles_without_leader": 0,
        "stale_cycles": 0,
        "freshness_degraded_cycles": 0,
        "stale_sched_cycles": 0,
        "poison_fires_isolation": 0,
        "degraded_boot": False,
        "degraded_fallback_level": 0,
        "poison_quarantined_total": 0.0,
        "bisect_probes_total": 0.0,
        "crash_backoffs_total": 0.0,
    }
    placed: dict = {}
    live: list = []
    pending: list = []
    poison_uids: set = set()
    poison_pods: list = []
    pod_seq = 0

    def _poison_fires() -> int:
        return sum(
            1 for _s, pt, _k in chaos.trace if pt == "solver.poison_batch"
        )

    total_cycles = cycles + drain_limit
    for cycle in range(total_cycles):
        sim_cycle[0] = cycle
        stats["cycles"] += 1
        arriving = []
        if cycle < cycles:
            if cycle == poison_cycle:
                # the poison specs + the armed point (label-gated: it
                # raises only while a carrier is in the lowered group,
                # which is exactly what lets the bisection converge)
                chaos.arm("solver.poison_batch")
                for tag in ("a", "b"):
                    poison = Pod(
                        meta=ObjectMeta(
                            name=f"poison-{tag}",
                            labels={POISON_LABEL: "1"},
                        ),
                        spec=PodSpec(
                            requests={
                                ext.RES_CPU: POD_CPU,
                                ext.RES_MEMORY: POD_MEM,
                            },
                            priority=9000,
                        ),
                    )
                    poison_uids.add(poison.meta.uid)
                    poison_pods.append(poison)
                    arriving.append(poison)
            if cycle == stall_cycle:
                chaos.arm("informer.silent_stall")
            for _ in range(rng.randint(1, max_arrivals)):
                pod_seq += 1
                arriving.append(
                    Pod(
                        meta=ObjectMeta(name=f"gray-{pod_seq:05d}"),
                        spec=PodSpec(
                            requests={
                                ext.RES_CPU: POD_CPU,
                                ext.RES_MEMORY: POD_MEM,
                            },
                            priority=9000 if pod_seq % 3 else 5500,
                        ),
                    )
                )
            stats["arrived"] += len(arriving)
        pending.extend(arriving)

        if cycle == stall_end:
            # events resume; the suppressed ones are GONE from the watch
            # streams, so recovery is a re-list (disarm FIRST — the
            # background re-list threads must never race an armed point)
            chaos.disarm("informer.silent_stall")
            hub.disconnect()

        if cycle == restart_cycle:
            # kill -9: process state dies; the lease, fence, journal and
            # BOTH containment ledgers survive. The dying incarnation's
            # governor records the death (rapid-death #1); the armed
            # boot_crash kills the next 2 takeover attempts, so the
            # crash-loop governor sees K=3 rapid deaths and imposes
            # backoff + a DEGRADED final boot.
            stats["crash_restarts"] += 1
            gov.note_death(reason="kill -9 (injected process death)")
            hub.detach_consumers()
            # per-incarnation counters die with the registry — fold the
            # dying instance's containment tallies into the soak totals
            for key, metric in _CONTAINMENT_COUNTERS:
                stats[key] += reg.get(metric).value()
            snap, sched, reg, quar, gov, wd = _make_instance()
            hub.health = sched.extender.health
            hub.error_registry = reg
            hub.wire_scheduler(sched)
            hub.start()
            wd.watch_hub(hub)
            wd_ref[0] = wd
            coord = _make_coordinator()
            chaos.arm("scheduler.boot_crash", times=2)

        # ---- election step ----
        was_leading = coord.leading
        leading, _drained = coord.tick()
        if leading and not was_leading:
            stats["takeovers"] += 1
            if cycle > restart_cycle:
                # the governed post-crash-loop takeover: DEGRADED boot
                plan = coord.boot_plan
                stats["degraded_boot"] = bool(plan and plan.degraded)
                stats["degraded_fallback_level"] = sched._fallback_level

        if not leading:
            stats["cycles_without_leader"] += 1
        else:
            fed = list(pending)
            pending = []
            out = sched.schedule(fed)
            if sched._cycle_stale:
                stats["stale_sched_cycles"] += 1
            for pod, node in out.bound:
                assert pod.meta.uid not in placed, (
                    f"pod {pod.meta.name} placed twice: "
                    f"{placed[pod.meta.uid]} then {node}"
                )
                placed[pod.meta.uid] = node
                pod.spec.node_name = node
                hub.publish(hub.pods, pod)
                live.append((pod, node, cycle + LIFETIME))
            stats["placed"] += len(out.bound)
            pending = list(out.unschedulable)

        if cycle == poison_cycle:
            # the whole isolation happened THIS cycle (ladder crash →
            # bisection → blame); the count must freeze here forever
            stats["poison_fires_isolation"] = _poison_fires()
            assert set(quar.entries()) == poison_uids, (
                "bisection blamed the wrong set: "
                f"{set(quar.entries())} != {poison_uids}"
            )

        # ---- completions release capacity through the informer ----
        still = []
        for pod, node, done in live:
            if done <= cycle:
                hub.delete(hub.pods, pod)
                stats["completed"] += 1
            else:
                still.append((pod, node, done))
        live = still

        in_stall = stall_cycle <= cycle < stall_end
        if in_stall:
            # the armed stall suppresses every fan-out: nothing to wait
            # for — the informers are exactly as far as they will get
            hub.wait_synced(timeout=0.05)
        else:
            assert hub.wait_synced()

        # ---- staleness watchdog sweep (virtual clock = cycle) ----
        wd.check(float(cycle))
        if wd.stale():
            stats["stale_cycles"] += 1
            row = sched.extender.health.snapshot().get(
                "snapshot_freshness"
            )
            if row is not None and not row["ok"]:
                stats["freshness_degraded_cycles"] += 1
            if victim_job is None:
                victim_job = mig.submit(
                    victim, MigrationMode.EVICT_DIRECTLY
                )
        if victim_job is not None:
            mig.reconcile(now=float(cycle))

        # ---- per-cycle invariants ----
        want = np.zeros_like(snap.nodes.requested)
        for uid, ap in snap._assumed.items():
            want[ap.node_idx] += ap.request
        np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
        if verbose and cycle % 10 == 0:
            print(
                f"cycle={cycle:3d} pending={len(pending):3d} "
                f"placed={stats['placed']} leader={leading} "
                f"stale={wd.stale()}"
            )

        if (
            cycle >= cycles
            and {p.meta.uid for p in pending} == poison_uids
            and victim_job is not None
            and victim_job.phase == MigrationPhase.SUCCEEDED
        ):
            break

    # ---- end-state assertions ----
    # exactly the poison set quarantined; 100% placement of the rest
    assert {p.meta.uid for p in pending} == poison_uids, (
        f"pending != poison set: {[p.meta.name for p in pending]}"
    )
    assert (
        stats["placed"]
        == stats["arrived"] - len(poison_uids)
        == len(placed)
    )
    # blame ledger: exactly the poison pods, at their CURRENT spec
    # fingerprints (the redeemable-ticket key a fixed spec would change)
    entries = quar.entries()
    assert set(entries) == poison_uids
    for pod in poison_pods:
        assert entries[pod.meta.uid]["fp"] == spec_fingerprint(pod)
    # the successor adopted blame BEFORE replay: every fire happened at
    # the isolation cycle — the kill-restart at restart_cycle (later)
    # re-fed the poison pods and they were gate-rejected, never
    # re-lowered, so the count never moved again
    assert _poison_fires() == stats["poison_fires_isolation"] > 0, (
        "solver.poison_batch fired after isolation — a successor "
        "re-lowered quarantined pods"
    )
    # crash-loop: kill + exactly 2 injected boot crashes = K deaths,
    # backoff recorded, bounded leaderless gap, DEGRADED final boot
    boot_crashes = sum(
        1 for _s, pt, _k in chaos.trace if pt == "scheduler.boot_crash"
    )
    assert boot_crashes == 2, boot_crashes
    assert gov.deaths == K_DEATHS, gov.deaths
    assert stats["takeovers"] >= 2
    assert stats["cycles_without_leader"] <= 10, (
        f"crash-loop governor let the leaderless gap run away: "
        f"{stats['cycles_without_leader']} cycles"
    )
    assert stats["degraded_boot"], "post-crash-loop boot was not DEGRADED"
    assert stats["degraded_fallback_level"] >= 1
    # staleness: the watchdog flipped health, scheduling captured the
    # verdict, the descheduler refused while stale and proceeded after
    assert stats["stale_cycles"] >= 1
    assert stats["freshness_degraded_cycles"] >= 1
    assert stats["stale_sched_cycles"] >= 1
    assert mig.refused_stale >= 1
    assert victim_job is not None
    assert victim_job.phase == MigrationPhase.SUCCEEDED
    assert evictions == [victim.meta.uid]
    assert not wd.stale(), "stall healed but the watchdog still reports stale"
    # resident device state reconverged bit-exactly
    assert_resident_state_converged(sched)
    # capture the ledger BEFORE disarming (fired_counts of a disarmed
    # point vanishes; the trace is the durable record)
    stats["fault_trace"] = list(chaos.trace)
    counts: dict = {}
    for _s, pt, _k in chaos.trace:
        counts[pt] = counts.get(pt, 0) + 1
    stats["faults"] = counts
    chaos.disarm()
    # decision sweep: crashloop records gap-free and recompute-clean
    dec_records = sorted(
        decision_store.load(), key=lambda r: r.get("seq", 0)
    )
    crashloop_recs = [
        r for r in dec_records if r.get("controller") == "crashloop"
    ]
    assert len(crashloop_recs) == K_DEATHS
    assert any(
        r["action"].get("op") == "backoff" for r in crashloop_recs
    ), "K rapid deaths never decided a backoff"
    stats["decision_trace"] = _sweep_decisions(
        dec_records, context="gray-failure soak decisions"
    )
    stats["decisions_total"] = len(dec_records)
    # zero lost acknowledged bindings across the takeover chain
    ha_rep = BindJournal(journal_store).replay()
    lost_acked = [u for u in ha_rep.live if u not in placed]
    assert not lost_acked, (
        f"{len(lost_acked)} journal-acknowledged bindings lost"
    )
    # ledger dumps (live + quarantined sidecars) so the fsck acceptance
    # test round-trips EXACTLY what this soak's stores ended up holding
    stats["quarantine_dump"] = [
        dict(r) for r in quarantine_store.load()
    ] + [dict(r) for r in quarantine_store.quarantined]
    stats["crashloop_dump"] = [dict(r) for r in crash_store.load()] + [
        dict(r) for r in crash_store.quarantined
    ]
    stats["bind_journal_live"] = sorted(ha_rep.live)
    # every subsystem recovers before the health rows freeze (informer
    # re-list backoff is wall-clock on background threads)
    import time as _walltime

    deadline = _walltime.monotonic() + 10.0
    while (
        not sched.extender.health.ok()
        and _walltime.monotonic() < deadline
    ):
        _walltime.sleep(0.05)
    hub.stop()
    stats["health_ok"] = sched.extender.health.ok()
    stats["health_detail"] = {
        k: v
        for k, v in sched.extender.health.snapshot().items()
        if not v["ok"]
    }
    for key, metric in _CONTAINMENT_COUNTERS:
        stats[key] += reg.get(metric).value()
    return stats


# ---------------------------------------------------------------------------
# Multi-shard chaos soak (PR 6: horizontally partitioned control plane)
# ---------------------------------------------------------------------------


def _run_sharded_soak(
    cycles: int,
    seed: int,
    n_nodes: int,
    max_arrivals: int,
    drain_limit: int,
    verbose: bool,
    shards: int,
    incarnations: int,
    shadow: bool = False,
) -> dict:
    """The multi-shard arm of :func:`run_chaos_soak`: N concurrently-live
    fenced scheduler incarnations partition node ownership across S
    shards (per-shard lease + epoch + journal), with rendezvous
    multi-standby election, voluntary shard handoffs, seeded leader
    flaps, one mid-commit chunk crash and one kill-restart mid-schedule
    whose lost-ack window is recovered per shard from the journals.

    Invariants (asserted inside, per cycle or at the end):

    * no pod is ever placed twice — across shards AND incarnations
      (every pump feeds through the single-winner claim table);
    * zero lost acknowledged bindings per shard (each shard journal's
      live set ⊆ the driver's placed ledger, node-exact);
    * quota never exceeded at its HOME shard's ledger;
    * per-owned-runtime snapshot accounting never drifts; resident
      device state bit-exact at every takeover (inside recovery) and at
      the end;
    * deletions on an OWNERLESS shard are journaled fence-exempt by the
      observer (the driver here; a standby in a real deployment) — the
      PR 5 standby-forget rule generalized per shard;
    * same seed ⇒ same fault trace;
    * (fleet-tracing PR) every placed pod has a GAP-FREE lifecycle
      timeline — time-ordered submit→…→ack on the shared sim clock,
      bridged across shard handoffs and the kill-restart by
      handoff/orphan/resubmit/recover events
      (:func:`~koordinator_tpu.obs.lifecycle.validate_timeline`);
    * (fleet-tracing PR) the killed incarnation's crash-surviving
      flight recorder is READABLE after recovery: the shard's new owner
      adopts the dead writer's per-cycle tail from the fabric's store
      and serves it at ``/debug/flightrecorder``;
    * (elastic-topology PR) one shard SPLIT and one MERGE execute under
      live traffic mid-schedule — each preceded by a crash-armed
      attempt (``shard.split_crash`` / ``shard.merge_crash``) that must
      roll back to the parent generation cleanly — with queue
      continuity across the transition, journal live sets re-homed into
      the child shards, claims following their pods, and every
      invariant above (zero-dup, zero-lost-ack, bit-exact resident
      state, gap-free timelines) green across the topology epoch bumps.
    """
    import json
    import random as _random

    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        ElasticQuota,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.chaos import FaultInjector
    from koordinator_tpu.core.journal import BindJournal
    from koordinator_tpu.obs.lifecycle import PodLifecycle, validate_timeline
    from koordinator_tpu.obs.slo import SloTracker
    from koordinator_tpu.runtime.shards import (
        ShardFabric,
        ShardRouter,
        ShardedScheduler,
    )
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )

    assert incarnations >= 2 and shards >= 2
    ALLOC_CPU, ALLOC_MEM = 32_000.0, 128 * 1024.0
    POD_CPU, POD_MEM = 2_000.0, 4_096.0
    LIFETIME = 6
    MAX_BATCH = 16
    rng = _random.Random(seed)
    rng_ha = _random.Random(seed ^ 0x51F15EED)

    chaos = FaultInjector(seed=seed)
    sim_cycle = [0]

    def _clock() -> float:
        return float(sim_cycle[0])

    fabric = ShardFabric(shards, clock=_clock, membership_ttl_s=2.5)
    # fleet-wide pod-lifecycle tracker + per-shard SLO tracker, both on
    # the SIM clock (one time domain end to end ⇒ deterministic
    # timelines/samples under the same seed); shared across every
    # incarnation, like the fabric — the timeline view is the FLEET's,
    # not any single process's
    lifecycle = PodLifecycle(clock=_clock)
    slo = SloTracker(clock=_clock)
    hub = ClusterStateHub(chaos=chaos)
    node_names = [f"n{i:03d}" for i in range(n_nodes)]
    for name in node_names:
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: ALLOC_CPU,
                        ext.RES_MEMORY: ALLOC_MEM,
                    }
                ),
            ),
        )
    # every shard must own at least one node, else its owner's recovery
    # has no world to verify against — with hashed partitioning this is
    # a property of (names, S); assert it up front so a failure is loud
    part = fabric.shard_map.partition(node_names)
    assert all(part[s] for s in range(shards)), (
        f"shard partition left an empty shard: "
        f"{ {s: len(v) for s, v in part.items()} }"
    )

    q_pods = max(6, (2 * max_arrivals * LIFETIME) // 5)
    quota_max = {
        ext.RES_CPU: q_pods * POD_CPU,
        ext.RES_MEMORY: q_pods * POD_MEM,
    }
    quota_min = {ext.RES_CPU: 2 * POD_CPU, ext.RES_MEMORY: 2 * POD_MEM}
    hub.publish(
        hub.quotas,
        ElasticQuota(
            meta=ObjectMeta(name="soak-team"),
            min=dict(quota_min),
            max=dict(quota_max),
        ),
    )
    home_shard = fabric.shard_map.shard_of_key("quota:soak-team")

    def make_scheduler(shard, snapshot, fence, journal):
        gqm = GroupQuotaManager(snapshot.config, enable_preemption=False)
        s = BatchScheduler(
            snapshot,
            LoadAwareArgs(usage_thresholds={}),
            quotas=gqm,
            batch_bucket=MAX_BATCH,
            chaos=chaos,
            journal=journal,
            fence=fence,
            # state-integrity PR: per-shard anti-entropy audit (the
            # resident.bit_flip arm below rides whichever shard's
            # cycle-tail scrub evaluates it first — deterministically)
            scrub_rows=8,
        )
        s.extender.monitor.stop_background()
        chaos.bind_counter(s.extender.registry.get("fault_injected_total"))
        return s

    def _make_incarnation(idx: int, gen: int) -> ShardedScheduler:
        return ShardedScheduler(
            f"inc{idx}-gen{gen}",
            hub,
            fabric,
            make_scheduler,
            pipelined=True,
            max_batch=MAX_BATCH,
            max_retries=8,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            chaos=chaos,
            lifecycle=lifecycle,
            slo=slo,
            flight_capacity=64,
        )

    # decision observatory (decision-observatory PR): the runtimes'
    # per-shard DecisionLedgers live over fabric.decision_stores (the
    # ShardedScheduler default), so a takeover adopts the dead owner's
    # decision tail exactly like the journal and the flight recorder —
    # swept gap-free + recompute-clean at the end. ``shadow=True`` is
    # the bit-exactness arm: an always-diverging shadow consults on
    # every depth record without ever acting (attached opportunistically
    # per cycle — runtimes are born on takeover; attach_shadow is
    # first-wins-idempotent per ledger).
    shadow_registry = None
    if shadow:
        from koordinator_tpu.obs.shadow import (
            AlwaysDivergeShadow,
            ShadowRegistry,
        )

        shadow_registry = ShadowRegistry()
        shadow_registry.attach("depth", AlwaysDivergeShadow())

    incs = [_make_incarnation(i, 0) for i in range(incarnations)]
    # elastic-topology PR: the controller that executes the scheduled
    # split/merge transactions (fixed cycles below — the burn-DRIVEN
    # policy path has its own deterministic unit tests; the soak's job
    # is the transactional invariants under full chaos load)
    from koordinator_tpu.runtime.elastic import TopologyController

    topo_ctrl = TopologyController(
        fabric,
        slo=slo,
        incarnations=lambda: [i for i in incs if not i.dead],
        node_names=lambda: list(node_names),
        chaos=chaos,
        lifecycle=lifecycle,
    )
    # leak-detector arm (devprof PR): live device arrays sampled at each
    # incarnation boundary — a killed incarnation's resident tables must
    # actually die; monotone growth across the samples fails the soak
    from koordinator_tpu.obs.devprof import LeakSentinel

    leaks = LeakSentinel(tolerance_bytes=4 << 20)
    leaks.sample("gen0-built")
    # everyone heartbeats BEFORE the first election step so the initial
    # rendezvous ranking sees the full membership (otherwise the first
    # ticker grabs every shard and immediately hands most back)
    for inc in incs:
        fabric.membership.heartbeat(inc.name)
    router = ShardRouter(fabric.shard_map, lifecycle=lifecycle)

    stats = {
        "cycles": 0,
        "arrived": 0,
        "placed": 0,
        "completed": 0,
        "takeovers": 0,
        "handoffs": 0,
        "claims_lost": 0,
        "crash_restarts": 0,
        "recovered_bindings": 0,
        "driver_forgets": 0,
        "shard_cycles_without_owner": 0,
        "timelines_validated": 0,
        "flight_recovered_records": 0,
        "scrub_divergence": {},
        "recovery_used_checkpoint": 0,
        "checkpoint_fallbacks": 0,
        "faults": {},
    }
    #: (inc name, shard) -> last folded RecoveryReport. Folded PER CYCLE
    #: because a topology transition deletes a retired shard's
    #: coordinator (and with it the report a one-shot end sweep would
    #: need); strong refs keep object identity stable
    seen_recovery: dict = {}

    def _fold_recoveries() -> None:
        for inc in incs:
            if inc.dead:
                continue
            for s in inc.owned():
                rec = inc.last_recovery(s)
                if rec is None or seen_recovery.get((inc.name, s)) is rec:
                    continue
                seen_recovery[(inc.name, s)] = rec
                stats["recovery_used_checkpoint"] += int(
                    rec.used_checkpoint
                )
                stats["checkpoint_fallbacks"] += int(
                    rec.checkpoint_fallback
                )

    #: (inc name, shard) -> divergence totals already folded (reports
    #: are cumulative per scheduler and die with their runtime — a kill
    #: OR a topology retirement — so folding is per-cycle, delta-wise)
    seen_scrub: dict = {}

    def _fold_scrub(inc) -> None:
        """Fold an incarnation's per-shard anti-entropy ledgers into the
        run stats, delta-wise against what was already folded."""
        for s in inc.owned():
            rt = inc.runtime(s)
            if rt is None:
                continue
            cur = rt.sched._scrub_report["divergence"]
            prev = seen_scrub.get((inc.name, s), {})
            for table, n in cur.items():
                delta = int(n) - int(prev.get(table, 0))
                if delta > 0:
                    stats["scrub_divergence"][table] = (
                        stats["scrub_divergence"].get(table, 0) + delta
                    )
            seen_scrub[(inc.name, s)] = dict(cur)
    #: flight-recorder readability check state: the shards the killed
    #: incarnation owned, pending a new owner whose adopted recorder
    #: must serve the dead writer's records
    doomed_name: Optional[str] = None
    doomed_flight_shards: set = set()
    placed: dict = {}          # uid -> node, forever (duplicate guard)
    pod_by_uid: dict = {}
    live: list = []            # (pod, node, done_cycle)
    pending: list = []         # fresh/unrouted pods
    pending_handoff: list = [] # (shard, pod, arrival, tries)
    inflight: dict = {}        # uid -> (pod, shard, inc_name)
    orphans: list = []         # (pod, shard) from the kill
    pod_seq = 0
    crash_cycle = max(2, cycles // 3)
    restart_cycle = max(6, (3 * cycles) // 5)
    # state-integrity PR (corruption fault domain, fixed cycles — no
    # rng draws): one resident bit flip for the per-shard scrubbers,
    # one mid-stream corrupt record + one seq write hole on whichever
    # shard journal appends next (deterministic pump order), and a
    # checkpoint recovery image per owned shard whose digest the
    # post-kill takeover must reject (full-replay fallback)
    bit_flip_cycle = max(2, (3 * cycles) // 8)
    corrupt_record_cycle = max(3, (4 * cycles) // 9)
    seq_gap_cycle = max(4, (5 * cycles) // 11)
    checkpoint_cycle = restart_cycle - 1
    # elastic-topology schedule (fixed cycles — no rng draws, so every
    # historical seeded fault trace stays bit-identical): a crash-armed
    # split attempt that must ROLL BACK, the real split two cycles
    # later, then the same pattern for the merge of the new siblings
    split_crash_cycle = max(3, cycles // 6)
    split_cycle = split_crash_cycle + 2
    merge_crash_cycle = max(split_cycle + 3, (7 * cycles) // 10)
    merge_cycle = merge_crash_cycle + 2
    quota_max_vec = None

    def _owner_of(shard: int):
        for inc in incs:
            if not inc.dead and inc.owns(shard):
                return inc
        return None

    def _place(pod, node, shard):
        assert pod.meta.uid not in placed, (
            f"pod {pod.meta.name} placed twice: "
            f"{placed[pod.meta.uid]} then {node} (shard {shard})"
        )
        # shard-correctness: the binding must land inside the shard's
        # CELL RANGE — a cross-range bind would mean the fencing/claim
        # machinery let a foreign owner mutate this partition.
        # cell_covers (not shard_of_node equality) because a donor's
        # drained decision can absorb AFTER a split committed: the node
        # now routes to a child, but the parent legitimately owned the
        # range when it decided
        assert fabric.shard_map.cell_covers(shard, node), (
            f"{pod.meta.name} bound on {node} by shard {shard}"
        )
        placed[pod.meta.uid] = node
        pod.spec.node_name = node
        hub.publish(hub.pods, pod)
        live.append((pod, node, sim_cycle[0] + LIFETIME))
        stats["placed"] += 1

    def _absorb_decided(inc, decided, acknowledged: bool = True):
        for shard, pod, node, _lat in decided:
            inflight.pop(pod.meta.uid, None)
            if pod.meta.uid in gang_tickets:
                # cross-shard gang member: the decision feeds the
                # two-phase ticket; the LEDGER is written only at
                # commit (all-or-nothing), never per member
                _note_gang(pod, node, shard)
                continue
            if not acknowledged:
                # the lost-ack window: the bind record is journaled but
                # the process died before the bind API write went out —
                # the takeover's replay must recover it, never re-place
                orphans.append((pod, shard))
                continue
            if node is not None:
                _place(pod, node, shard)
            else:
                # terminally unschedulable: re-enter the backlog (the
                # soak's contract is eventual placement; capacity always
                # frees as pods complete)
                pending.append(pod)

    def _absorb_handoffs(inc, handoffs):
        for shard, hand in sorted(handoffs.items()):
            stats["handoffs"] += 1
            for pod, node, _lat in hand.decided:
                inflight.pop(pod.meta.uid, None)
                if pod.meta.uid in gang_tickets:
                    _note_gang(pod, node, shard)
                elif node is not None:
                    _place(pod, node, shard)
                else:
                    pending.append(pod)
            for pod, arr, tries in hand.queued:
                inflight.pop(pod.meta.uid, None)
                pending_handoff.append((shard, pod, arr, tries))

    # ---- cross-shard gang arm (overload-control PR satellite): the
    # two-phase commit/abort path runs INSIDE the soak's placed-once
    # ledger — a committed gang lands in `placed` all-or-nothing, an
    # aborted gang's members must come back fully CLAIMABLE (no
    # tombstone, no zombie hold) and re-place exactly once as plain
    # pods, never duplicating and never getting lost. ----
    from koordinator_tpu.runtime.elastic import CrossShardGangCoordinator

    xs_coord = CrossShardGangCoordinator(
        fabric, router, _owner_of, lifecycle=lifecycle
    )
    gang_tickets: dict = {}   # member uid -> live ticket
    gang_nodes: dict = {}     # member uid -> (shard, node), pre-commit
    xs_stats = {"committed": 0, "aborted": 0, "abort_resubmitted": 0}
    gang_seq = [0]

    def _xs_gang_pods(tag: str, doom: bool):
        """Three members pinned across the two largest OWNED shards —
        the span the gang-home router cannot place. ``doom`` makes the
        third member infeasible (larger than any node) so the gang must
        abort once its retries exhaust."""
        part = fabric.shard_map.partition(list(node_names))
        owned_cells = [
            s
            for s in sorted(part, key=lambda s: (-len(part[s]), s))
            if part[s] and _owner_of(s) is not None
        ]
        if len(owned_cells) < 2 or len(part[owned_cells[0]]) < 2:
            return None
        sa, sb = owned_cells[0], owned_cells[1]
        gang_seq[0] += 1
        pods = []
        pins = [
            (part[sa][0], POD_CPU),
            (part[sa][1], POD_CPU),
            (part[sb][0], 2 * ALLOC_CPU if doom else POD_CPU),
        ]
        for i, (node, cpu) in enumerate(pins):
            pod = Pod(
                meta=ObjectMeta(
                    name=f"xsg-{tag}{gang_seq[0]}-m{i}",
                    annotations={
                        ext.ANNOTATION_GANG_NAME: f"{tag}{gang_seq[0]}",
                        ext.ANNOTATION_GANG_MIN_AVAILABLE: "3",
                        ext.ANNOTATION_GANG_TOTAL_NUM: "3",
                    },
                ),
                spec=PodSpec(
                    node_name=node,
                    requests={ext.RES_CPU: cpu, ext.RES_MEMORY: POD_MEM},
                    priority=9000,
                ),
            )
            pods.append(pod)
        return pods

    def _begin_xs_gang(tag: str, doom: bool) -> bool:
        pods = _xs_gang_pods(tag, doom)
        if pods is None:
            return False
        ticket = xs_coord.begin(pods)
        if ticket is None:
            # an ownerless member shard mid-chaos refused the attempt
            # with zero holds — retry a later cycle
            return False
        stats["arrived"] += len(pods)
        for p in pods:
            pod_by_uid[p.meta.uid] = p
            gang_tickets[p.meta.uid] = ticket
        return True

    def _note_gang(pod, node, shard) -> None:
        uid = pod.meta.uid
        ticket = gang_tickets[uid]
        if node is not None:
            gang_nodes[uid] = (shard, node)
            pod.spec.node_name = node
            hub.publish(hub.pods, pod)
        verdict = xs_coord.note(ticket, uid, node)
        if verdict is not None:
            _finish_gang(ticket)

    def _finish_gang(ticket) -> None:
        def _unbind(pod, shard, node):
            # the driver's bind-API delete: releases snapshot/journal
            # charges through the ordinary informer fan-out
            hub.delete(hub.pods, pod)
            pod.spec.node_name = None
            gang_nodes.pop(pod.meta.uid, None)

        committed = xs_coord.finish(ticket, unbind=_unbind)
        for uid in ticket.members:
            gang_tickets.pop(uid, None)
        if committed:
            xs_stats["committed"] += 1
            for uid in sorted(ticket.members):
                shard, node = gang_nodes.pop(uid)
                _place(ticket.pods[uid], node, shard)
        else:
            xs_stats["aborted"] += 1
            # LEDGER integration: aborted members are CLAIMABLE — no
            # winner, no tombstone, no residual hold — and re-enter the
            # ordinary flow as rightsized plain pods
            assert fabric.claims.gang_holds(ticket.attempt_id) == 0
            for uid, pod in sorted(ticket.pods.items()):
                assert fabric.claims.winner(uid) is None, (
                    f"aborted gang member {uid} left a claim winner"
                )
                assert uid not in placed, (
                    f"aborted gang member {uid} leaked into the ledger"
                )
                gang_nodes.pop(uid, None)
                for key in (
                    ext.ANNOTATION_GANG_NAME,
                    ext.ANNOTATION_GANG_MIN_AVAILABLE,
                    ext.ANNOTATION_GANG_TOTAL_NUM,
                ):
                    pod.meta.annotations.pop(key, None)
                try:
                    del pod._gang_key
                except AttributeError:
                    pass
                pod.spec.node_name = None
                pod.spec.requests = {
                    ext.RES_CPU: POD_CPU,
                    ext.RES_MEMORY: POD_MEM,
                }
                pending.append(pod)
                xs_stats["abort_resubmitted"] += 1

    total_cycles = cycles + drain_limit
    for cycle in range(total_cycles):
        sim_cycle[0] = cycle
        stats["cycles"] += 1

        # ---- seeded fault schedule (stops at `cycles`; drain is clean) ----
        doomed = None
        if cycle < cycles:
            if rng_ha.random() < 0.05:
                chaos.arm("leader.lost", times=1)      # per-shard flap
            if cycle == crash_cycle:
                chaos.arm("commit.crash", error=RuntimeError, times=1)
            if cycle == bit_flip_cycle:
                chaos.arm("resident.bit_flip", times=1)
            if cycle == corrupt_record_cycle:
                chaos.arm("journal.corrupt_record", times=1)
            if cycle == seq_gap_cycle:
                chaos.arm("journal.seq_gap", times=1)
            if cycle == checkpoint_cycle:
                # one checkpoint recovery image per OWNED shard (via
                # the owner's own journal instance — seq-consistent);
                # the digest mismatch armed with the kill below forces
                # the first checkpoint-bearing takeover recovery to
                # fall back to the full-history replay
                for inc in incs:
                    if inc.dead:
                        continue
                    for s in inc.owned():
                        rt = inc.runtime(s)
                        if rt is not None and rt.sched.bind_journal is not None:
                            rt.sched.bind_journal.append_checkpoint(
                                epoch=rt.sched._fence_epoch
                            )
            if cycle == restart_cycle:
                # the incarnation owning the most shards dies THIS cycle,
                # right after its pumps journaled their trailing commits
                alive = [i for i in incs if not i.dead]
                doomed = max(
                    alive, key=lambda i: (len(i.owned()), i.name)
                )
                # armed WITH the kill: the first checkpoint-bearing
                # takeover recovery rejects its image and falls back
                chaos.arm("checkpoint.digest_mismatch", times=1)

        # ---- elastic topology schedule (elastic-topology PR): a split
        # and a merge under LIVE traffic, each preceded by a crash-armed
        # attempt whose rollback must leave the parent generation
        # serving (never a half-owned range). The donor's surfaced
        # queue rides the ordinary handoff path and re-routes against
        # whatever topology the transaction settled on. ----
        if cycle < cycles:
            if cycle == split_crash_cycle:
                target = topo_ctrl.pick_split_candidate()
                if target is not None:
                    chaos.arm("shard.split_crash", times=1)
                    assert topo_ctrl.split(target, cycle=cycle) is None, (
                        "crash-armed split must roll back"
                    )
                    assert fabric.topology.open_transition() is None
                    assert fabric.shard_map.is_active(target), (
                        "rolled-back split must keep the parent active"
                    )
            if cycle == split_cycle:
                target = topo_ctrl.pick_split_candidate()
                if target is not None:
                    out = topo_ctrl.split(target, cycle=cycle)
                    assert out is not None, "scheduled split failed"
            if cycle == merge_crash_cycle and fabric.shard_map.siblings():
                a_s, b_s = fabric.shard_map.siblings()[0]
                chaos.arm("shard.merge_crash", times=1)
                assert topo_ctrl.merge(a_s, b_s, cycle=cycle) is None, (
                    "crash-armed merge must roll back"
                )
                assert fabric.shard_map.is_active(a_s)
                assert fabric.shard_map.is_active(b_s)
            if cycle == merge_cycle and fabric.shard_map.siblings():
                a_s, b_s = fabric.shard_map.siblings()[0]
                out = topo_ctrl.merge(a_s, b_s, cycle=cycle)
                assert out is not None, "scheduled merge failed"

        # ---- cross-shard gang schedule (overload-control PR
        # satellite): one gang that must COMMIT through the ledger and
        # one doomed gang that must ABORT with claimable members — each
        # begun once two owned shards exist, retried on chaos refusal,
        # one ticket in flight at a time ----
        if cycle < cycles and not gang_tickets and cycle >= split_cycle + 2:
            if xs_stats["committed"] == 0:
                _begin_xs_gang("xc", doom=False)
            elif (
                xs_stats["aborted"] == 0 and cycle >= split_cycle + 4
            ):
                _begin_xs_gang("xa", doom=True)

        # ---- arrivals ----
        arriving = []
        if cycle < cycles:
            n_arr = rng.randint(1, max_arrivals)
            if cycle == restart_cycle - 1:
                # surge: the doomed incarnation's trailing commit at the
                # kill cycle must carry real binds (the lost-ack window)
                n_arr += 3 * MAX_BATCH
            for _ in range(n_arr):
                pod_seq += 1
                labels = {}
                if pod_seq % 5 == 0:
                    labels[ext.LABEL_QUOTA_NAME] = "soak-team"
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"soak-{pod_seq:05d}", labels=labels
                    ),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: POD_CPU,
                            ext.RES_MEMORY: POD_MEM,
                        },
                        priority=9000 if pod_seq % 3 else 5500,
                    ),
                )
                arriving.append(pod)
                pod_by_uid[pod.meta.uid] = pod
            stats["arrived"] += len(arriving)
        pending.extend(arriving)

        # ---- election step on every live incarnation ----
        for inc in incs:
            if inc.dead:
                continue
            _absorb_handoffs(inc, inc.tick())

        # ---- flight-recorder readability (after the kill): the shard's
        # new owner adopted the DEAD incarnation's per-cycle tail from
        # the fabric's store at runtime build — assert it actually
        # serves those records, promptly (the adopted records age out of
        # the live owner's bounded ring as it keeps recording) ----
        if doomed_flight_shards:
            for s in sorted(doomed_flight_shards):
                if not fabric.shard_map.is_active(s):
                    # the shard was merged/split away before a takeover
                    # could serve the dead writer's tail — the records
                    # live on in the fabric store, but there is no
                    # owner surface left to assert against
                    doomed_flight_shards.discard(s)
                    continue
                owner = _owner_of(s)
                rt = owner.runtime(s) if owner is not None else None
                if rt is None or rt.sched.flight_recorder is None:
                    continue
                dead_in_store = any(
                    r.get("incarnation") == doomed_name
                    for r in fabric.flight_stores[s].load()
                )
                doomed_flight_shards.discard(s)
                if not dead_in_store:
                    continue  # the dead owner never cycled this shard
                code, body = owner.fleet().dispatch(
                    "GET", "/debug/flightrecorder"
                )
                assert code == 200
                served = json.loads(body)["shards"][str(s)]
                assert served["recovered"] > 0, (
                    f"shard {s}: takeover {owner.name} does not serve "
                    f"dead incarnation {doomed_name}'s flight records"
                )
                assert any(
                    r["incarnation"] == doomed_name
                    for r in served["records"]
                    if r["recovered"]
                )
                stats["flight_recovered_records"] += served["recovered"]

        # ---- orphan reconciliation (after the kill): an ACKNOWLEDGED
        # (journaled) binding is recovered from the shard's takeover
        # replay — never re-placed; the rest re-enter the shard's queue
        if orphans:
            still_orphaned = []
            for pod, shard in orphans:
                if pod.meta.uid in placed:
                    continue
                # a topology transition may have retired the orphan's
                # shard mid-reconciliation: its journal live set was
                # re-homed, so the binding (if acknowledged) is in a
                # SUCCESSOR's recovery — check whichever successors
                # have owners, defer while any is still ownerless
                cand_shards = fabric.shard_map.successors(shard)
                owners = [
                    (s, _owner_of(s)) for s in (cand_shards or [shard])
                ]
                if any(o is None for _s, o in owners):
                    still_orphaned.append((pod, shard))
                    continue
                node = None
                hit_shard = shard
                for s, owner in owners:
                    rec = owner.last_recovery(s)
                    bindings = rec.bindings if rec is not None else {}
                    node = bindings.get(pod.meta.uid)
                    if node is not None:
                        hit_shard = s
                        break
                if node is not None and pod.meta.uid in gang_tickets:
                    # a gang member's journaled bind recovered from the
                    # kill: the decision feeds the TICKET (commit writes
                    # the ledger), and the replay's recover event gets
                    # its ack bracket like any recovered binding
                    if not lifecycle.is_done(pod.meta.uid):
                        lifecycle.acked(pod.meta.uid, hit_shard, node)
                    _note_gang(pod, node, hit_shard)
                    stats["recovered_bindings"] += 1
                    continue
                if node is not None:
                    shard = hit_shard
                    _place(pod, node, shard)
                    # the replay emitted ``recover``; the driver (the
                    # bind-API observer here) publishing the recovered
                    # binding IS the acknowledgement — unless the dead
                    # owner's pump already acked it in the lost-ack
                    # window (the timeline is terminal; replay bridged
                    # nothing and no second ack is due)
                    if not lifecycle.is_done(pod.meta.uid):
                        lifecycle.acked(pod.meta.uid, shard, node)
                    stats["recovered_bindings"] += 1
                else:
                    pending_handoff.append((shard, pod, float(cycle), 0))
            orphans = still_orphaned

        # ---- routing: handoff pods back to their shard's new owner,
        # fresh pods to their routed shard; ownerless shards defer ----
        still_handoff = []
        for shard, pod, arr, tries in pending_handoff:
            if not fabric.shard_map.is_active(shard):
                # the shard retired under the pod (split/merge commit):
                # re-route against the live topology, stamps intact —
                # the route event is the timeline's bridge anchor
                shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is not None and owner.resubmit(shard, pod, arr, tries):
                inflight[pod.meta.uid] = (pod, shard, owner.name)
            else:
                still_handoff.append((shard, pod, arr, tries))
        pending_handoff = still_handoff
        still_pending = []
        for pod in pending:
            shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is not None and owner.submit(
                shard, pod, now=float(cycle)
            ):
                inflight[pod.meta.uid] = (pod, shard, owner.name)
            else:
                still_pending.append(pod)
        pending = still_pending
        for s in fabric.shard_map.active_shards():
            if _owner_of(s) is None:
                stats["shard_cycles_without_owner"] += 1

        # ---- pump every owned shard on every live incarnation ----
        for inc in incs:
            if inc.dead:
                continue
            decided = inc.pump()
            _absorb_decided(
                inc, decided, acknowledged=(inc is not doomed)
            )

        # ---- the kill-restart: state dies, leases lapse, a fresh
        # generation joins and the rendezvous ranking rebalances ----
        if doomed is not None:
            stats["crash_restarts"] += 1
            _fold_scrub(doomed)   # its audit ledgers die with it
            # flight-recorder readability check state: the takeover
            # owners of these shards must serve THIS incarnation's
            # per-cycle tail after recovery (checked promptly below —
            # the adopted records age out of a live owner's ring)
            doomed_name = doomed.name
            doomed_flight_shards = set(doomed.owned())
            for shard, pod in doomed.kill():
                inflight.pop(pod.meta.uid, None)
                orphans.append((pod, shard))
            # pods fed into the dead pipelines (decided by nobody now)
            for uid, (pod, shard, inc_name) in list(inflight.items()):
                if inc_name == doomed.name:
                    inflight.pop(uid)
                    orphans.append((pod, shard))
                    # the queue-side orphans were stamped by kill();
                    # pipeline-inflight pods die without a queue to be
                    # extracted from — bracket the dead incarnation here
                    lifecycle.event(
                        uid, "orphan", shard=shard, detail=doomed.name
                    )
            # fold the doomed incarnation's counters into the run ledger
            # NOW — the end-of-run sweep only sees survivors, and the
            # doomed instance is by construction the one that performed
            # the most initial takeovers
            stats["takeovers"] += doomed.stats["takeovers"]
            stats["claims_lost"] += doomed.stats["claims_lost"]
            idx = incs.index(doomed)
            incs[idx] = _make_incarnation(idx, gen=1)
            # incarnation boundary: the dead incarnation's per-shard
            # resident state must be collectable now (leak-detector arm)
            leaks.sample("post-kill")

        # ---- completions release through the informer fan-out; on an
        # OWNERLESS shard the driver journals the forget fence-exempt
        # (the PR 5 standby-forget rule, per shard) ----
        stillliving = []
        for pod, node, done in live:
            if done <= cycle:
                hub.delete(hub.pods, pod)
                shard = fabric.shard_map.shard_of_node(node)
                if _owner_of(shard) is None:
                    # a FRESH journal view per forget is deliberate, not
                    # waste: its load picks up the interleaved owner
                    # journals' seq high, so this forget sorts AFTER the
                    # bind it releases in replay (a cached view's stale
                    # seq would resurrect the pod). Ownerless-gap
                    # forgets are rare; O(load) here is fine.
                    BindJournal(
                        fabric.journal_stores[shard], shard=shard
                    ).append_forget(None, cycle, [pod.meta.uid])
                    stats["driver_forgets"] += 1
                fabric.claims.release(pod.meta.uid)
                stats["completed"] += 1
            else:
                stillliving.append((pod, node, done))
        live = stillliving
        assert hub.wait_synced()

        # ---- per-cycle invariants over every live runtime ----
        _fold_recoveries()
        for inc in incs:
            if not inc.dead:
                _fold_scrub(inc)
        for inc in incs:
            if inc.dead:
                continue
            for s in inc.owned():
                rt = inc.runtime(s)
                if rt is None:
                    continue
                if (
                    shadow_registry is not None
                    and rt.sched.decision_ledger is not None
                ):
                    rt.sched.decision_ledger.attach_shadow(
                        shadow_registry
                    )
                snap = rt.sched.snapshot
                want = np.zeros_like(snap.nodes.requested)
                for uid, ap in snap._assumed.items():
                    want[ap.node_idx] += ap.request
                np.testing.assert_allclose(
                    snap.nodes.requested, want, atol=1e-3
                )
        # the quota HOME moves with the topology: a split of the home
        # shard re-homes the ledger to the child now covering the key
        home_shard = fabric.shard_map.shard_of_key("quota:soak-team")
        home_owner = _owner_of(home_shard)
        if home_owner is not None:
            rt = home_owner.runtime(home_shard)
            gqm = rt.sched.quotas
            qi = gqm.index_of("soak-team")
            if qi is not None and qi < gqm.used.shape[0]:
                if quota_max_vec is None:
                    quota_max_vec = rt.sched.snapshot.config.res_vector(
                        quota_max
                    )
                assert np.all(gqm.used[qi] <= quota_max_vec + 1e-3), (
                    gqm.used[qi],
                    quota_max_vec,
                )

        if verbose and cycle % 10 == 0:
            owned = {
                inc.name: inc.owned() for inc in incs if not inc.dead
            }
            print(
                f"cycle={cycle:4d} pending={len(pending):3d} "
                f"inflight={len(inflight):3d} placed={stats['placed']} "
                f"owned={owned}"
            )

        if (
            cycle >= cycles
            and not pending
            and not pending_handoff
            and not inflight
            and not orphans
            and not gang_tickets
        ):
            break

    # ---- drain every pipeline tail ----
    for inc in incs:
        if inc.dead:
            continue
        _absorb_decided(inc, inc.flush())
    # a final routed pass for anything a flush returned unschedulable
    for _ in range(drain_limit):
        if (
            not pending
            and not pending_handoff
            and not inflight
            and not gang_tickets
        ):
            break
        sim_cycle[0] += 1
        for inc in incs:
            if not inc.dead:
                _absorb_handoffs(inc, inc.tick())
        still = []
        for pod in pending:
            shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is not None and owner.submit(
                shard, pod, now=float(sim_cycle[0])
            ):
                inflight[pod.meta.uid] = (pod, shard, owner.name)
            else:
                still.append(pod)
        pending = still
        still_handoff = []
        for shard, pod, arr, tries in pending_handoff:
            if not fabric.shard_map.is_active(shard):
                shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is not None and owner.resubmit(shard, pod, arr, tries):
                inflight[pod.meta.uid] = (pod, shard, owner.name)
            else:
                still_handoff.append((shard, pod, arr, tries))
        pending_handoff = still_handoff
        for inc in incs:
            if not inc.dead:
                _absorb_decided(inc, inc.pump())
    for inc in incs:
        if not inc.dead:
            _absorb_decided(inc, inc.flush())

    # ---- end-state assertions ----
    assert not pending and not pending_handoff and not inflight, (
        f"{len(pending)} pending / {len(pending_handoff)} handoff / "
        f"{len(inflight)} inflight pods never placed"
    )
    assert stats["placed"] == stats["arrived"] == len(placed)
    # cross-shard gang arm (overload-control PR satellite): the commit
    # path landed the gang in the ledger all-or-nothing, and at least
    # one abort returned its members claimable (re-placed above — they
    # are inside the placed==arrived accounting, never lost/duplicated)
    assert not gang_tickets, f"gang tickets never settled: {gang_tickets}"
    assert xs_stats["committed"] >= 1, xs_stats
    assert xs_stats["aborted"] >= 1, xs_stats
    assert fabric.claims.gang_holds() == 0, "zombie gang holds remain"
    stats["xs_gangs"] = dict(xs_stats)
    # zero lost acknowledged bindings, PER SHARD: every journal-live
    # bind (acked binds minus forgets, across every incarnation that
    # ever owned the shard) landed in the placed ledger on ITS node.
    # EVERY journal store ever minted is checked — retired donors'
    # stores included (their live sets were re-homed, so the same entry
    # also appears in a child journal; both must agree with `placed`)
    for s in sorted(fabric.journal_stores):
        rep = BindJournal(fabric.journal_stores[s]).replay()
        for uid, entry in rep.live.items():
            assert uid in placed, (
                f"shard {s}: journal-acknowledged binding {uid} lost"
            )
            assert placed[uid] == entry.get("node"), (
                f"shard {s}: {uid} journaled on {entry.get('node')} "
                f"but placed on {placed[uid]}"
            )
    # state-integrity PR: the corruption arms fired and were CONTAINED
    # per shard — the corrupt record quarantined (the zero-lost-ack
    # sweep above ran THROUGH it), the write hole counted, the doomed
    # takeover's recovery rejected its checkpoint image and fell back
    # to full replay, and a per-shard scrubber healed the bit flip
    stats["journal_corrupt_quarantined"] = sum(
        st.integrity_total.corrupt
        for st in fabric.journal_stores.values()
    )
    stats["journal_seq_gaps"] = sum(
        st.integrity_total.seq_gaps
        for st in fabric.journal_stores.values()
    )
    if cycles > corrupt_record_cycle:
        assert stats["journal_corrupt_quarantined"] >= 1, (
            "journal.corrupt_record armed but nothing was quarantined"
        )
    if cycles > seq_gap_cycle:
        assert stats["journal_seq_gaps"] >= 1, (
            "journal.seq_gap armed but no write hole was detected"
        )
    _fold_recoveries()
    for inc in incs:
        if not inc.dead:
            _fold_scrub(inc)
    if cycles > restart_cycle:
        assert stats["checkpoint_fallbacks"] >= 1, (
            "checkpoint.digest_mismatch armed but no recovery fell back"
        )
    # (fleet-tracing PR) GAP-FREE lifecycle timelines: every placed pod's
    # events are time-ordered on the sim clock, start at submit, end
    # terminal, and every shard/incarnation transition is bracketed by
    # handoff/orphan/resubmit/recover events — the distributed-tracing
    # invariant that survives the kill-restart and every rebalancing
    # handoff above
    bad_timelines = []
    for uid in placed:
        evs = lifecycle.timeline(uid)
        problems = validate_timeline(evs)
        if problems:
            bad_timelines.append(
                (pod_by_uid[uid].meta.name, problems,
                 [e.to_dict() for e in evs])
            )
        else:
            stats["timelines_validated"] += 1
    assert not bad_timelines, (
        f"{len(bad_timelines)} placed pods have gap-ful lifecycle "
        f"timelines; first 3: {bad_timelines[:3]}"
    )
    assert stats["timelines_validated"] == len(placed)
    # (fleet-tracing PR) the killed incarnation's flight recorder was
    # readable after recovery on at least one of its shards (the
    # per-shard readability assert ran promptly post-takeover above)
    if doomed_name is not None:
        assert stats["flight_recovered_records"] > 0, (
            f"no takeover served dead incarnation {doomed_name}'s "
            "flight-recorder tail"
        )
    # per-shard resident state reconverged bit-exactly on every LIVE
    # owner (takeover-time bit-exactness was asserted inside recovery)
    for inc in incs:
        if inc.dead:
            continue
        for s in inc.owned():
            rt = inc.runtime(s)
            if rt is not None:
                assert_resident_state_converged(rt.sched)
        stats["takeovers"] += inc.stats["takeovers"]
        stats["claims_lost"] += inc.stats["claims_lost"]
    stats["faults"] = chaos.fired_counts()
    stats["fault_trace"] = list(chaos.trace)
    chaos.disarm()
    # decision observatory (decision-observatory PR): sweep every
    # shard's decision store — the stores outlive the incarnations, so
    # the full history (kill-restart takeovers included) is here. Per
    # shard: gap-free per-controller sequences (the takeover's ledger
    # adopted the dead owner's tail and continued its cseq) and
    # recompute-replay cleanliness; the canonical per-shard traces ride
    # the stats for the same-seed / shadow bit-exactness arms.
    dec_by_shard = {
        s: sorted(
            fabric.decision_stores[s].load(),
            key=lambda r: r.get("seq", 0),
        )
        for s in sorted(fabric.decision_stores)
    }
    dec_by_shard = {s: recs for s, recs in dec_by_shard.items() if recs}
    assert dec_by_shard, "no shard recorded any controller decisions"
    stats["decision_trace"] = {
        str(s): _sweep_decisions(
            recs, context=f"sharded-soak shard {s} decisions"
        )
        for s, recs in dec_by_shard.items()
    }
    stats["decisions_total"] = sum(
        len(recs) for recs in dec_by_shard.values()
    )
    stats["shadow_divergences"] = sum(
        1
        for recs in dec_by_shard.values()
        for r in recs
        if r.get("shadow", {}).get("diverged")
    )
    if doomed_name is not None:
        # the kill-restart leg left an ADOPTED decision tail: at least
        # one shard's store carries records from two writer
        # incarnations, and the gap-free sweep above ran THROUGH the
        # takeover boundary
        assert any(
            len({r.get("incarnation") for r in recs}) >= 2
            for recs in dec_by_shard.values()
        ), "kill-restart fired but no shard shows an adopted decision tail"
    stats["owned_final"] = {
        inc.name: inc.owned() for inc in incs if not inc.dead
    }
    stats["shard_epochs_final"] = {
        s: fabric.fences[s].current() for s in sorted(fabric.fences)
    }
    stats["journal_records"] = {
        s: len(fabric.journal_stores[s].load())
        for s in sorted(fabric.journal_stores)
    }
    # elastic-topology PR: the scheduled split + merge really executed
    # (and their crash-armed attempts really rolled back)
    stats["splits"] = topo_ctrl.stats["splits"]
    stats["merges"] = topo_ctrl.stats["merges"]
    stats["topology_rollbacks"] = topo_ctrl.stats["rollbacks"]
    stats["generation_final"] = fabric.topology.generation
    stats["active_shards_final"] = fabric.shard_map.active_shards()
    stats["health_ok"] = all(
        inc.runtime(s).sched.extender.health.ok()
        for inc in incs
        if not inc.dead
        for s in inc.owned()
        if inc.runtime(s) is not None
    )
    # (fleet-tracing PR) the SLO layer saw the soak: per-pod placement
    # latency from every ack and one time-to-recover sample per
    # takeover's recovery (thresholds are wall-clock-sized and the sim
    # clock ticks in cycles, so violation VERDICTS are not asserted —
    # sample plumbing is)
    slo_eval = slo.evaluate()
    stats["slo_latency_samples"] = sum(
        sh["p99_latency"]["samples"]
        for sh in slo_eval.values()
        if "p99_latency" in sh
    )
    stats["slo_recovery_samples"] = sum(
        sh["recovery"]["samples"]
        for sh in slo_eval.values()
        if "recovery" in sh
    )
    assert stats["slo_latency_samples"] > 0
    assert stats["slo_recovery_samples"] > 0
    # leak-detector arm (devprof PR): monotone live-array growth across
    # the incarnation boundaries fails the soak
    leaks.sample("end")
    leak_problems = leaks.problems()
    assert not leak_problems, leak_problems
    stats["leak_samples"] = list(leaks.samples)
    for inc in incs:
        inc.close()
    hub.stop()
    return stats


def run_overload_storm_soak(
    cycles: int = 56,
    seed: int = 0,
    n_nodes: int = 24,
    base_arrivals: int = 4,
    storm_mult: int = 10,
    drain_limit: int = 80,
    shards: int = 2,
    incarnations: int = 2,
    verbose: bool = False,
    shadow: bool = False,
) -> dict:
    """Overload-control acceptance soak (brownout PR): a seeded arrival
    STORM (``storm_mult``× the base rate, mixed PROD/MID/BATCH/FREE
    QoS bands) plus a channel brownout (``channel.breaker_storm``
    tripping the :class:`~koordinator_tpu.runtime.overload.
    CircuitBreaker` on a live loopback gRPC mirror) plus one shard
    SPLIT mid-storm, driven through the sharded control plane with
    QoS-aware bounded admission and the brownout ladder wired.

    Asserted inside:

    * **zero duplicate placements** (the placed ledger, across the
      split's topology epoch bump);
    * **PROD/MID are never shed** — only BATCH/FREE pay for the storm;
    * **every terminally shed pod has a gap-free timeline ending at
      ``shed``** (and every placed pod one ending at ``ack``, including
      redeemed-resubmit-ticket pods whose story bridges the shed);
    * **the ladder is monotonic with hysteresis**: every transition is
      ±1 level, the transition count is bounded (no flapping), the
      storm actually engages it (≥ L3) and it walks back down after;
    * **the breaker trips, fails fast, probes and recloses** — the
      mirror heals by full resync, never by per-call retry grind;
    * **same seed ⇒ same trace** (fault trace + ladder transitions +
      shed counts, for the determinism arm).
    """
    import random as _random

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.extension import PriorityClass
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.chaos import FaultInjector
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.obs.lifecycle import PodLifecycle, validate_timeline
    from koordinator_tpu.obs.slo import SloTarget, SloTracker
    from koordinator_tpu.runtime.elastic import TopologyController
    from koordinator_tpu.runtime.overload import (
        AdmissionController,
        BrownoutController,
        CircuitBreaker,
        OverloadConfig,
    )
    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.shards import (
        ShardedScheduler,
        ShardFabric,
        ShardRouter,
    )
    from koordinator_tpu.runtime.snapshot_channel import (
        ChannelBreakerOpen,
        ChannelError,
        SolverClient,
        SolverService,
        serve,
    )
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    assert shards >= 2 and incarnations >= 2
    ALLOC_CPU, ALLOC_MEM = 32_000.0, 128 * 1024.0
    POD_CPU, POD_MEM = 2_000.0, 4_096.0
    LIFETIME = 6
    MAX_BATCH = 8
    rng = _random.Random(seed)
    chaos = FaultInjector(seed=seed)
    sim_cycle = [0]

    def _clock() -> float:
        return float(sim_cycle[0])

    fabric = ShardFabric(shards, clock=_clock, membership_ttl_s=2.5)
    lifecycle = PodLifecycle(clock=_clock)
    # SLO targets in SIM-CYCLE units; small windows so the post-storm
    # recovery is visible inside the run (stale violations age out)
    slo = SloTracker(
        clock=_clock,
        targets=(
            # time horizons (max_age_s, in cycles) so the post-storm
            # burn decays even for objectives that stop sampling while
            # the ladder defers their traffic — recovery must be
            # OBSERVABLE or the ladder could never walk back down
            SloTarget(
                "p99_latency", threshold_s=6.0, budget=0.1, window=48,
                max_age_s=16.0, min_samples=4,
            ),
            SloTarget(
                "queue_age", threshold_s=2.0, budget=0.05, window=48,
                max_age_s=16.0, min_samples=4,
            ),
            SloTarget("recovery", threshold_s=6.0, budget=0.5, window=16),
        ),
    )
    hub = ClusterStateHub(chaos=chaos)
    node_names = [f"n{i:03d}" for i in range(n_nodes)]
    for name in node_names:
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: ALLOC_CPU,
                        ext.RES_MEMORY: ALLOC_MEM,
                    }
                ),
            ),
        )

    def make_scheduler(shard, snapshot, fence, journal):
        s = BatchScheduler(
            snapshot,
            LoadAwareArgs(usage_thresholds={}),
            batch_bucket=MAX_BATCH,
            chaos=chaos,
            journal=journal,
            fence=fence,
        )
        s.extender.monitor.stop_background()
        chaos.bind_counter(s.extender.registry.get("fault_injected_total"))
        return s

    incs: list = []
    topo_ctrl = TopologyController(
        fabric,
        slo=slo,
        incarnations=lambda: [i for i in incs if not i.dead],
        node_names=lambda: list(node_names),
        chaos=chaos,
        lifecycle=lifecycle,
    )
    brownout = BrownoutController(
        slo=slo,
        shards=lambda: fabric.shard_map.active_shards(),
        thresholds=(1.0, 2.0, 4.0, 8.0),
        sustain=2,
        cooldown=3,
        clock=_clock,
        topology=topo_ctrl,
    )
    admission = AdmissionController(
        OverloadConfig(
            band_budget={
                PriorityClass.BATCH: 3 * MAX_BATCH,
                PriorityClass.FREE: MAX_BATCH,
            },
            band_age_limit_s={
                PriorityClass.BATCH: 10.0,
                PriorityClass.FREE: 4.0,
            },
        ),
        brownout=brownout,
        lifecycle=lifecycle,
        clock=_clock,
    )
    # decision observatory (decision-observatory PR): ONE fleet-level
    # ledger for the fleet-scoped controllers — ladder, admission,
    # breaker (attached below, once built). Wired BEFORE the
    # incarnations are constructed so the runtimes' per-shard ledgers
    # can't claim the controllers' first-wins slot in _build_runtime;
    # the per-shard depth records live on fabric.decision_stores as in
    # every sharded run. ``shadow=True`` is the bit-exactness arm: an
    # always-diverging shadow consults on EVERY fleet and depth record
    # without ever acting.
    from koordinator_tpu.core.journal import (
        MemoryJournalStore as _DecisionStore,
    )
    from koordinator_tpu.obs.decisions import DecisionLedger

    fleet_decisions = DecisionLedger(
        _DecisionStore(),
        capacity=4096,
        incarnation="storm-fleet",
        clock=_clock,
    )
    shadow_registry = None
    if shadow:
        from koordinator_tpu.obs.shadow import (
            AlwaysDivergeShadow,
            ShadowRegistry,
        )

        shadow_registry = ShadowRegistry()
        for _name in ("depth", "brownout", "admission", "breaker"):
            shadow_registry.attach(_name, AlwaysDivergeShadow())
        fleet_decisions.attach_shadow(shadow_registry)
    brownout.attach_decisions(fleet_decisions)
    admission.attach_decisions(fleet_decisions)
    topo_ctrl.attach_decisions(fleet_decisions)

    def _make_incarnation(idx: int) -> ShardedScheduler:
        inc = ShardedScheduler(
            f"ov{idx}",
            hub,
            fabric,
            make_scheduler,
            pipelined=True,
            pipeline_depth=2,
            max_batch=MAX_BATCH,
            max_retries=8,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            chaos=chaos,
            lifecycle=lifecycle,
            slo=slo,
            overload=admission,
            flight_capacity=64,
        )
        fabric.membership.heartbeat(inc.name)
        return inc

    incs.extend(_make_incarnation(i) for i in range(incarnations))
    router = ShardRouter(
        fabric.shard_map,
        lifecycle=lifecycle,
        burn_of=topo_ctrl.shard_burn,
        brownout=brownout,
    )

    # the channel mirror: a loopback gRPC sidecar the driver syncs its
    # placed/completed world into — through the breaker. During the
    # channel brownout the breaker trips and sync attempts FAIL FAST;
    # the driver accumulates the un-mirrored state and flushes it as
    # one delta when the half-open probe recloses the breaker.
    service = SolverService(ClusterSnapshot())
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service)
    breaker = CircuitBreaker(threshold=3, cooldown_s=5.0, clock=_clock)
    breaker.attach_decisions(fleet_decisions)
    client = SolverClient(
        f"127.0.0.1:{port}", timeout_s=5.0, chaos=chaos, breaker=breaker
    )
    cfg = ClusterSnapshot().config

    def _vec(rl):
        return pb.ResourceVector(
            values=[float(x) for x in cfg.res_vector(rl)]
        )

    mirror_rev = 0
    mirror_nodes_sent = False
    pending_assumes: dict = {}   # uid -> node, not yet mirrored
    pending_forgets: list = []

    def _mirror_sync():
        nonlocal mirror_rev, mirror_nodes_sent, pending_assumes
        nonlocal pending_forgets
        delta = pb.SnapshotDelta(
            revision=mirror_rev + 1, now=float(sim_cycle[0])
        )
        if not mirror_nodes_sent:
            for name in node_names:
                delta.node_upserts.add(
                    name=name,
                    allocatable=_vec(
                        {ext.RES_CPU: ALLOC_CPU, ext.RES_MEMORY: ALLOC_MEM}
                    ),
                )
        for uid, node in sorted(pending_assumes.items()):
            delta.pod_assumed.add(
                uid=uid,
                node=node,
                requests=_vec(
                    {ext.RES_CPU: POD_CPU, ext.RES_MEMORY: POD_MEM}
                ),
            )
        for uid in pending_forgets:
            delta.pod_forgotten.append(uid)
        try:
            ack = client.sync(delta)
        except ChannelBreakerOpen:
            stats["breaker_fast_fails"] += 1
            return
        except ChannelError:
            stats["channel_failures"] += 1
            return
        assert not ack.resync_required, "accumulated deltas never gap"
        mirror_rev = ack.applied_revision
        mirror_nodes_sent = True
        pending_assumes = {}
        del pending_forgets[:]
        stats["mirror_syncs"] += 1

    stats = {
        "cycles": 0,
        "arrived": 0,
        "placed": 0,
        "completed": 0,
        "shed_terminal": 0,
        "tickets_redeemed": 0,
        "mirror_syncs": 0,
        "channel_failures": 0,
        "breaker_fast_fails": 0,
        "splits": 0,
        "faults": {},
    }
    placed: dict = {}
    pod_by_uid: dict = {}
    live: list = []
    pending: list = []
    pending_handoff: list = []
    held_tickets: list = []   # shed tickets awaiting post-storm triage
    shed_final: dict = {}     # uid -> ticket, terminally shed
    redeemed: set = set()
    pod_seq = 0
    storm_lo = max(4, cycles // 4)
    storm_hi = storm_lo + max(6, cycles // 4)
    split_cycle = storm_lo + max(2, cycles // 8)
    #: deterministic QoS mix by sequence number: 3 PROD, 2 MID, 3 BATCH,
    #: 2 FREE per 10 arrivals
    BAND_PRIO = (9000, 9000, 9000, 7500, 7500, 5500, 5500, 5500, 3500, 3500)

    def _owner_of(shard: int):
        for inc in incs:
            if not inc.dead and inc.owns(shard):
                return inc
        return None

    def _place(pod, node, shard):
        assert pod.meta.uid not in placed, (
            f"pod {pod.meta.name} placed twice: "
            f"{placed[pod.meta.uid]} then {node} (shard {shard})"
        )
        assert fabric.shard_map.cell_covers(shard, node), (
            f"{pod.meta.name} bound on {node} by shard {shard}"
        )
        placed[pod.meta.uid] = node
        pod.spec.node_name = node
        hub.publish(hub.pods, pod)
        live.append((pod, node, sim_cycle[0] + LIFETIME))
        pending_assumes[pod.meta.uid] = node
        stats["placed"] += 1

    def _absorb_decided(decided):
        for shard, pod, node, _lat in decided:
            if node is not None:
                _place(pod, node, shard)
            else:
                pending.append(pod)

    def _absorb_handoffs(handoffs):
        for shard, hand in sorted(handoffs.items()):
            for pod, node, _lat in hand.decided:
                if node is not None:
                    _place(pod, node, shard)
                else:
                    pending.append(pod)
            for pod, arr, tries in hand.queued:
                pending_handoff.append((shard, pod, arr, tries))

    def _triage_tickets():
        """Post-storm ticket redemption: BATCH tickets are resubmitted
        (the driver's retry — their timelines bridge the shed with a
        fresh enqueue); FREE tickets stay terminally shed. Redemption
        waits for the ladder to drop below L3 — resubmitting into a
        still-deferring fleet would just shed the same pods again."""
        held_tickets.extend(admission.take_tickets())
        if (
            sim_cycle[0] < storm_hi
            or brownout.level >= BrownoutController.L3
        ):
            return
        keep = []
        budget = 2 * MAX_BATCH  # paced: a retry stampede would just
        for t in held_tickets:  # re-burn the queue-age budget
            uid = t.pod.meta.uid
            if uid in placed:
                # a fanned/requeued copy already placed — not terminal
                continue
            if t.band == PriorityClass.BATCH and budget > 0:
                budget -= 1
                redeemed.add(uid)
                pending.append(t.pod)
                stats["tickets_redeemed"] += 1
            elif t.band == PriorityClass.BATCH:
                keep.append(t)
            else:
                shed_final[uid] = t
        held_tickets[:] = keep

    level_trace: list = []
    #: (ladder level at pump, effective depth cap, adaptive choice) per
    #: owned pipeline per cycle — the brownout-interplay assertions
    depth_cap_samples: list = []
    total_cycles = cycles + drain_limit
    for cycle in range(total_cycles):
        sim_cycle[0] = cycle
        stats["cycles"] += 1

        # ---- the storm schedule (fixed cycles: deterministic trace) ----
        if cycle == storm_lo:
            # channel brownout for the storm's duration: every channel
            # attempt fails at the transport until the schedule runs
            # out — the breaker must trip and meter the probes
            chaos.arm("channel.breaker_storm", times=5)
        if cycle == split_cycle:
            target = topo_ctrl.pick_split_candidate()
            if target is not None:
                out = topo_ctrl.split(target, cycle=cycle)
                assert out is not None, "mid-storm split failed"
                stats["splits"] += 1

        # ---- arrivals (QoS-mixed; storm window multiplies) ----
        arriving = []
        if cycle < cycles:
            n_arr = rng.randint(max(1, base_arrivals - 1), base_arrivals + 1)
            if storm_lo <= cycle < storm_hi:
                n_arr *= storm_mult
            for _ in range(n_arr):
                pod_seq += 1
                pod = Pod(
                    meta=ObjectMeta(name=f"storm-{pod_seq:05d}"),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: POD_CPU,
                            ext.RES_MEMORY: POD_MEM,
                        },
                        priority=BAND_PRIO[pod_seq % len(BAND_PRIO)],
                    ),
                )
                arriving.append(pod)
                pod_by_uid[pod.meta.uid] = pod
            stats["arrived"] += len(arriving)
        pending.extend(arriving)

        # ---- election + handoffs ----
        for inc in incs:
            if not inc.dead:
                _absorb_handoffs(inc.tick())

        # ---- routing + submit (admission verdicts inside the streams) --
        still = []
        for shard, pod, arr, tries in pending_handoff:
            if not fabric.shard_map.is_active(shard):
                shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is None or not owner.resubmit(shard, pod, arr, tries):
                still.append((shard, pod, arr, tries))
        pending_handoff = still
        still = []
        for pod in pending:
            if pod.meta.uid in placed:
                continue
            shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is None or not owner.submit(
                shard, pod, now=float(cycle)
            ):
                still.append(pod)
        pending = still

        # ---- pump every owned shard ----
        level_at_pump = brownout.level
        for inc in incs:
            if not inc.dead:
                _absorb_decided(inc.pump())
        # adaptive-depth × brownout interplay (open the last gates PR):
        # sample every owned pipeline's effective cap against the ladder
        # level the pumps ran under — L1+'s cap must DOMINATE the
        # adaptive controller, and the controller's choice must be the
        # effective cap again once the ladder is back at L0
        for inc in incs:
            if inc.dead:
                continue
            for s in inc.owned():
                rt = inc.runtime(s)
                if (
                    shadow_registry is not None
                    and rt is not None
                    and rt.sched.decision_ledger is not None
                ):
                    # runtimes are born on takeover; attach_shadow is
                    # first-wins-idempotent per ledger
                    rt.sched.decision_ledger.attach_shadow(
                        shadow_registry
                    )
                pipe = rt.stream._pipe if rt is not None else None
                if pipe is not None:
                    depth_cap_samples.append(
                        (level_at_pump, pipe.last_depth_cap,
                         pipe.last_adaptive_depth)
                    )

        # ---- completions free capacity ----
        stillliving = []
        for pod, node, done in live:
            if done <= cycle:
                hub.delete(hub.pods, pod)
                fabric.claims.release(pod.meta.uid)
                pending_forgets.append(pod.meta.uid)
                stats["completed"] += 1
            else:
                stillliving.append((pod, node, done))
        live = stillliving
        assert hub.wait_synced()

        # ---- channel mirror + ladder tick + ticket triage ----
        if pending_assumes or pending_forgets or not mirror_nodes_sent:
            _mirror_sync()
        brownout.tick(cycle)
        level_trace.append(brownout.level)
        _triage_tickets()

        if verbose and cycle % 5 == 0:
            backlogs = {
                s: _owner_of(s).backlog(s)
                for s in fabric.shard_map.active_shards()
                if _owner_of(s)
            }
            print(
                f"cycle={cycle:3d} L{brownout.level} "
                f"pending={len(pending):4d} backlogs={backlogs} "
                f"placed={stats['placed']} shed={admission.shed_total()} "
                f"breaker={breaker.state_name}"
            )

        if (
            cycle >= cycles
            and not pending
            and not pending_handoff
            and not held_tickets
            # the soak's contract includes RECOVERY: keep ticking until
            # the ladder has walked all the way back down (the burn
            # horizons guarantee it decays once the world is idle)
            and brownout.level == BrownoutController.L0
            and all(
                _owner_of(s) is None
                or (
                    _owner_of(s).backlog(s) == 0
                    and _owner_of(s)
                    .runtime(s)
                    .stream.deferred_backlog()
                    == 0
                )
                for s in fabric.shard_map.active_shards()
            )
        ):
            break

    # ---- drain the pipeline tails ----
    for inc in incs:
        if not inc.dead:
            _absorb_decided(inc.flush())
    for _ in range(drain_limit):
        if not pending and not pending_handoff:
            break
        sim_cycle[0] += 1
        for inc in incs:
            if not inc.dead:
                _absorb_handoffs(inc.tick())
        still = []
        for pod in pending:
            if pod.meta.uid in placed:
                continue
            shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is None or not owner.submit(
                shard, pod, now=float(sim_cycle[0])
            ):
                still.append(pod)
        pending = still
        still = []
        for shard, pod, arr, tries in pending_handoff:
            if not fabric.shard_map.is_active(shard):
                shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is None or not owner.resubmit(shard, pod, arr, tries):
                still.append((shard, pod, arr, tries))
        pending_handoff = still
        for inc in incs:
            if not inc.dead:
                _absorb_decided(inc.pump())
        stillliving = []
        for pod, node, done in live:
            if done <= sim_cycle[0]:
                hub.delete(hub.pods, pod)
                fabric.claims.release(pod.meta.uid)
                pending_forgets.append(pod.meta.uid)
                stats["completed"] += 1
            else:
                stillliving.append((pod, node, done))
        live = stillliving
        assert hub.wait_synced()
        _triage_tickets()
    for inc in incs:
        if not inc.dead:
            _absorb_decided(inc.flush())
    _triage_tickets()
    for t in held_tickets:
        if t.pod.meta.uid not in placed:
            shed_final[t.pod.meta.uid] = t

    # ---- the storm's verdicts ----
    stats["shed_terminal"] = len(shed_final)
    # every pod is accounted for exactly once: placed or terminally shed
    assert not pending and not pending_handoff, (
        f"{len(pending)}/{len(pending_handoff)} pods lost in the storm"
    )
    accounted = set(placed) | set(shed_final)
    assert len(placed) + len(shed_final) == stats["arrived"], (
        f"arrived {stats['arrived']} != placed {len(placed)} + "
        f"shed {len(shed_final)}"
    )
    assert accounted == set(pod_by_uid), "a pod vanished unaccounted"
    # PROD/MID are NEVER shed — the QoS contract under storm
    from koordinator_tpu.api.extension import PriorityClass as _PC

    assert set(admission.shed_counts) <= {
        int(_PC.BATCH), int(_PC.FREE)
    }, f"PROD/MID shed: {admission.shed_counts}"
    for t in shed_final.values():
        assert t.band in (_PC.BATCH, _PC.FREE)
    assert admission.shed_total() > 0, (
        "the storm never engaged admission shedding"
    )
    assert stats["tickets_redeemed"] > 0, (
        "no BATCH resubmit ticket was redeemed post-storm"
    )
    # gap-free timelines: placed pods end at ack (shed pods that were
    # redeemed bridge shed→resubmit/enqueue inside the same story);
    # terminally shed pods end at shed
    bad = []
    for uid in placed:
        problems = validate_timeline(lifecycle.timeline(uid))
        if problems:
            bad.append((pod_by_uid[uid].meta.name, problems))
    for uid in shed_final:
        evs = lifecycle.timeline(uid)
        problems = validate_timeline(evs)
        if evs[-1].stage != "shed":
            problems.append(f"terminally shed pod ends at {evs[-1].stage}")
        if problems:
            bad.append((pod_by_uid[uid].meta.name, problems))
    assert not bad, (
        f"{len(bad)} gap-ful storm timelines; first 3: {bad[:3]}"
    )
    # the ladder: engaged by the storm, monotonic ±1, bounded, recovered
    transitions = brownout.transitions()
    assert all(
        abs(t["to"] - t["from"]) == 1 for t in transitions
    ), f"non-monotonic ladder transition: {transitions}"
    peak = max(level_trace)
    assert peak >= BrownoutController.L3, (
        f"storm never drove the ladder past L2 (peak L{peak}; "
        f"trace {level_trace})"
    )
    assert len(transitions) <= 2 * peak + 4, (
        f"ladder flapped: {len(transitions)} transitions for peak "
        f"L{peak}: {transitions}"
    )
    assert brownout.level == BrownoutController.L0, (
        f"ladder never recovered post-storm (final L{brownout.level}; "
        f"trace {level_trace})"
    )
    assert brownout.stats["deescalations"] >= 1
    # adaptive depth × brownout interplay (open the last gates PR):
    # while browning (L1+), the ladder's depth cap DOMINATES — the
    # effective cap never exceeds 1 whatever the controller wants; at
    # L0 the controller's own choice is the effective cap again, and
    # the post-recovery tail actually runs at it (resumes cleanly)
    assert depth_cap_samples, "no pipeline depth samples collected"
    for level, cap, _adaptive in depth_cap_samples:
        if level >= BrownoutController.L1:
            assert cap <= 1, (level, cap)
    l0_tail = [
        (cap, adaptive)
        for level, cap, adaptive in depth_cap_samples
        if level == BrownoutController.L0
    ]
    assert l0_tail and all(cap == adaptive for cap, adaptive in l0_tail), (
        "the adaptive controller's choice must be the effective cap at L0"
    )
    assert any(level >= BrownoutController.L1 for level, _c, _a in
               depth_cap_samples), "storm never sampled a browning pump"
    stats["depth_cap_samples"] = depth_cap_samples
    # the breaker: tripped by the channel brownout, failed fast, and
    # reclosed via the half-open probe; the mirror then caught up by
    # one accumulated flush
    assert breaker.stats["trips"] >= 1, "channel storm never tripped"
    assert stats["breaker_fast_fails"] >= 1, (
        "an open breaker never failed a sync fast"
    )
    assert breaker.state == CircuitBreaker.CLOSED, breaker.report()
    if pending_assumes or pending_forgets:
        _mirror_sync()
    assert not pending_assumes and not pending_forgets
    with service._lock:
        mirrored = set(service.snapshot._assumed)
    assert mirrored == {p.meta.uid for p, _n, _d in live}, (
        "mirror diverged from the live set after breaker recovery"
    )
    # the mid-storm split really happened under load
    assert stats["splits"] == 1 and fabric.topology.generation >= 1
    stats["shed_counts"] = {
        _PC(b).name: n for b, n in sorted(admission.shed_counts.items())
    }
    stats["deferred_total"] = admission.deferred_total
    stats["brownout"] = {
        "peak": peak,
        "final": brownout.level,
        "transitions": transitions,
        "stats": dict(brownout.stats),
    }
    stats["breaker"] = breaker.report()
    stats["level_trace"] = level_trace
    stats["faults"] = chaos.fired_counts()
    stats["fault_trace"] = list(chaos.trace)
    chaos.disarm()
    # decision observatory (decision-observatory PR): the storm's whole
    # control-plane story is on the ledgers — every ladder move,
    # admission verdict, breaker transition (fleet ledger) and depth
    # choice (per-shard stores). Swept gap-free + recompute-clean, with
    # the canonical traces stamped for the same-seed / shadow
    # bit-exactness arms.
    fleet_recs = sorted(
        fleet_decisions.store.load(), key=lambda r: r.get("seq", 0)
    )
    assert fleet_recs, "the storm recorded no fleet controller decisions"
    recorded_controllers = {str(r["controller"]) for r in fleet_recs}
    assert {"brownout", "admission", "breaker"} <= recorded_controllers, (
        f"storm fleet ledger is missing controllers: "
        f"{recorded_controllers}"
    )
    shard_recs = {
        s: sorted(
            fabric.decision_stores[s].load(),
            key=lambda r: r.get("seq", 0),
        )
        for s in sorted(fabric.decision_stores)
    }
    shard_recs = {s: recs for s, recs in shard_recs.items() if recs}
    assert any(
        str(r["controller"]) == "depth"
        for recs in shard_recs.values()
        for r in recs
    ), "no per-shard depth decisions recorded under the storm"
    stats["decision_trace"] = {
        "fleet": _sweep_decisions(
            fleet_recs, context="storm fleet decisions"
        ),
        "shards": {
            str(s): _sweep_decisions(
                recs, context=f"storm shard {s} decisions"
            )
            for s, recs in shard_recs.items()
        },
    }
    stats["decisions_total"] = len(fleet_recs) + sum(
        len(recs) for recs in shard_recs.values()
    )
    stats["shadow_divergences"] = sum(
        1
        for recs in [fleet_recs, *shard_recs.values()]
        for r in recs
        if r.get("shadow", {}).get("diverged")
    )
    for inc in incs:
        if not inc.dead:
            inc.close()
    client.close()
    server.stop(None)
    hub.stop()
    return stats
