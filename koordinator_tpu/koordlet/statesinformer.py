"""States informer: single source of node-local state + callback registry.

Rebuild of ``pkg/koordlet/statesinformer/`` — the one component every other
koordlet subsystem reads state through (``statesinformer/api.go:117-132``
callback registry, ``impl/callback_runner.go`` fan-out): Node, Pods (the
reference pulls from the kubelet API via ``impl/kubelet_stub.go``; here a
pluggable ``pod_source``), NodeSLO, NodeMetric collect spec,
NodeResourceTopology (CPU topology + kubelet cpu-manager state,
``impl/states_noderesourcetopology.go``) and the Device inventory
(NVML GPU discovery in ``impl/states_device_linux.go`` — here an
injectable prober, since TPU hosts enumerate accelerators differently).

Consumers register callbacks per state type; every setter synchronously
fans out to registered callbacks in registration order, exactly like the
reference's callback runner draining its channel per update.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Callable, Dict, List, Mapping, Optional, Protocol, Sequence, Tuple

from ..api.types import (
    Device,
    DeviceInfo,
    Node,
    NodeMetric,
    NodeResourceTopology,
    NodeSLO,
    ObjectMeta,
    Pod,
    TopologyZone,
)
from ..api import extension as ext
from ..core.topology import CPUTopology, format_cpuset_sorted


class StateType(enum.Enum):
    """Registered callback channels (reference RegisterTypeNodeSLOSpec /
    ...NodeTopology / ...AllPods / ...NodeMetricSpec, api.go:117-132)."""

    NODE = "node"
    ALL_PODS = "all_pods"
    NODE_SLO = "node_slo_spec"
    NODE_METRIC_SPEC = "node_metric_spec"
    NODE_TOPOLOGY = "node_topology"
    DEVICE = "device"
    PVCS = "pvcs"


Callback = Callable[[object], None]


class CallbackRunner:
    """Per-state-type callback fan-out (impl/callback_runner.go)."""

    def __init__(self):
        self._callbacks: Dict[StateType, List[tuple]] = {t: [] for t in StateType}
        self._lock = threading.Lock()

    def register(self, state: StateType, name: str, fn: Callback) -> None:
        with self._lock:
            self._callbacks[state].append((name, fn))

    def fire(self, state: StateType, value: object) -> List[str]:
        with self._lock:
            cbs = list(self._callbacks[state])
        fired = []
        for name, fn in cbs:
            fn(value)
            fired.append(name)
        return fired


class DeviceProber(Protocol):
    """Injectable accelerator discovery (the reference's NVML binding)."""

    def probe(self) -> List[DeviceInfo]: ...


@dataclasses.dataclass
class FakeDeviceProber:
    """Test/simulator prober; the production analog shells out to the
    platform's accelerator enumeration."""

    devices: List[DeviceInfo] = dataclasses.field(default_factory=list)

    def probe(self) -> List[DeviceInfo]:
        return list(self.devices)


class TpuDeviceProber:
    """TPU-host device discovery — the TPU-native analog of the
    reference's NVML GPU enumeration (``impl/states_device_linux.go``):
    on a TPU node the Device CR inventories TPU chips, discovered through
    the JAX runtime. Interconnect-complete groups (one chip's cores; a
    host's chips sharing an ICI domain) surface through the Device
    partition table just like NVLink groups do for GPUs."""

    def __init__(self, registry=None):
        #: component registry for exceptions_total{site} (e.g. the
        #: koordlet registry), mirroring KubeletStub
        self.registry = registry

    def probe(self) -> List[DeviceInfo]:
        try:
            import jax

            devices = jax.devices()
        except Exception as exc:  # noqa: BLE001 — no runtime = no inventory
            from ..obs.errors import report_exception

            report_exception(
                "koordlet.device_probe", exc, registry=self.registry
            )
            return []
        out: List[DeviceInfo] = []
        for d in devices:
            out.append(
                DeviceInfo(
                    dev_type="tpu",
                    minor=int(getattr(d, "id", len(out))),
                    resources={"google.com/tpu": 1.0},
                    # real NUMA locality isn't exposed by the JAX runtime;
                    # -1 = unknown (process_index is a host index, not a
                    # NUMA domain — reporting it would mislead topology
                    # packing)
                    numa_node=-1,
                )
            )
        return out


class StatesInformer:
    """Holds the latest node-local state; setters fire callbacks."""

    def __init__(self, node_name: str = "node-local"):
        self.node_name = node_name
        self.callbacks = CallbackRunner()
        self._lock = threading.Lock()
        self._node: Optional[Node] = None
        self._pods: List[Pod] = []
        self._node_slo: Optional[NodeSLO] = None
        self._node_metric_spec: Optional[NodeMetric] = None
        self._topology: Optional[NodeResourceTopology] = None
        self._device: Optional[Device] = None
        self._pvcs: List["PersistentVolumeClaim"] = []

    # ---- setters (watch-stream analogs) ----
    # Each setter validates its input before mutating state or firing
    # callbacks: the reference's informer layer only delivers decoded,
    # schema-valid objects, so a malformed object (None, wrong type, a
    # node that isn't ours, pods with duplicate uids) must be dropped at
    # the door instead of poisoning every downstream subsystem.

    def set_node(self, node: Node) -> None:
        if not isinstance(node, Node) or not node.meta.name:
            return
        if node.meta.name != self.node_name:
            return  # another node's object — a misrouted watch event
        with self._lock:
            self._node = node
        self.callbacks.fire(StateType.NODE, node)

    def set_pods(self, pods: Sequence[Pod]) -> None:
        if pods is None:
            return
        clean: List[Pod] = []
        seen = set()
        for p in pods:
            if not isinstance(p, Pod) or not p.meta.uid:
                continue
            if p.meta.uid in seen:
                continue  # duplicate uid: keep the first, drop the echo
            seen.add(p.meta.uid)
            clean.append(p)
        with self._lock:
            self._pods = clean
        self.callbacks.fire(StateType.ALL_PODS, list(clean))

    def set_node_slo(self, slo: NodeSLO) -> None:
        if not isinstance(slo, NodeSLO):
            return
        with self._lock:
            self._node_slo = slo
        self.callbacks.fire(StateType.NODE_SLO, slo)

    def set_node_metric_spec(self, spec: NodeMetric) -> None:
        if not isinstance(spec, NodeMetric):
            return
        with self._lock:
            self._node_metric_spec = spec
        self.callbacks.fire(StateType.NODE_METRIC_SPEC, spec)

    def set_pvcs(self, pvcs: Sequence["PersistentVolumeClaim"]) -> None:
        """PVC watch surface (the reference informer tracks claims so
        storage capacity decisions see what is bound on this node)."""
        if pvcs is None:
            return
        clean = [
            p
            for p in pvcs
            if isinstance(p, PersistentVolumeClaim) and p.meta.name
        ]
        with self._lock:
            self._pvcs = clean
        self.callbacks.fire(StateType.PVCS, list(clean))

    def pvcs(self) -> List["PersistentVolumeClaim"]:
        with self._lock:
            return list(self._pvcs)

    # ---- reporters (status writes in the reference) ----

    def _cpu_shared_pools(
        self,
        topo: CPUTopology,
        excluded_all: Sequence[int],
        excluded_lse: Sequence[int],
    ) -> Tuple[list, list]:
        """(ls_pools, be_pools) — reference
        ``states_noderesourcetopology.go`` calCPUSharePools: the LS pool
        is every CPU minus ALL cpuset-bound pods' CPUs (and reserved /
        exclusive system-QoS CPUs, already in ``excluded_all``); the BE
        pool carves out only LSE pods' CPUs (BE may ride LSR cores,
        never LSE). Pools are grouped per (socket, numa) with a cpuset
        string (covertCPUsToSharePool)."""
        excl_all = set(excluded_all)
        excl_lse = set(excluded_lse)

        def pools(excluded: set) -> list:
            groups: Dict[Tuple[int, int], list] = {}
            for c in topo.cpus:
                if c.cpu_id in excluded:
                    continue
                groups.setdefault((c.socket, c.numa_node), []).append(c.cpu_id)
            return [
                {
                    "socket": socket,
                    "node": numa,
                    "cpuset": format_cpuset_sorted(sorted(ids)),
                }
                for (socket, numa), ids in sorted(groups.items())
            ]

        return pools(excl_all), pools(excl_lse)

    def report_topology(
        self,
        topo: CPUTopology,
        kubelet_reserved: Sequence[int] = (),
        policy: str = "None",
        mem_per_numa_bytes: float = 0.0,
        kubelet_policy_name: str = "none",
        system_qos_cpuset: str = "",
        kubelet_pod_allocs: Sequence[Mapping] = (),
    ) -> NodeResourceTopology:
        """Build + publish the NodeResourceTopology report
        (states_noderesourcetopology.go: zones from sysfs topology, kubelet
        cpu-manager state read back so the scheduler never double-allocates
        kubelet-reserved CPUs). The report's annotations carry the full
        numa-aware protocol: LS/BE CPU shared pools (computed from the
        topology minus cpuset-bound pods — ``numa_aware.go:46-51``),
        the kubelet cpu-manager policy, kubelet static pod-cpu-allocs,
        and the system-QoS carve-out."""
        from ..core.topology import parse_cpuset

        by_numa: Dict[int, int] = {}
        for info in topo.cpus:
            by_numa[info.numa_node] = by_numa.get(info.numa_node, 0) + 1
        zones = [
            TopologyZone(
                name=f"node-{numa}",
                allocatable={
                    ext.RES_CPU: 1000.0 * cnt,
                    ext.RES_MEMORY: mem_per_numa_bytes,
                },
                capacity={
                    ext.RES_CPU: 1000.0 * cnt,
                    ext.RES_MEMORY: mem_per_numa_bytes,
                },
            )
            for numa, cnt in sorted(by_numa.items())
        ]
        # exclusions: kubelet-reserved + kubelet static allocs + exclusive
        # system-QoS cpuset come out of BOTH pools; per-pod cpusets come
        # out of the LS pool always and the BE pool only for LSE pods
        import json as _json

        base_excluded: set = set(kubelet_reserved)
        for alloc in kubelet_pod_allocs:
            base_excluded |= parse_cpuset(str(alloc.get("cpuset", "")))
        if system_qos_cpuset:
            base_excluded |= parse_cpuset(system_qos_cpuset)
        excluded_all = set(base_excluded)
        excluded_lse = set(base_excluded)
        with self._lock:
            pods = list(self._pods)
        for pod in pods:
            raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS)
            if not raw:
                continue
            try:
                cpus = parse_cpuset(_json.loads(raw).get("cpuset", ""))
            except (ValueError, AttributeError, TypeError):
                continue
            if not cpus:
                continue
            excluded_all |= cpus
            if pod.qos == ext.QoSClass.LSE:
                excluded_lse |= cpus
        ls_pools, be_pools = self._cpu_shared_pools(
            topo, sorted(excluded_all), sorted(excluded_lse)
        )
        annotations = {
            ext.ANNOTATION_NODE_CPU_SHARED_POOLS: ext.format_cpu_shared_pools(
                ls_pools
            ),
            ext.ANNOTATION_NODE_BE_CPU_SHARED_POOLS: ext.format_cpu_shared_pools(
                be_pools
            ),
            ext.ANNOTATION_KUBELET_CPU_MANAGER_POLICY: _json.dumps(
                {
                    "policy": kubelet_policy_name,
                    "reservedCPUs": format_cpuset_sorted(
                        sorted(set(kubelet_reserved))
                    ),
                }
            ),
        }
        if kubelet_pod_allocs:
            annotations[ext.ANNOTATION_NODE_CPU_ALLOCS] = _json.dumps(
                list(kubelet_pod_allocs)
            )
        if system_qos_cpuset:
            annotations[ext.ANNOTATION_NODE_SYSTEM_QOS_RESOURCE] = _json.dumps(
                {"cpuset": system_qos_cpuset, "cpusetExclusive": True}
            )
        report = NodeResourceTopology(
            meta=ObjectMeta(name=self.node_name, annotations=annotations),
            zones=zones,
            cpu_topology={
                c.cpu_id: (c.core_id, c.numa_node, c.socket) for c in topo.cpus
            },
            kubelet_reserved_cpus=list(kubelet_reserved),
            topology_policy=policy,
        )
        with self._lock:
            self._topology = report
        self.callbacks.fire(StateType.NODE_TOPOLOGY, report)
        return report

    def report_devices(self, prober: DeviceProber) -> Device:
        """Probe accelerators and publish the Device inventory
        (states_device_linux.go NVML walk)."""
        report = Device(
            meta=ObjectMeta(name=self.node_name), devices=prober.probe()
        )
        with self._lock:
            self._device = report
        self.callbacks.fire(StateType.DEVICE, report)
        return report

    # ---- getters ----

    def node(self) -> Optional[Node]:
        with self._lock:
            return self._node

    def pods(self) -> List[Pod]:
        with self._lock:
            return list(self._pods)

    def node_slo(self) -> Optional[NodeSLO]:
        with self._lock:
            return self._node_slo

    def topology(self) -> Optional[NodeResourceTopology]:
        with self._lock:
            return self._topology

    def device(self) -> Optional[Device]:
        with self._lock:
            return self._device


# ---------------------------------------------------------------------------
# Kubelet stub + PVC surface (impl/kubelet_stub.go, impl/states_pvc.go)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PersistentVolumeClaim:
    """Minimal PVC surface (the reference informer tracks PVCs so volume
    capacity decisions can see bound claims)."""

    meta: ObjectMeta
    capacity_gib: float = 0.0
    storage_class: str = ""
    phase: str = "Bound"
    volume_name: str = ""


class KubeletStub:
    """HTTP client for the kubelet's read-only ``/pods`` endpoint
    (``impl/kubelet_stub.go:52-96``): the koordlet learns its pods from
    the LOCAL kubelet instead of an apiserver watch — survives apiserver
    partitions and sees exactly what the node runs.

    The payload is the kubelet's PodList JSON; only the fields the
    informer needs are decoded (name/namespace/uid/labels/annotations,
    resource requests, priority, nodeName, phase).
    """

    def __init__(
        self,
        addr: str = "127.0.0.1",
        port: int = 10255,
        scheme: str = "http",
        timeout_s: float = 10.0,
        token: str = "",
        verify_tls: bool = False,
        registry=None,
    ):
        """Defaults target the kubelet's read-only HTTP endpoint (10255);
        pair ``scheme="https"`` with port 10250 for the secure port (the
        reference's serviceaccount-token + TLS flow; ``verify_tls=False``
        mirrors its InsecureSkipTLSVerify default for self-signed kubelet
        certs)."""
        self.base = f"{scheme}://{addr}:{port}"
        self.timeout_s = timeout_s
        #: component registry for exceptions_total{site} — pulls the
        #: counts onto the koordlet's /metrics instead of the hidden
        #: process-wide default registry
        self.registry = registry
        self.token = token
        self.verify_tls = verify_tls

    def get_all_pods(self) -> List[Pod]:
        """GET /pods; raises OSError/ValueError on transport or decode
        failure (the caller keeps its previous pod view — partial state
        must never replace a healthy one)."""
        import json as _json
        import ssl
        import urllib.request

        req = urllib.request.Request(self.base + "/pods/")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = None
        if self.base.startswith("https") and not self.verify_tls:
            ctx = ssl._create_unverified_context()
        with urllib.request.urlopen(
            req, timeout=self.timeout_s, context=ctx
        ) as resp:
            payload = _json.loads(resp.read().decode())
        return [
            p
            for item in payload.get("items", []) or []
            if (p := self._decode_pod(item)) is not None
        ]

    @staticmethod
    def _decode_pod(item) -> Optional[Pod]:
        from ..api.types import PodSpec

        if not isinstance(item, dict):
            return None
        meta = item.get("metadata") or {}
        name = meta.get("name")
        if not name:
            return None
        spec = item.get("spec") or {}
        requests: Dict[str, float] = {}
        for c in spec.get("containers") or []:
            for res, val in (
                (c.get("resources") or {}).get("requests") or {}
            ).items():
                try:
                    requests[res] = requests.get(res, 0.0) + _parse_quantity(
                        val, res
                    )
                except (TypeError, ValueError):
                    continue
        return Pod(
            meta=ObjectMeta(
                name=name,
                namespace=meta.get("namespace", "default"),
                uid=meta.get("uid", ""),
                labels=dict(meta.get("labels") or {}),
                annotations=dict(meta.get("annotations") or {}),
            ),
            spec=PodSpec(
                requests=requests,
                priority=spec.get("priority"),
                node_name=spec.get("nodeName"),
            ),
        )

    def sync_into(self, informer: "StatesInformer") -> bool:
        """One kubelet pull → informer.set_pods; False (state untouched)
        when the kubelet is unreachable or returns garbage."""
        try:
            pods = self.get_all_pods()
        except Exception as exc:  # noqa: BLE001 — degrade, never crash the loop:
            # transport errors (OSError), malformed HTTP (HTTPException),
            # bad JSON (ValueError), or a garbage top-level payload
            # (AttributeError/TypeError) all mean "keep the previous view"
            from ..obs.errors import report_exception

            report_exception(
                "koordlet.kubelet_pull", exc, registry=self.registry
            )
            return False
        informer.set_pods(pods)
        return True


def _parse_quantity(val, resource: str = "") -> float:
    """k8s quantity → the snapshot's native units, per resource:

    cpu     → milli-cores: '2'/2 → 2000, '500m' → 500
    memory  → MiB: '1Gi' → 1024, '128974848' (bytes) → ~123, '128M'
              (decimal) → ~122
    other   → native count, passed through ('2' → 2.0)

    Raises ValueError on unparseable strings (the caller drops that one
    resource entry)."""
    s = str(val).strip()
    binary = {
        "Ki": 2.0**10,
        "Mi": 2.0**20,
        "Gi": 2.0**30,
        "Ti": 2.0**40,
        "Pi": 2.0**50,
        "Ei": 2.0**60,
    }
    decimal = {"k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18}
    if resource == "cpu":
        if s.endswith("n"):
            return float(s[:-1]) / 1e6      # nano-cores → milli
        if s.endswith("u"):
            return float(s[:-1]) / 1e3      # micro-cores → milli
        if s.endswith("m"):
            return float(s[:-1])
        return float(s) * 1000.0
    if resource == "memory":
        for suf, mult in binary.items():
            if s.endswith(suf):
                return float(s[: -len(suf)]) * mult / 2.0**20
        for suf, mult in decimal.items():
            if s.endswith(suf):
                return float(s[: -len(suf)]) * mult / 2.0**20
        return float(s) / 2.0**20  # plain bytes
    return float(s)
