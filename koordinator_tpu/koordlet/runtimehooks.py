"""Runtime hooks: QoS enforcement at pod/container lifecycle.

Rebuild of ``pkg/koordlet/runtimehooks/`` hook plugins:
  * groupidentity (``hooks/groupidentity/bvt.go:39-64``): per-QoS bvt
    (group identity) values so the CPU scheduler favors latency-sensitive
    groups: LSE/LSR/LS → 2, BE → −1, others → 0.
  * batchresource (``hooks/batchresource``): BE pods running on
    ``kubernetes.io/batch-*`` resources get cpu.shares / cfs quota /
    memory limits derived from batch requests.
  * cpuset (``hooks/cpuset``): apply the exclusive cpuset the scheduler
    wrote into ``scheduling.koordinator.sh/resource-status``.
  * coresched (``hooks/coresched``): per-QoS core scheduling cookies.

The reference delivers hooks over three paths (CRI proxy gRPC, NRI, and a
periodic reconciler); here every path funnels into the same pure
``pod_plan`` rendering, and :class:`Reconciler` is the periodic driver.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import extension as ext
from ..api.extension import QoSClass
from ..api.types import Pod
from . import resourceexecutor as rex

#: bvt_warp_ns values by QoS (bvt.go)
BVT_BY_QOS = {
    QoSClass.LSE: 2,
    QoSClass.LSR: 2,
    QoSClass.LS: 2,
    QoSClass.BE: -1,
    QoSClass.SYSTEM: 0,
    QoSClass.NONE: 0,
}

#: core-sched cookie groups by QoS (coresched hook)
CORE_SCHED_COOKIE_BY_QOS = {
    QoSClass.BE: 2,
    QoSClass.LS: 1,
    QoSClass.LSR: 1,
    QoSClass.LSE: 1,
}


def pod_cgroup(pod: Pod) -> str:
    tier = "besteffort" if pod.qos == QoSClass.BE else "burstable"
    return f"kubepods/{tier}/pod-{pod.meta.name}"


def group_identity_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    bvt = BVT_BY_QOS.get(pod.qos, 0)
    return [(pod_cgroup(pod), rex.CPU_BVT, str(bvt))]


def batch_resource_plan(
    pod: Pod, period_us: int = 100_000
) -> List[Tuple[str, str, str]]:
    """cfs quota + shares + memory limit from batch-tier requests
    (batchresource hook; shares follow the k8s 1024-per-core convention)."""
    cpu = pod.spec.requests.get(ext.RES_BATCH_CPU, 0.0)
    mem = pod.spec.requests.get(ext.RES_BATCH_MEMORY, 0.0)
    if cpu <= 0 and mem <= 0:
        return []
    group = pod_cgroup(pod)
    plan: List[Tuple[str, str, str]] = []
    if cpu > 0:
        limit_cpu = pod.spec.limits.get(ext.RES_BATCH_CPU, cpu)
        plan.append((group, rex.CPU_SHARES, str(int(cpu * 1024 / 1000))))
        plan.append((group, rex.CPU_CFS_PERIOD, str(period_us)))
        plan.append(
            (group, rex.CPU_CFS_QUOTA, str(int(limit_cpu / 1000.0 * period_us)))
        )
    if mem > 0:
        limit_mem = pod.spec.limits.get(ext.RES_BATCH_MEMORY, mem)
        plan.append(
            (group, rex.MEMORY_LIMIT, str(int(limit_mem * 1024 * 1024)))
        )
    return plan


def cpuset_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS)
    if not raw:
        return []
    try:
        status = json.loads(raw)
        cpuset = status.get("cpuset", "")
    except (ValueError, AttributeError):
        return []
    if not cpuset:
        return []
    return [(pod_cgroup(pod), rex.CPUSET_CPUS, cpuset)]


def core_sched_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    cookie = CORE_SCHED_COOKIE_BY_QOS.get(pod.qos)
    if cookie is None:
        return []
    return [(pod_cgroup(pod), rex.CORE_SCHED_COOKIE, str(cookie))]


ALL_HOOKS = (
    group_identity_plan,
    batch_resource_plan,
    cpuset_plan,
    core_sched_plan,
)


def pod_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    plan: List[Tuple[str, str, str]] = []
    for hook in ALL_HOOKS:
        plan.extend(hook(pod))
    return plan


class Reconciler:
    """Periodic cgroup reconciler (``reconciler/reconciler.go``): renders
    and applies every running pod's plan; statesinformer callbacks call
    ``reconcile`` on pod updates."""

    def __init__(self, executor: rex.ResourceExecutor):
        self.executor = executor

    def reconcile(self, pods: Sequence[Pod]) -> int:
        writes = 0
        for pod in pods:
            writes += self.executor.apply(pod_plan(pod), reason="runtimehooks")
        return writes
