"""Runtime hooks: QoS enforcement at pod/container lifecycle.

Rebuild of ``pkg/koordlet/runtimehooks/`` — all ten hook plugins:
  * groupidentity (``hooks/groupidentity/bvt.go:39-64``): per-QoS bvt
    (group identity) values so the CPU scheduler favors latency-sensitive
    groups: LSE/LSR/LS → 2, BE → −1, others → 0.
  * batchresource (``hooks/batchresource``): BE pods running on
    ``kubernetes.io/batch-*`` resources get cpu.shares / cfs quota /
    memory limits derived from batch requests.
  * cpuset (``hooks/cpuset``): apply the exclusive cpuset the scheduler
    wrote into ``scheduling.koordinator.sh/resource-status``.
  * coresched (``hooks/coresched``): per-QoS core scheduling cookies.
  * cpunormalization (``hooks/cpunormalization``): scale cfs quota by the
    node's CPU-model performance ratio annotation.
  * resctrl (``hooks/resctrl``): assign the pod to its QoS tier's RDT
    control group (schemata content is the qosmanager's job).
  * tc (``hooks/tc``): net_cls classid per QoS tier for the tc/HTB
    hierarchy.
  * terwayqos (``hooks/terwayqos``): pod ingress/egress bandwidth from the
    ``koordinator.sh/networkQOS`` annotation.
  * gpu (``hooks/gpu``): container env from the scheduler's
    ``device-allocated`` annotation (visible-device minors).
  * rdma (``hooks/rdma``): RDMA device mounts from the same annotation.

Cgroup-level hooks render write plans; container-spec-level hooks (gpu,
rdma, terwayqos) render :class:`ContainerMutation` env/device patches —
the NRI adjustment payload of the reference.

The reference delivers hooks over three paths (CRI proxy gRPC, NRI, and a
periodic reconciler); here every path funnels into the same pure
``pod_plan`` / ``pod_mutation`` rendering: :class:`Reconciler` is the
periodic driver and :class:`NRIServer` (``nri/server.go``) the lifecycle
driver (the CRI-proxy gRPC path lives in ``runtimeproxy``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..api import extension as ext
from ..api.extension import QoSClass
from ..api.types import Pod
from . import resourceexecutor as rex

#: bvt_warp_ns values by QoS (bvt.go)
BVT_BY_QOS = {
    QoSClass.LSE: 2,
    QoSClass.LSR: 2,
    QoSClass.LS: 2,
    QoSClass.BE: -1,
    QoSClass.SYSTEM: 0,
    QoSClass.NONE: 0,
}

#: core-sched cookie groups by QoS (coresched hook)
CORE_SCHED_COOKIE_BY_QOS = {
    QoSClass.BE: 2,
    QoSClass.LS: 1,
    QoSClass.LSR: 1,
    QoSClass.LSE: 1,
}


def pod_cgroup(pod: Pod) -> str:
    tier = "besteffort" if pod.qos == QoSClass.BE else "burstable"
    return f"kubepods/{tier}/pod-{pod.meta.name}"


def group_identity_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    bvt = BVT_BY_QOS.get(pod.qos, 0)
    return [(pod_cgroup(pod), rex.CPU_BVT, str(bvt))]


def batch_resource_plan(
    pod: Pod, period_us: int = 100_000
) -> List[Tuple[str, str, str]]:
    """cfs quota + shares + memory limit from batch-tier requests
    (batchresource hook; shares follow the k8s 1024-per-core convention)."""
    cpu = pod.spec.requests.get(ext.RES_BATCH_CPU, 0.0)
    mem = pod.spec.requests.get(ext.RES_BATCH_MEMORY, 0.0)
    if cpu <= 0 and mem <= 0:
        return []
    group = pod_cgroup(pod)
    plan: List[Tuple[str, str, str]] = []
    if cpu > 0:
        limit_cpu = pod.spec.limits.get(ext.RES_BATCH_CPU, cpu)
        plan.append((group, rex.CPU_SHARES, str(int(cpu * 1024 / 1000))))
        plan.append((group, rex.CPU_CFS_PERIOD, str(period_us)))
        plan.append(
            (group, rex.CPU_CFS_QUOTA, str(int(limit_cpu / 1000.0 * period_us)))
        )
    if mem > 0:
        limit_mem = pod.spec.limits.get(ext.RES_BATCH_MEMORY, mem)
        plan.append(
            (group, rex.MEMORY_LIMIT, str(int(limit_mem * 1024 * 1024)))
        )
    return plan


@dataclasses.dataclass
class CpusetRule:
    """hooks/cpuset rule state parsed from the NodeResourceTopology
    annotations (reference ``hooks/cpuset/rule.go`` parseRule): the LS
    and BE CPU shared pools the koordlet computed, the kubelet
    cpu-manager policy, and the SYSTEM-QoS carve-out."""

    share_pools: List[Mapping] = dataclasses.field(default_factory=list)
    be_share_pools: List[Mapping] = dataclasses.field(default_factory=list)
    kubelet_policy: str = "none"
    system_qos_cpuset: str = ""
    #: features.BECPUManager gate: BE pods with numa-aware allocations
    #: use the BE pools instead of getting cleared
    be_cpu_manager: bool = False

    @classmethod
    def from_topology(cls, topo, be_cpu_manager: bool = False) -> "CpusetRule":
        ann = topo.meta.annotations or {}
        kubelet = ext.parse_kubelet_cpu_manager_policy(ann) or {}
        sysqos = ext.parse_system_qos_resource(ann) or {}
        return cls(
            share_pools=ext.parse_cpu_shared_pools(ann),
            be_share_pools=ext.parse_cpu_shared_pools(ann, be=True),
            kubelet_policy=str(kubelet.get("policy", "none")),
            system_qos_cpuset=str(sysqos.get("cpuset", "")),
            be_cpu_manager=be_cpu_manager,
        )

    def _pools_cpuset(self, pools: List[Mapping], numa_nodes=None) -> str:
        return ",".join(
            str(p.get("cpuset", ""))
            for p in pools
            if p.get("cpuset")
            and (numa_nodes is None or p.get("node") in numa_nodes)
        )

    def container_cpuset(self, pod: Pod) -> Optional[str]:
        """``rule.go:47-146`` getContainerCPUSet decision table:

        - numa-aware allocation (scheduler stamped NUMA zones): LS-side
          pods take the LS pools of THOSE zones; BE pods take the BE
          pools of those zones when the BECPUManager gate is on;
        - SYSTEM QoS with a configured carve-out: the system cpuset;
        - LS: every LS shared pool;
        - BE/besteffort: cleared ("" — cpu-suppress owns the group);
        - no QoS label: all pools under the kubelet *none* policy, hands
          off (None) under *static* (kubelet already pinned them).
        """
        alloc = {}
        raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS)
        if raw:
            try:
                alloc = json.loads(raw)
            except (ValueError, TypeError):
                alloc = {}
        numa_nodes = {
            e.get("node")
            for e in alloc.get("numaNodeResources", []) or []
            if isinstance(e, dict) and e.get("node") is not None
        }
        qos = pod.qos
        if numa_nodes:
            if qos == QoSClass.BE:
                if self.be_cpu_manager:
                    return (
                        self._pools_cpuset(self.be_share_pools, numa_nodes)
                        or None
                    )
            else:
                # empty/absent pools: hands off — '' is reserved for the
                # deliberate BE clear, never for a missing report
                return (
                    self._pools_cpuset(self.share_pools, numa_nodes) or None
                )
        if qos == QoSClass.SYSTEM and self.system_qos_cpuset:
            return self.system_qos_cpuset
        if qos == QoSClass.LS:
            return self._pools_cpuset(self.share_pools) or None
        if qos == QoSClass.BE:
            return ""
        if self.kubelet_policy == "static":
            return None
        return self._pools_cpuset(self.share_pools) or None


def cpuset_plan(
    pod: Pod, rule: Optional[CpusetRule] = None
) -> List[Tuple[str, str, str]]:
    """cpuset hook: an exclusive cpuset the scheduler stamped into
    resource-status wins outright; otherwise the shared-pool rule decides
    (LS pods → LS pools, BE → cleared, SYSTEM → carve-out, …). With no
    rule (NodeResourceTopology not yet seen) only exclusive sets apply —
    the pre-round-4 behavior."""
    raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_STATUS)
    cpuset = ""
    if raw:
        try:
            cpuset = json.loads(raw).get("cpuset", "")
        except (ValueError, AttributeError, TypeError):
            cpuset = ""
    if cpuset:
        return [(pod_cgroup(pod), rex.CPUSET_CPUS, cpuset)]
    if rule is None:
        return []
    decided = rule.container_cpuset(pod)
    if decided is None:
        return []
    return [(pod_cgroup(pod), rex.CPUSET_CPUS, decided)]


def core_sched_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    cookie = CORE_SCHED_COOKIE_BY_QOS.get(pod.qos)
    if cookie is None:
        return []
    return [(pod_cgroup(pod), rex.CORE_SCHED_COOKIE, str(cookie))]


#: net_cls classids by QoS tier (tc hook: HTB classes 1:2 prod / 1:3 mid /
#: 1:4 BE; classid wire format is 0xMAJOR0000|MINOR)
NET_CLS_BY_QOS = {
    QoSClass.LSE: 0x10002,
    QoSClass.LSR: 0x10002,
    QoSClass.LS: 0x10002,
    QoSClass.BE: 0x10004,
}

NET_CLS_CLASSID = "net_cls.classid"


def cpu_normalization_plan(
    pod: Pod, ratio: float, period_us: int = 100_000
) -> List[Tuple[str, str, str]]:
    """cpunormalization hook: divide the cfs quota by the node's CPU
    performance ratio so a "normalized milli" buys the same work on fast
    and slow CPU models (the reference scales the batch/LS quota the same
    way from the node annotation)."""
    if ratio <= 0 or ratio == 1.0:
        return []
    # Only pods with an explicit CPU limit have a quota to normalize — a
    # limitless pod runs at cfs quota -1 and must stay unthrottled.
    cpu_limit = pod.spec.limits.get(ext.RES_CPU, 0.0)
    if cpu_limit <= 0:
        return []
    # batchresource already derived this pod's quota from batch-cpu; the
    # batch quota wins (the reference normalizes inside batchresource).
    if pod.spec.requests.get(ext.RES_BATCH_CPU, 0.0) > 0:
        return []
    quota = int(cpu_limit / ratio / 1000.0 * period_us)
    return [(pod_cgroup(pod), rex.CPU_CFS_QUOTA, str(quota))]


def resctrl_group_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    """resctrl hook: record the pod's RDT control-group membership (the
    reference moves container pids into /sys/fs/resctrl/<tier>/tasks; the
    pid move is the runtime's side — the decision is the tier name)."""
    tier = {
        QoSClass.LSE: "LSR",
        QoSClass.LSR: "LSR",
        QoSClass.LS: "LS",
        QoSClass.BE: "BE",
    }.get(pod.qos)
    if tier is None:
        return []
    return [(pod_cgroup(pod), "resctrl.group", tier)]


def tc_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    classid = NET_CLS_BY_QOS.get(pod.qos)
    if classid is None:
        return []
    return [(pod_cgroup(pod), NET_CLS_CLASSID, str(classid))]


def terway_qos_plan(pod: Pod) -> List[Tuple[str, str, str]]:
    """terwayqos hook: pod network bandwidth limits from the
    ``koordinator.sh/networkQOS`` annotation (IngressLimit/EgressLimit in
    bytes/s), written where the terway dataplane reads them."""
    raw = pod.meta.annotations.get(ext.ANNOTATION_NETWORK_QOS)
    if not raw:
        return []
    # a malformed user-supplied annotation must never break the node-wide
    # reconcile pass — ignore the pod's network QoS instead
    plan: List[Tuple[str, str, str]] = []
    try:
        spec = json.loads(raw)
        if not isinstance(spec, dict):
            return []
        for key, fname in (
            ("IngressLimit", "net_qos.ingress_bps"),
            ("EgressLimit", "net_qos.egress_bps"),
        ):
            if key in spec:
                plan.append((pod_cgroup(pod), fname, str(int(spec[key]))))
    except (ValueError, TypeError):
        return []
    return plan


@dataclasses.dataclass
class ContainerMutation:
    """Container-spec patch (the NRI ContainerAdjustment payload): env
    vars + device nodes to expose."""

    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    devices: List[str] = dataclasses.field(default_factory=list)


def _parse_device_allocation(pod: Pod) -> Dict[str, List[Dict]]:
    raw = pod.meta.annotations.get(ext.ANNOTATION_DEVICE_ALLOCATED)
    if not raw:
        return {}
    try:
        alloc = json.loads(raw)
    except ValueError:
        return {}
    return alloc if isinstance(alloc, dict) else {}


def gpu_mutation(pod: Pod) -> ContainerMutation:
    """gpu hook: visible-device env from the scheduler's allocation
    annotation (the reference writes NVIDIA_VISIBLE_DEVICES; accelerator-
    neutral names carry the same minors for TPU hosts)."""
    alloc = _parse_device_allocation(pod).get("gpu", [])
    minors = [str(e.get("minor", -1)) for e in alloc if e.get("minor", -1) >= 0]
    if not minors:
        return ContainerMutation()
    joined = ",".join(minors)
    return ContainerMutation(
        env={
            "KOORD_VISIBLE_DEVICES": joined,
            "NVIDIA_VISIBLE_DEVICES": joined,
        },
        devices=[f"/dev/accel{m}" for m in minors],
    )


def rdma_mutation(pod: Pod) -> ContainerMutation:
    """rdma hook: expose allocated RDMA devices (/dev/infiniband/uverbsN)."""
    alloc = _parse_device_allocation(pod).get("rdma", [])
    minors = [e.get("minor", -1) for e in alloc if e.get("minor", -1) >= 0]
    if not minors:
        return ContainerMutation()
    return ContainerMutation(
        devices=[f"/dev/infiniband/uverbs{m}" for m in minors]
    )


ALL_HOOKS = (
    group_identity_plan,
    batch_resource_plan,
    core_sched_plan,
    resctrl_group_plan,
    tc_plan,
    terway_qos_plan,
)

MUTATION_HOOKS = (gpu_mutation, rdma_mutation)


def pod_plan(
    pod: Pod,
    cpu_norm_ratio: float = 1.0,
    cpuset_rule: Optional[CpusetRule] = None,
) -> List[Tuple[str, str, str]]:
    plan: List[Tuple[str, str, str]] = []
    for hook in ALL_HOOKS:
        plan.extend(hook(pod))
    plan.extend(cpuset_plan(pod, cpuset_rule))
    plan.extend(cpu_normalization_plan(pod, cpu_norm_ratio))
    return plan


def pod_mutation(pod: Pod) -> ContainerMutation:
    merged = ContainerMutation()
    for hook in MUTATION_HOOKS:
        m = hook(pod)
        merged.env.update(m.env)
        merged.devices.extend(m.devices)
    return merged


class Reconciler:
    """Periodic cgroup reconciler (``reconciler/reconciler.go``): renders
    and applies every running pod's plan; statesinformer callbacks call
    ``reconcile`` on pod updates.

    ``probes`` (koordlet.system.KernelProbes) gates plan entries on
    kernel support — the reference enables core-sched/bvt/resctrl hooks
    only after the util/system feature probe passes
    (``core_sched.go:275-294``); without it the rebuild emitted those
    writes unconditionally."""

    def __init__(self, executor: rex.ResourceExecutor, probes=None):
        self.executor = executor
        #: node CPU-model performance ratio (cpunormalization hook input,
        #: published by the manager's cpunormalization plugin)
        self.cpu_norm_ratio = 1.0
        #: shared-pool rule from the NodeResourceTopology report
        #: (``rule.go`` parseRule); None until the first report lands
        self.cpuset_rule: Optional[CpusetRule] = None
        self.probes = probes
        self._blocked = (
            probes.unsupported_plan_files() if probes is not None else None
        )

    def set_topology(self, topo) -> None:
        """statesinformer NODE_TOPOLOGY callback target (the reference
        registers parseRule on the same callback)."""
        self.cpuset_rule = CpusetRule.from_topology(topo)

    def render(self, pod: Pod) -> List[Tuple[str, str, str]]:
        plan = pod_plan(pod, self.cpu_norm_ratio, self.cpuset_rule)
        if self._blocked:
            plan = [e for e in plan if e[1] not in self._blocked]
        return plan

    def reconcile(self, pods: Sequence[Pod]) -> int:
        writes = 0
        for pod in pods:
            writes += self.executor.apply(
                self.render(pod), reason="runtimehooks"
            )
        return writes


class NRIServer:
    """NRI-path delivery (``nri/server.go``): the container runtime calls
    in at pod/container lifecycle points; responses carry cgroup writes
    applied synchronously plus the container adjustment. The reference
    registers RunPodSandbox / CreateContainer / UpdateContainerResources;
    the PLEG-independent synchronous path is what distinguishes it from
    the reconciler."""

    def __init__(self, executor: rex.ResourceExecutor):
        self.executor = executor
        self.cpu_norm_ratio = 1.0
        self.cpuset_rule: Optional[CpusetRule] = None

    def set_topology(self, topo) -> None:
        self.cpuset_rule = CpusetRule.from_topology(topo)

    def run_pod_sandbox(self, pod: Pod) -> int:
        """Pre-start: tier/bvt/netcls knobs must exist before containers."""
        return self.executor.apply(
            pod_plan(pod, self.cpu_norm_ratio, self.cpuset_rule),
            reason="nri:RunPodSandbox",
        )

    def create_container(self, pod: Pod) -> ContainerMutation:
        """CreateContainer: return the spec adjustment (env/devices)."""
        return pod_mutation(pod)

    def update_container_resources(self, pod: Pod) -> int:
        return self.executor.apply(
            pod_plan(pod, self.cpu_norm_ratio),
            reason="nri:UpdateContainerResources",
        )
