"""PLEG: pod lifecycle events from the cgroup filesystem.

Rebuild of ``pkg/koordlet/pleg/`` (``watcher_linux.go:25-30`` inotify on
the kubepods cgroup dirs, handler API ``pleg.go:33-45``): pod/container
cgroup directories appearing or vanishing under the QoS-tier hierarchy
become PodAdded/PodDeleted/ContainerAdded/ContainerDeleted events fanned
out to registered handlers.

:class:`Pleg` diffs a directory scan per tick (deterministic; tests and
the simulator drive ticks). :class:`InotifyPleg` is the production
watcher matching the reference's kernel-latency path
(``watcher_linux.go:25-30`` ``inotify.NewWatcher``): ``inotify_init1``
via ctypes, one watch per tier dir and per pod dir, a reader thread
translating kernel events to the same handler stream — with the polling
diff kept as the resync/fallback (non-Linux, fd exhaustion, overflow).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import dataclasses
import enum
import errno
import os
import select
import struct
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

# QoS-tier cgroup parents scanned for pod dirs (the reference watches
# kubepods, kubepods/burstable, kubepods/besteffort).
TIER_DIRS = ("kubepods", "kubepods/burstable", "kubepods/besteffort")


class EventType(enum.Enum):
    POD_ADDED = "PodAdded"
    POD_DELETED = "PodDeleted"
    CONTAINER_ADDED = "ContainerAdded"
    CONTAINER_DELETED = "ContainerDeleted"


@dataclasses.dataclass(frozen=True)
class Event:
    type: EventType
    pod_dir: str                 # tier-relative pod cgroup dir
    container_id: str = ""


Handler = Callable[[Event], None]


def _is_pod_dir(name: str) -> bool:
    return name.startswith("pod")


class Pleg:
    """Directory-diff lifecycle watcher with handler registry."""

    def __init__(self, cgroup_root: str):
        self.cgroup_root = cgroup_root
        self._handlers: List[Tuple[int, Handler]] = []
        self._next_id = 0
        self._known: Dict[str, Set[str]] = {}   # pod_dir -> container ids
        self._lock = threading.Lock()
        #: serializes _known mutation AND the dispatch that follows it
        #: between tick() resyncs and an inotify reader thread
        #: (InotifyPleg) — dispatching outside the lock could deliver a
        #: later delete before an earlier add (re-entrant: a handler may
        #: call back into the pleg)
        self._state_lock = threading.RLock()

    def register_handler(self, handler: Handler) -> int:
        """Returns a handler id usable with unregister (pleg.go HandlerID)."""
        with self._lock:
            hid = self._next_id
            self._next_id += 1
            self._handlers.append((hid, handler))
        return hid

    def unregister_handler(self, hid: int) -> None:
        with self._lock:
            self._handlers = [(i, h) for i, h in self._handlers if i != hid]

    def _scan(self) -> Dict[str, Set[str]]:
        seen: Dict[str, Set[str]] = {}
        for tier in TIER_DIRS:
            tier_path = os.path.join(self.cgroup_root, tier)
            try:
                entries = os.listdir(tier_path)
            except OSError:
                continue
            for entry in entries:
                pod_path = os.path.join(tier_path, entry)
                if not _is_pod_dir(entry) or not os.path.isdir(pod_path):
                    continue
                rel = os.path.join(tier, entry)
                try:
                    containers = {
                        c
                        for c in os.listdir(pod_path)
                        if os.path.isdir(os.path.join(pod_path, c))
                    }
                except OSError:
                    containers = set()
                seen[rel] = containers
        return seen

    def tick(self) -> List[Event]:
        """Diff the hierarchy against the last scan; fire + return events."""
        seen = self._scan()
        events: List[Event] = []
        with self._state_lock:
            for pod_dir, containers in seen.items():
                old = self._known.get(pod_dir)
                if old is None:
                    events.append(Event(EventType.POD_ADDED, pod_dir))
                    old = set()
                for c in sorted(containers - old):
                    events.append(Event(EventType.CONTAINER_ADDED, pod_dir, c))
                for c in sorted(old - containers):
                    events.append(
                        Event(EventType.CONTAINER_DELETED, pod_dir, c)
                    )
            for pod_dir in list(self._known):
                if pod_dir not in seen:
                    for c in sorted(self._known[pod_dir]):
                        events.append(
                            Event(EventType.CONTAINER_DELETED, pod_dir, c)
                        )
                    events.append(Event(EventType.POD_DELETED, pod_dir))
            self._known = seen
            # dispatch INSIDE the state lock: an inotify reader racing in
            # must not deliver a later event before these (causal order)
            self._dispatch(events)
        return events

    def _dispatch(self, events: List[Event]) -> None:
        with self._lock:
            handlers = list(self._handlers)
        for event in events:
            for _hid, handler in handlers:
                handler(event)


# ---- inotify constants (linux/inotify.h) ----
IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_MOVED_FROM = 0x00000040
IN_MOVED_TO = 0x00000080
IN_DELETE_SELF = 0x00000400
IN_ISDIR = 0x40000000
IN_IGNORED = 0x00008000
IN_Q_OVERFLOW = 0x00004000
IN_CLOEXEC = 0x00080000

_WATCH_MASK = IN_CREATE | IN_DELETE | IN_MOVED_FROM | IN_MOVED_TO
_EVENT_HDR = struct.Struct("iIII")   # wd, mask, cookie, len


class InotifyPleg(Pleg):
    """Kernel-latency lifecycle watcher (reference
    ``pkg/koordlet/pleg/watcher_linux.go:25-30``): inotify watches on the
    QoS tier dirs and every pod dir, translated to the same handler
    event stream as the polling diff. ``start()`` returns False when
    inotify is unavailable (non-Linux libc, fd/watch exhaustion) — the
    caller then drives :meth:`tick` as before, so polling remains the
    portable fallback; a queue overflow triggers a full resync through
    the same diff."""

    def __init__(self, cgroup_root: str, registry=None):
        super().__init__(cgroup_root)
        #: component registry for exceptions_total{site}
        self.registry = registry
        self._fd: Optional[int] = None
        self._libc = None
        self._wd_to_dir: Dict[int, str] = {}     # wd -> tier or pod rel dir
        self._dir_to_wd: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake_r, self._wake_w = -1, -1

    # -- libc plumbing --

    def _load_libc(self):
        if self._libc is None:
            name = ctypes.util.find_library("c") or "libc.so.6"
            self._libc = ctypes.CDLL(name, use_errno=True)
        return self._libc

    def _add_watch(self, rel_dir: str) -> Optional[int]:
        path = os.path.join(self.cgroup_root, rel_dir) if rel_dir else self.cgroup_root
        wd = self._libc.inotify_add_watch(
            self._fd, os.fsencode(path), _WATCH_MASK
        )
        if wd < 0:
            return None
        self._wd_to_dir[wd] = rel_dir
        self._dir_to_wd[rel_dir] = wd
        return wd

    def _rm_watch(self, rel_dir: str) -> None:
        wd = self._dir_to_wd.pop(rel_dir, None)
        if wd is not None:
            self._wd_to_dir.pop(wd, None)
            try:
                self._libc.inotify_rm_watch(self._fd, wd)
            except Exception as exc:  # noqa: BLE001 — degrade, counted
                from ..obs.errors import report_exception

                report_exception(
                    "koordlet.pleg.rm_watch", exc, registry=self.registry
                )

    # -- lifecycle --

    def start(self) -> bool:
        """Initialize inotify, seed state with one scan, and start the
        reader thread. False = unavailable (caller keeps polling)."""
        try:
            libc = self._load_libc()
            fd = libc.inotify_init1(IN_CLOEXEC)
        except (OSError, AttributeError):
            return False
        if fd < 0:
            return False
        self._fd = fd
        ok = False
        for tier in TIER_DIRS:
            if self._add_watch(tier) is not None:
                ok = True
        if not ok:
            os.close(fd)
            self._fd = None
            return False
        # seed through tick() so pods already present at startup FIRE
        # their PodAdded/ContainerAdded events (the polling Pleg's first
        # tick delivered them; silent seeding would lose them), then
        # watch each discovered pod dir; dirs raced during setup surface
        # through the next resync tick
        self.tick()
        for pod_dir in list(self._known):
            self._add_watch(pod_dir)
        self._wake_r, self._wake_w = os.pipe()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pleg-inotify", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._wake_w >= 0:
            try:
                os.write(self._wake_w, b"x")
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for fdesc in (self._fd, self._wake_r, self._wake_w):
            if fdesc is not None and fdesc >= 0:
                try:
                    os.close(fdesc)
                except OSError:
                    pass
        self._fd = None
        self._wake_r = self._wake_w = -1

    # -- reader --

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                ready, _, _ = select.select(
                    [self._fd, self._wake_r], [], [], 1.0
                )
            except (OSError, ValueError):
                return
            if self._stop.is_set():
                return
            if self._fd not in ready:
                continue
            try:
                buf = os.read(self._fd, 65536)
            except OSError as e:
                if e.errno == errno.EAGAIN:
                    continue
                return
            self._consume(buf)

    def _consume(self, buf: bytes) -> None:
        with self._state_lock:
            events, overflow = self._consume_locked(buf)
            # events parsed from this buffer already mutated _known, so
            # they MUST be delivered even on overflow (a resync diff
            # would no longer see them); the resync then recovers
            # whatever the kernel dropped after the overflow marker
            if events:
                self._dispatch(events)
            if overflow:
                self.tick()

    def _consume_locked(self, buf: bytes) -> Tuple[List[Event], bool]:
        events: List[Event] = []
        off = 0
        overflow = False
        while off + _EVENT_HDR.size <= len(buf):
            wd, mask, _cookie, nlen = _EVENT_HDR.unpack_from(buf, off)
            name = buf[
                off + _EVENT_HDR.size : off + _EVENT_HDR.size + nlen
            ].split(b"\0", 1)[0].decode(errors="replace")
            off += _EVENT_HDR.size + nlen
            if mask & IN_Q_OVERFLOW:
                overflow = True
                continue
            if mask & IN_IGNORED:
                continue
            rel = self._wd_to_dir.get(wd)
            if rel is None or not name:
                continue
            created = mask & (IN_CREATE | IN_MOVED_TO)
            deleted = mask & (IN_DELETE | IN_MOVED_FROM)
            if rel in TIER_DIRS:
                if not _is_pod_dir(name):
                    continue
                pod_dir = os.path.join(rel, name)
                if created and mask & IN_ISDIR:
                    if pod_dir not in self._known:
                        self._known[pod_dir] = set()
                        self._add_watch(pod_dir)
                        events.append(Event(EventType.POD_ADDED, pod_dir))
                elif deleted:
                    containers = self._known.pop(pod_dir, None)
                    if containers is not None:
                        for c in sorted(containers):
                            events.append(
                                Event(
                                    EventType.CONTAINER_DELETED, pod_dir, c
                                )
                            )
                        events.append(Event(EventType.POD_DELETED, pod_dir))
                    self._rm_watch(pod_dir)
            else:
                containers = self._known.get(rel)
                if containers is None:
                    continue
                if created and mask & IN_ISDIR and name not in containers:
                    containers.add(name)
                    events.append(
                        Event(EventType.CONTAINER_ADDED, rel, name)
                    )
                elif deleted and name in containers:
                    containers.discard(name)
                    events.append(
                        Event(EventType.CONTAINER_DELETED, rel, name)
                    )
        return events, overflow
