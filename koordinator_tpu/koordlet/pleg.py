"""PLEG: pod lifecycle events from the cgroup filesystem.

Rebuild of ``pkg/koordlet/pleg/`` (``watcher_linux.go:25-30`` inotify on
the kubepods cgroup dirs, handler API ``pleg.go:33-45``): pod/container
cgroup directories appearing or vanishing under the QoS-tier hierarchy
become PodAdded/PodDeleted/ContainerAdded/ContainerDeleted events fanned
out to registered handlers.

The reference registers inotify watches per tier dir; this rebuild diffs a
directory scan per tick, which gives the identical event stream (tests and
the simulator drive ticks; a production deployment ticks at the collect
interval, bounding event latency the same way the reference's inotify
queue drain does).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
from typing import Callable, Dict, List, Set, Tuple

# QoS-tier cgroup parents scanned for pod dirs (the reference watches
# kubepods, kubepods/burstable, kubepods/besteffort).
TIER_DIRS = ("kubepods", "kubepods/burstable", "kubepods/besteffort")


class EventType(enum.Enum):
    POD_ADDED = "PodAdded"
    POD_DELETED = "PodDeleted"
    CONTAINER_ADDED = "ContainerAdded"
    CONTAINER_DELETED = "ContainerDeleted"


@dataclasses.dataclass(frozen=True)
class Event:
    type: EventType
    pod_dir: str                 # tier-relative pod cgroup dir
    container_id: str = ""


Handler = Callable[[Event], None]


def _is_pod_dir(name: str) -> bool:
    return name.startswith("pod")


class Pleg:
    """Directory-diff lifecycle watcher with handler registry."""

    def __init__(self, cgroup_root: str):
        self.cgroup_root = cgroup_root
        self._handlers: List[Tuple[int, Handler]] = []
        self._next_id = 0
        self._known: Dict[str, Set[str]] = {}   # pod_dir -> container ids
        self._lock = threading.Lock()

    def register_handler(self, handler: Handler) -> int:
        """Returns a handler id usable with unregister (pleg.go HandlerID)."""
        with self._lock:
            hid = self._next_id
            self._next_id += 1
            self._handlers.append((hid, handler))
        return hid

    def unregister_handler(self, hid: int) -> None:
        with self._lock:
            self._handlers = [(i, h) for i, h in self._handlers if i != hid]

    def _scan(self) -> Dict[str, Set[str]]:
        seen: Dict[str, Set[str]] = {}
        for tier in TIER_DIRS:
            tier_path = os.path.join(self.cgroup_root, tier)
            try:
                entries = os.listdir(tier_path)
            except OSError:
                continue
            for entry in entries:
                pod_path = os.path.join(tier_path, entry)
                if not _is_pod_dir(entry) or not os.path.isdir(pod_path):
                    continue
                rel = os.path.join(tier, entry)
                try:
                    containers = {
                        c
                        for c in os.listdir(pod_path)
                        if os.path.isdir(os.path.join(pod_path, c))
                    }
                except OSError:
                    containers = set()
                seen[rel] = containers
        return seen

    def tick(self) -> List[Event]:
        """Diff the hierarchy against the last scan; fire + return events."""
        seen = self._scan()
        events: List[Event] = []
        for pod_dir, containers in seen.items():
            old = self._known.get(pod_dir)
            if old is None:
                events.append(Event(EventType.POD_ADDED, pod_dir))
                old = set()
            for c in sorted(containers - old):
                events.append(Event(EventType.CONTAINER_ADDED, pod_dir, c))
            for c in sorted(old - containers):
                events.append(Event(EventType.CONTAINER_DELETED, pod_dir, c))
        for pod_dir in list(self._known):
            if pod_dir not in seen:
                for c in sorted(self._known[pod_dir]):
                    events.append(
                        Event(EventType.CONTAINER_DELETED, pod_dir, c)
                    )
                events.append(Event(EventType.POD_DELETED, pod_dir))
        self._known = seen
        with self._lock:
            handlers = list(self._handlers)
        for event in events:
            for _hid, handler in handlers:
                handler(event)
        return events
