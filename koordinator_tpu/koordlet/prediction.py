"""Peak prediction: exponentially-decayed histograms, vectorized.

Rebuild of ``pkg/koordlet/prediction/`` (``predict_server.go:65-73``) +
``pkg/util/histogram/``: per-subject decayed histograms of observed usage
feed p95/p98 peak estimates into the NodeMetric ``Prediction`` field that
the batchresource overcommit uses. The reference keeps one Go histogram
object per pod/priority/node; here every subject is one row of a shared
(S, B) bucket-weight matrix so decay and percentile extraction are single
vectorized numpy passes over all subjects at once.

Checkpoint/resume mirrors ``prediction/checkpoint.go``: the full matrix +
subject index round-trips through one ``.npz`` file.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np


def default_buckets(max_value: float = 512_000.0, n: int = 128) -> np.ndarray:
    """Exponential bucket upper bounds (reference histogram uses 5%-growth
    exponential buckets)."""
    ratio = (max_value / 1.0) ** (1.0 / (n - 1))
    return np.array([ratio**i for i in range(n)], np.float64)


@dataclasses.dataclass
class PredictorConfig:
    half_life_s: float = 12 * 3600.0   # decay half-life (reference 24h default window)
    buckets: np.ndarray = dataclasses.field(default_factory=default_buckets)
    safety_margin: float = 1.1         # peak multiplier


class PeakPredictor:
    """Decayed-histogram peak predictor over many subjects."""

    def __init__(self, config: Optional[PredictorConfig] = None, capacity: int = 256):
        self.config = config or PredictorConfig()
        b = self.config.buckets.shape[0]
        self._weights = np.zeros((capacity, b), np.float64)
        self._last_decay = np.zeros(capacity, np.float64)
        self._index: Dict[str, int] = {}
        self._free: List[int] = list(range(capacity))

    def _slot(self, subject: str) -> int:
        idx = self._index.get(subject)
        if idx is None:
            if not self._free:
                grow = self._weights.shape[0]
                self._weights = np.vstack(
                    [self._weights, np.zeros_like(self._weights)]
                )
                self._last_decay = np.concatenate(
                    [self._last_decay, np.zeros(grow)]
                )
                self._free = list(range(grow, 2 * grow))
            idx = self._free.pop(0)
            self._index[subject] = idx
        return idx

    def observe(self, subject: str, value: float, ts: float) -> None:
        idx = self._slot(subject)
        if self._last_decay[idx] == 0.0:
            self._last_decay[idx] = ts
        elif ts > self._last_decay[idx]:
            dt = ts - self._last_decay[idx]
            self._weights[idx] *= 0.5 ** (dt / self.config.half_life_s)
            self._last_decay[idx] = ts
        bucket = int(np.searchsorted(self.config.buckets, value, side="left"))
        bucket = min(bucket, self.config.buckets.shape[0] - 1)
        self._weights[idx, bucket] += 1.0

    def observe_many(self, samples: Mapping[str, float], ts: float) -> None:
        for subject, value in samples.items():
            self.observe(subject, value, ts)

    def forget(self, subject: str) -> None:
        """Drop a subject's histogram and recycle its slot (workload/pod
        deletion; the reference GC's pod histograms the same way)."""
        idx = self._index.pop(subject, None)
        if idx is None:
            return
        self._weights[idx] = 0.0
        self._last_decay[idx] = 0.0
        self._free.append(idx)

    def peak(self, subject: str, percentile: float = 95.0) -> Optional[float]:
        idx = self._index.get(subject)
        if idx is None:
            return None
        w = self._weights[idx]
        total = w.sum()
        if total <= 0:
            return None
        cdf = np.cumsum(w) / total
        bucket = int(np.searchsorted(cdf, percentile / 100.0, side="left"))
        bucket = min(bucket, self.config.buckets.shape[0] - 1)
        return float(self.config.buckets[bucket] * self.config.safety_margin)

    def peaks(
        self, percentile: float = 95.0
    ) -> Dict[str, float]:
        """Vectorized peak extraction for ALL subjects at once."""
        if not self._index:
            return {}
        subjects = list(self._index.items())
        rows = np.array([i for _, i in subjects])
        w = self._weights[rows]
        totals = w.sum(axis=1, keepdims=True)
        safe = np.maximum(totals, 1e-12)
        cdf = np.cumsum(w, axis=1) / safe
        buckets = (cdf >= percentile / 100.0).argmax(axis=1)
        values = self.config.buckets[buckets] * self.config.safety_margin
        return {
            name: float(v)
            for (name, _), v, t in zip(subjects, values, totals[:, 0])
            if t > 0
        }

    # ---- checkpoint / resume (prediction/checkpoint.go) ----

    def checkpoint(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                weights=self._weights,
                last_decay=self._last_decay,
                buckets=self.config.buckets,
                index=json.dumps(self._index),
            )
        os.replace(tmp, path)

    @classmethod
    def restore(
        cls, path: str, config: Optional[PredictorConfig] = None
    ) -> "PeakPredictor":
        data = np.load(path, allow_pickle=False)
        cfg = config or PredictorConfig(buckets=data["buckets"])
        self = cls(cfg, capacity=data["weights"].shape[0])
        self._weights = data["weights"]
        self._last_decay = data["last_decay"]
        self._index = json.loads(str(data["index"]))
        used = set(self._index.values())
        self._free = [
            i for i in range(self._weights.shape[0]) if i not in used
        ]
        return self
