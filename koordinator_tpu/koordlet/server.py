"""Koordlet HTTP surface: metrics exposition + audit pull API.

Rebuild of the koordlet's observability endpoints — the Prometheus metrics
registry (``pkg/koordlet/metrics/``) and the audit log's HTTP pull API
(``pkg/koordlet/audit/auditor.go:130-160,230``: GET with ``since`` /
``group`` filters over the ring buffer).
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse
from typing import Optional

from ..utils.metrics import Registry
from .resourceexecutor import Auditor


def koordlet_registry(reg: Optional[Registry] = None) -> Registry:
    """The koordlet metric set (pkg/koordlet/metrics/): node/pod usage
    gauges, BE suppression state, collector health."""
    reg = reg or Registry(namespace="koordlet")
    reg.gauge("node_cpu_usage_milli", "node CPU usage in millicores")
    reg.gauge("node_memory_usage_bytes", "node memory usage")
    reg.gauge("be_cpu_usage_milli", "best-effort tier CPU usage")
    reg.gauge("be_cpu_limit_milli", "current BE suppression allowance")
    reg.counter("be_evictions_total", "BE pods evicted by QoS strategies")
    reg.counter(
        "collect_errors_total", "collector failures", labels=("collector",)
    )
    reg.gauge(
        "collector_last_collect_ts", "last success per collector",
        labels=("collector",),
    )
    reg.counter(
        "retry_attempts_total",
        "retries performed by shared RetryPolicy call sites",
        labels=("site",),
    )
    from ..obs import ensure_exceptions_counter

    ensure_exceptions_counter(reg)
    return reg


class KoordletServer:
    """Serves /metrics, /trace and /apis/v1/audit over HTTP."""

    def __init__(self, registry: Registry, auditor: Auditor, tracer=None):
        from ..obs import Tracer

        self.registry = registry
        self.auditor = auditor
        self.tracer = tracer or Tracer(enabled=False)
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    def dispatch(self, path: str, method: str = "GET", body: str = "") -> tuple[int, str]:
        parsed = urllib.parse.urlparse(path)
        if parsed.path == "/metrics":
            return 200, self.registry.expose()
        if parsed.path == "/trace":
            if method == "POST":
                flag = body.strip()
                if flag not in ("0", "1", "true", "false"):
                    return 400, "bad sampling flag (want 0/1/true/false)"
                self.tracer.enabled = flag in ("1", "true")
                if not self.tracer.enabled:
                    self.tracer.clear()
                return 200, str(self.tracer.enabled)
            return 200, self.tracer.export_json()
        if parsed.path == "/apis/v1/audit":
            qs = urllib.parse.parse_qs(parsed.query)
            since = float(qs.get("since", ["0"])[0])
            group = qs.get("group", [""])[0]
            events = self.auditor.query(since=since, group_prefix=group)
            return 200, json.dumps(
                [
                    {
                        "ts": e.ts,
                        "group": e.group,
                        "file": e.file,
                        "old": e.old,
                        "new": e.new,
                        "reason": e.reason,
                    }
                    for e in events
                ]
            )
        return 404, "not found"

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        srv = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _run(self, method: str):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length).decode() if length else ""
                code, text = srv.dispatch(self.path, method, body)
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def log_message(self, *args):
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
