"""QoS manager: BE suppression / eviction / burst / reconcile strategies.

Rebuild of ``pkg/koordlet/qosmanager/`` strategy plugins:
  * CPUSuppress (``plugins/cpusuppress/cpu_suppress.go:100-108``):
    shrink the BE tier's cpuset/cfs quota so prod keeps headroom:
        beAllowance = nodeAllocatable × threshold% − (nodeUsed − beUsed)
  * CPUEvict / MemoryEvict (``cpuevict``, ``memoryevict``): evict BE pods
    when BE satisfaction or node memory utilization crosses thresholds.
  * CPUBurst (``cpuburst``): grant cfs burst to latency-sensitive pods.
  * CgReconcile (``cgreconcile``): hold the QoS tier root cgroups at their
    baseline knobs so one-off kernel/kubelet drift is healed every tick.
  * Resctrl (``resctrl``): render per-tier RDT L3 way masks + MBA percent
    into resctrl schemata writes.
  * BlkIO (``blkio``): per-tier block-IO throttles.
  * SysReconcile (``sysreconcile``): node-level vm knobs from the NodeSLO
    system strategy.

Each strategy is a pure decision function (fixture-testable exactly like
the reference's table-driven tests) plus a thin applier that renders the
decision into a ResourceExecutor write plan.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..api.types import NodeSLO
from . import resourceexecutor as rex

BE_GROUP = "kubepods/besteffort"


@dataclasses.dataclass
class CPUSuppressDecision:
    #: BE tier cpu allowance in milli-cores (cfs quota basis)
    be_allowance_milli: float
    #: number of cpus for the BE cpuset (ceil of allowance)
    be_cpuset_cpus: int
    suppressed: bool


def cpu_suppress(
    node_allocatable_milli: float,
    node_used_milli: float,
    be_used_milli: float,
    threshold_percent: float,
    min_be_cpus: int = 1,
    sys_used_milli: float | None = None,
    node_reserved_milli: float = 0.0,
    min_threshold_percent: float | None = None,
) -> CPUSuppressDecision:
    """``calculateBESuppressCPU`` (``cpu_suppress.go:136-170``)::

        suppress(BE) = capacity × SLOPercent − pod(non-BE).Used
                       − max(system.Used, node.reserved)

    floored at ``capacity × beCPUMinThresholdPercent`` when that knob is
    set (the reference's ``beCPUMinThreshold``); ``min_be_cpus`` is the
    legacy whole-cpu floor used when no percent floor is given. When
    ``sys_used_milli`` is None, system usage is whatever of
    ``node_used − be_used`` isn't attributed elsewhere (aggregate-input
    mode) and the reserved floor applies to that aggregate."""
    budget = node_allocatable_milli * threshold_percent / 100.0
    if sys_used_milli is None:
        non_be_used = max(node_used_milli - be_used_milli, 0.0)
        allowance = budget - max(non_be_used, node_reserved_milli)
    else:
        pod_non_be = max(node_used_milli - be_used_milli - sys_used_milli, 0.0)
        allowance = budget - pod_non_be - max(sys_used_milli, node_reserved_milli)
    if min_threshold_percent is not None:
        floor = node_allocatable_milli * min_threshold_percent / 100.0
    else:
        floor = min_be_cpus * 1000.0
    allowance = max(allowance, floor)
    n_cpus = max(int(-(-allowance // 1000)), min_be_cpus)  # ceil
    return CPUSuppressDecision(
        be_allowance_milli=allowance,
        be_cpuset_cpus=n_cpus,
        suppressed=allowance < node_allocatable_milli,
    )


def cpu_suppress_plan(
    decision: CPUSuppressDecision,
    total_cpus: int,
    period_us: int = 100_000,
) -> List[Tuple[str, str, str]]:
    """Render the decision as cgroup writes: cfs quota + cpuset width."""
    quota = int(decision.be_allowance_milli / 1000.0 * period_us)
    cpus = min(decision.be_cpuset_cpus, total_cpus)
    cpuset = f"0-{cpus - 1}" if cpus > 1 else "0"
    return [
        (BE_GROUP, rex.CPU_CFS_PERIOD, str(period_us)),
        (BE_GROUP, rex.CPU_CFS_QUOTA, str(quota)),
        (BE_GROUP, rex.CPUSET_CPUS, cpuset),
    ]


@dataclasses.dataclass
class EvictDecision:
    evict: bool
    victims: List[str]          # pod uids, lowest priority first
    reason: str = ""


def memory_evict(
    node_memory_used_mib: float,
    node_memory_capacity_mib: float,
    threshold_percent: float,
    lower_percent: Optional[float],
    be_pods: Sequence[Tuple[str, float, int]],  # (uid, mem_mib, priority)
) -> EvictDecision:
    """``memoryevict``: when node memory crosses the threshold, evict BE
    pods (lowest priority, largest usage first) until below the lower
    watermark (default threshold − 2, reference memory_evict.go)."""
    if node_memory_capacity_mib <= 0:
        return EvictDecision(False, [])
    util = node_memory_used_mib * 100.0 / node_memory_capacity_mib
    if util < threshold_percent:
        return EvictDecision(False, [])
    lower = lower_percent if lower_percent is not None else threshold_percent - 2.0
    target_mib = node_memory_capacity_mib * lower / 100.0
    victims: List[str] = []
    used = node_memory_used_mib
    for uid, mem, _prio in sorted(be_pods, key=lambda x: (x[2], -x[1])):
        if used <= target_mib:
            break
        victims.append(uid)
        used -= mem
    return EvictDecision(
        bool(victims),
        victims,
        reason=f"node memory {util:.1f}% >= {threshold_percent:.1f}%",
    )


def cpu_evict(
    be_cpu_request_milli: float,
    be_cpu_usage_milli: float,
    be_cpu_limit_milli: float,
    satisfaction_threshold: float,
    usage_threshold_percent: float,
    be_pods: Sequence[Tuple[str, float, int]],
    satisfaction_upper_threshold: float | None = None,
) -> EvictDecision:
    """``cpuevict`` (``calculateResourceMilliToRelease``,
    ``cpu_evict.go:262-282``): evict when BE satisfaction (realLimit /
    request) collapses below the lower threshold while BE usage saturates
    its shrunken limit; the release amount is
    ``request × (upperPercent − satisfactionRate)`` — restore satisfaction
    to the upper watermark, not merely the lower bound."""
    if be_cpu_request_milli <= 0 or be_cpu_limit_milli <= 0:
        return EvictDecision(False, [])
    satisfaction = be_cpu_limit_milli / be_cpu_request_milli
    usage_ratio = be_cpu_usage_milli * 100.0 / be_cpu_limit_milli
    if satisfaction >= satisfaction_threshold or usage_ratio < usage_threshold_percent:
        return EvictDecision(False, [])
    upper = (
        satisfaction_upper_threshold
        if satisfaction_upper_threshold is not None
        else satisfaction_threshold
    )
    rate_gap = upper - satisfaction
    if rate_gap <= 0:
        return EvictDecision(False, [])
    # int64(milliRelease) truncation, as the reference casts
    need_release = float(int(be_cpu_request_milli * rate_gap))
    victims: List[str] = []
    released = 0.0
    for uid, req, _prio in sorted(be_pods, key=lambda x: (x[2], -x[1])):
        if released >= need_release:
            break
        victims.append(uid)
        released += req
    return EvictDecision(
        bool(victims),
        victims,
        reason=f"BE satisfaction {satisfaction:.2f} < {satisfaction_threshold:.2f}",
    )


def cpu_burst_plan(
    pod_group: str,
    cpu_limit_milli: float,
    burst_percent: float,
    period_us: int = 100_000,
) -> List[Tuple[str, str, str]]:
    """``cpuburst``: grant cfs burst of burst_percent × limit."""
    burst_us = int(cpu_limit_milli / 1000.0 * period_us * burst_percent / 100.0)
    return [(pod_group, rex.CPU_BURST, str(burst_us))]


class BurstLimiter:
    """Token bucket gating sustained CFS quota bursting (reference
    ``burstLimiter``, ``cpu_burst.go:112-163``): capacity =
    burstPeriodSec × (maxScalePercent − 100); usage ≥ 100% consumes
    ``(usage% − 100) × Δt`` tokens, usage < 60% saves ``(100 − usage%) ×
    Δt``, both clamped to ±capacity; bursting is allowed while the token
    count is positive. ``init_ratio`` replaces the reference's random
    [0, 0.5) initial fill for determinism in tests."""

    CONSUME_AT_PERCENT = 100
    SAVE_BELOW_PERCENT = 60

    def __init__(
        self,
        burst_period_s: float,
        max_scale_percent: float,
        now: float,
        init_ratio: float = 0.25,
    ):
        self.capacity = int(burst_period_s * (max_scale_percent - 100))
        self.tokens = int(self.capacity * init_ratio)
        self.last_update = now
        self.expire_s = 2 * burst_period_s

    def allow(self, now: float, usage_scale_percent: float) -> Tuple[bool, int]:
        past = now - self.last_update
        if usage_scale_percent >= self.CONSUME_AT_PERCENT:
            self.tokens -= int((usage_scale_percent - 100) * int(past))
        elif usage_scale_percent < self.SAVE_BELOW_PERCENT:
            self.tokens += int((100 - usage_scale_percent) * int(past))
        self.tokens = max(min(self.tokens, self.capacity), -self.capacity)
        self.last_update = now
        return self.tokens > 0, self.tokens

    def update_if_changed(
        self, burst_period_s: float, max_scale_percent: float, now: float
    ) -> None:
        new_capacity = int(burst_period_s * (max_scale_percent - 100))
        if new_capacity != self.capacity:
            self.__init__(burst_period_s, max_scale_percent, now)

    def expired(self, now: float) -> bool:
        return now - self.last_update > self.expire_s


def cg_reconcile_plan(total_cpus: int) -> List[Tuple[str, str, str]]:
    """``cgreconcile``: baseline tier-root knobs (burstable unrestricted,
    besteffort at minimum shares) re-asserted every tick; the executor's
    no-op suppression makes the steady state free."""
    return [
        ("kubepods", rex.CPU_SHARES, str(total_cpus * 1024)),
        ("kubepods/burstable", rex.CPU_CFS_QUOTA, "-1"),
        ("kubepods/besteffort", rex.CPU_SHARES, "2"),
        ("kubepods/besteffort", rex.MEMORY_WMARK_RATIO, "95"),
    ]


def _llc_mask(percent: float, cache_ways: int) -> str:
    """Contiguous low-order way mask covering ``percent`` of the LLC
    (resctrl requires contiguous masks; the reference computes the same
    ceil(ways×pct) low mask)."""
    ways = max(int(-(-cache_ways * min(percent, 100.0) // 100.0)), 1)
    return format((1 << ways) - 1, "x")


def resctrl_schemata_plan(
    strategy, cache_ways: int = 11, n_l3_domains: int = 1
) -> List[Tuple[str, str, str]]:
    """``resctrl`` strategy: one control group per QoS tier with an L3 way
    mask + MB percent line per cache domain (resource_manager writing
    ``/sys/fs/resctrl/<tier>/schemata``). Group dirs here are relative to
    the executor root so tests run on a temp dir."""
    from ..api.extension import QoSClass

    plan: List[Tuple[str, str, str]] = []
    for qos, tier in ((QoSClass.LSR, "LSR"), (QoSClass.LS, "LS"), (QoSClass.BE, "BE")):
        llc = strategy.llc_percent.get(qos, 100.0)
        mba = strategy.mba_percent.get(qos, 100.0)
        l3_line = "L3:" + ";".join(
            f"{d}={_llc_mask(llc, cache_ways)}" for d in range(n_l3_domains)
        )
        mb_line = "MB:" + ";".join(
            f"{d}={int(min(mba, 100.0))}" for d in range(n_l3_domains)
        )
        plan.append((f"resctrl/{tier}", "schemata", l3_line + "\n" + mb_line))
    return plan


def blkio_plan(strategy, device: str = "8:0") -> List[Tuple[str, str, str]]:
    """``blkio``: throttle the BE tier's block IO (blk-throttle knobs keyed
    by major:minor, reference blkio strategy)."""
    group = BE_GROUP
    plan: List[Tuple[str, str, str]] = []
    for limit, fname in (
        (strategy.be_read_bps, "blkio.throttle.read_bps_device"),
        (strategy.be_write_bps, "blkio.throttle.write_bps_device"),
        (strategy.be_read_iops, "blkio.throttle.read_iops_device"),
        (strategy.be_write_iops, "blkio.throttle.write_iops_device"),
    ):
        if limit > 0:
            plan.append((group, fname, f"{device} {int(limit)}"))
    return plan


def sys_reconcile_plan(
    strategy, node_memory_capacity_mib: float
) -> List[Tuple[str, str, str]]:
    """``sysreconcile``: vm knobs from NodeSLO systemStrategy; paths are
    relative to the executor root ("proc/sys/vm" under a real root)."""
    min_free_kbytes = int(
        node_memory_capacity_mib * 1024.0 * strategy.min_free_kbytes_factor / 10000.0
    )
    return [
        ("proc/sys/vm", "min_free_kbytes", str(min_free_kbytes)),
        (
            "proc/sys/vm",
            "watermark_scale_factor",
            str(int(strategy.watermark_scale_factor)),
        ),
    ]


from typing import Callable


class QoSManager:
    """Timer-driven strategy runner wiring NodeSLO → decisions → executor.

    ``evict_cb`` performs the actual eviction (kills the pod / calls the
    eviction API); the manager dedups so a pod is evicted once even while
    the pressure condition persists across ticks.
    """

    def __init__(
        self,
        executor: rex.ResourceExecutor,
        total_cpus: int,
        node_allocatable_milli: float,
        node_memory_capacity_mib: float,
        evict_cb: Optional[Callable[[str, str], None]] = None,
        tracer=None,
    ):
        from ..obs import NULL_TRACER

        self.executor = executor
        self.total_cpus = total_cpus
        self.node_allocatable_milli = node_allocatable_milli
        self.node_memory_capacity_mib = node_memory_capacity_mib
        self.evict_cb = evict_cb
        self.tracer = tracer or NULL_TRACER
        #: qosmanager tick counter — the koordlet-side cycle_id joining
        #: strategy spans with the tick that produced them
        self.ticks = 0
        self.evicted: List[str] = []
        self._evicted_set: set = set()

    def _evict(self, victims: Sequence[str], reason: str) -> None:
        for uid in victims:
            if uid in self._evicted_set:
                continue
            self._evicted_set.add(uid)
            self.evicted.append(uid)
            if self.evict_cb is not None:
                self.evict_cb(uid, reason)

    def run_once(
        self,
        slo: NodeSLO,
        node_used_milli: float,
        be_used_milli: float,
        node_memory_used_mib: float,
        be_pods_mem: Sequence[Tuple[str, float, int]] = (),
        be_pods_cpu: Sequence[Tuple[str, float, int]] = (),
        ls_pod_limits: Sequence[Tuple[str, float]] = (),
    ) -> Dict[str, object]:
        """One qosmanager tick (the reference runs each strategy on its own
        wait.Until timer; a single tick keeps tests deterministic).

        be_pods_cpu: (uid, cpu_request_milli, priority) for BE pods;
        ls_pod_limits: (cgroup, cpu_limit_milli) for burst-eligible pods.
        """
        tr = self.tracer
        self.ticks += 1
        tick = self.ticks
        out: Dict[str, object] = {}
        with tr.span("qos_tick", cat="koordlet", cycle=tick):
            if slo.threshold.enable:
                with tr.span("strategy:cpusuppress", cat="koordlet", cycle=tick):
                    dec = cpu_suppress(
                        self.node_allocatable_milli,
                        node_used_milli,
                        be_used_milli,
                        slo.threshold.cpu_suppress_threshold_percent,
                    )
                    self.executor.apply(
                        cpu_suppress_plan(dec, self.total_cpus),
                        reason="cpusuppress",
                    )
                    out["cpu_suppress"] = dec
                with tr.span("strategy:memoryevict", cat="koordlet", cycle=tick):
                    mev = memory_evict(
                        node_memory_used_mib,
                        self.node_memory_capacity_mib,
                        slo.threshold.memory_evict_threshold_percent,
                        slo.threshold.memory_evict_lower_percent,
                        be_pods_mem,
                    )
                    if mev.evict:
                        self._evict(mev.victims, mev.reason)
                    out["memory_evict"] = mev
                # BE satisfaction collapse → CPU eviction (cpuevict)
                with tr.span("strategy:cpuevict", cat="koordlet", cycle=tick):
                    be_request = sum(req for _, req, _ in be_pods_cpu)
                    cev = cpu_evict(
                        be_cpu_request_milli=be_request,
                        be_cpu_usage_milli=be_used_milli,
                        be_cpu_limit_milli=dec.be_allowance_milli,
                        satisfaction_threshold=0.6,
                        usage_threshold_percent=slo.threshold.cpu_evict_be_usage_threshold_percent,
                        be_pods=be_pods_cpu,
                    )
                    if cev.evict:
                        self._evict(cev.victims, cev.reason)
                    out["cpu_evict"] = cev
            if slo.cpu_burst.policy != "none":
                with tr.span("strategy:cpuburst", cat="koordlet", cycle=tick):
                    for group, limit_milli in ls_pod_limits:
                        self.executor.apply(
                            cpu_burst_plan(
                                group, limit_milli, slo.cpu_burst.cpu_burst_percent
                            ),
                            reason="cpuburst",
                        )
            # tier-root baseline reassertion (cgreconcile)
            with tr.span("strategy:cgreconcile", cat="koordlet", cycle=tick):
                self.executor.apply(
                    cg_reconcile_plan(self.total_cpus), reason="cgreconcile"
                )
            if slo.resctrl.enable:
                with tr.span("strategy:resctrl", cat="koordlet", cycle=tick):
                    self.executor.apply(
                        resctrl_schemata_plan(slo.resctrl), reason="resctrl"
                    )
            if slo.blkio.enable:
                with tr.span("strategy:blkio", cat="koordlet", cycle=tick):
                    self.executor.apply(blkio_plan(slo.blkio), reason="blkio")
            if slo.system.enable:
                with tr.span("strategy:sysreconcile", cat="koordlet", cycle=tick):
                    self.executor.apply(
                        sys_reconcile_plan(
                            slo.system, self.node_memory_capacity_mib
                        ),
                        reason="sysreconcile",
                    )
        return out
