"""Metrics-advisor collectors: node cpu/memory/PSI via the native library.

Rebuild of ``pkg/koordlet/metricsadvisor/`` (``framework/plugin.go:28-45``
Collector interface + the 12 collectors under ``collectors/``): each
collector samples a source on a timer and appends to the MetricCache. The
procfs/PSI readers are the native C++ component
(``runtime/csrc/telemetry.cpp``, the analog of the reference's cgo→libpfm4
binding) loaded over ctypes, with a pure-Python fallback when the shared
library hasn't been built (or on non-Linux dev machines).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metriccache as mc

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "runtime",
    "build",
    "libkoordtelemetry.so",
)


class _CpuTimes(ctypes.Structure):
    _fields_ = [
        (name, ctypes.c_double)
        for name in (
            "user",
            "nice_",
            "system_",
            "idle",
            "iowait",
            "irq",
            "softirq",
            "steal",
        )
    ]


def _load_native() -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.koord_read_cpu_times.argtypes = [ctypes.POINTER(_CpuTimes)]
    lib.koord_read_cpu_times.restype = ctypes.c_int
    lib.koord_read_meminfo.argtypes = [
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_meminfo.restype = ctypes.c_int
    lib.koord_read_psi.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_psi.restype = ctypes.c_int
    lib.koord_read_cgroup_cpu_ns.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_cgroup_cpu_ns.restype = ctypes.c_int
    return lib


_NATIVE = _load_native()


def native_available() -> bool:
    return _NATIVE is not None


@dataclasses.dataclass
class CpuTimes:
    busy: float
    total: float


def read_cpu_times() -> Optional[CpuTimes]:
    if _NATIVE is not None:
        out = _CpuTimes()
        if _NATIVE.koord_read_cpu_times(ctypes.byref(out)) == 0:
            busy = (
                out.user
                + out.nice_
                + out.system_
                + out.irq
                + out.softirq
                + out.steal
            )
            total = busy + out.idle + out.iowait
            return CpuTimes(busy=busy, total=total)
        return None
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu "):
                    v = [float(x) for x in line.split()[1:9]]
                    busy = v[0] + v[1] + v[2] + v[5] + v[6] + v[7]
                    return CpuTimes(busy=busy, total=busy + v[3] + v[4])
    except OSError:
        pass
    return None


def read_meminfo() -> Optional[Tuple[float, float]]:
    """(total_mib, available_mib)."""
    if _NATIVE is not None:
        total = ctypes.c_double()
        avail = ctypes.c_double()
        if (
            _NATIVE.koord_read_meminfo(
                ctypes.byref(total), ctypes.byref(avail)
            )
            == 0
        ):
            return total.value / 1024.0, avail.value / 1024.0
        return None
    try:
        total = avail = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1]) / 1024.0
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1]) / 1024.0
        if total is not None and avail is not None:
            return total, avail
    except OSError:
        pass
    return None


def read_psi(resource: str) -> Optional[Tuple[float, float]]:
    """(some_avg10, full_avg10) from /proc/pressure/<resource>."""
    if _NATIVE is not None:
        some = ctypes.c_double()
        full = ctypes.c_double()
        if (
            _NATIVE.koord_read_psi(
                resource.encode(), ctypes.byref(some), ctypes.byref(full)
            )
            == 0
        ):
            return some.value, full.value
        return None
    try:
        some = full = 0.0
        with open(f"/proc/pressure/{resource}") as f:
            found = False
            for line in f:
                parts = dict(
                    kv.split("=") for kv in line.split()[1:] if "=" in kv
                )
                if line.startswith("some"):
                    some = float(parts.get("avg10", 0.0))
                    found = True
                elif line.startswith("full"):
                    full = float(parts.get("avg10", 0.0))
        return (some, full) if found else None
    except OSError:
        return None


def read_cgroup_cpu_ns(root: str, group: str) -> Optional[float]:
    """Cumulative cpu usage of a cgroup in nanoseconds (v1 cpuacct.usage
    or v2 cpu.stat usage_usec)."""
    if _NATIVE is not None and hasattr(_NATIVE, "koord_read_cgroup_cpu_ns"):
        out = ctypes.c_double()
        if (
            _NATIVE.koord_read_cgroup_cpu_ns(
                root.encode(), group.encode(), ctypes.byref(out)
            )
            == 0
        ):
            return out.value
        return None
    for path, scale in (
        (os.path.join(root, group, "cpuacct.usage"), 1.0),
        (os.path.join(root, group, "cpu.stat"), 1000.0),
    ):
        try:
            with open(path) as f:
                if path.endswith("cpuacct.usage"):
                    return float(f.read().strip()) * scale
                for line in f:
                    if line.startswith("usage_usec"):
                        return float(line.split()[1]) * scale
        except (OSError, ValueError):
            continue
    return None


def read_cgroup_memory_mib(root: str, group: str) -> Optional[float]:
    for name in ("memory.current", "memory.usage_in_bytes"):
        try:
            with open(os.path.join(root, group, name)) as f:
                return float(f.read().strip()) / (1024.0 * 1024.0)
        except (OSError, ValueError):
            continue
    return None


class BETierCollector:
    """beresource collector: the BE tier cgroup's cpu/memory usage
    (collectors/beresource). Prod usage is derived as node − BE — exact
    when the tiers partition all pods, which is how the reference's
    kubepods hierarchy is laid out."""

    BE_GROUP = "kubepods/besteffort"

    def __init__(self, cache: mc.MetricCache, cgroup_root: str):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self._last: Optional[Tuple[float, float]] = None  # (ts, cpu_ns)

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        cpu_ns = read_cgroup_cpu_ns(self.cgroup_root, self.BE_GROUP)
        if cpu_ns is not None:
            if self._last is not None:
                last_ts, last_ns = self._last
                dt = now - last_ts
                if dt > 0 and cpu_ns >= last_ns:
                    milli = (cpu_ns - last_ns) / dt / 1e6  # ns/s → milli-cores
                    self.cache.append(mc.BE_CPU_USAGE, "node", now, milli)
                    ok = True
            self._last = (now, cpu_ns)
        mem = read_cgroup_memory_mib(self.cgroup_root, self.BE_GROUP)
        if mem is not None:
            self.cache.append("be_memory_usage", "node", now, mem)
            ok = True
        return ok


class NodeResourceCollector:
    """noderesource collector: cpu (delta of jiffies → milli-cores) and
    memory usage into the cache (collectors/noderesource)."""

    def __init__(self, cache: mc.MetricCache, n_cpus: Optional[int] = None):
        self.cache = cache
        self.n_cpus = n_cpus or os.cpu_count() or 1
        self._last: Optional[Tuple[float, CpuTimes]] = None

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        times = read_cpu_times()
        mem = read_meminfo()
        ok = False
        if times is not None:
            if self._last is not None:
                _last_ts, last = self._last
                dbusy = times.busy - last.busy
                dtotal = times.total - last.total
                if dtotal > 0:
                    util = max(min(dbusy / dtotal, 1.0), 0.0)
                    self.cache.append(
                        mc.NODE_CPU_USAGE,
                        "node",
                        now,
                        util * self.n_cpus * 1000.0,
                    )
                    ok = True
            self._last = (now, times)
        if mem is not None:
            total, avail = mem
            self.cache.append(
                mc.NODE_MEMORY_USAGE, "node", now, max(total - avail, 0.0)
            )
            ok = True
        return ok


class PerformanceCollector:
    """performance collector: PSI pressure gauges (the CPI half of the
    reference needs perf_event_open privileges; PSI is the portable part)."""

    def __init__(self, cache: mc.MetricCache):
        self.cache = cache

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        for resource, metric in (
            ("cpu", mc.NODE_PSI_CPU),
            ("memory", mc.NODE_PSI_MEM),
            ("io", mc.NODE_PSI_IO),
        ):
            psi = read_psi(resource)
            if psi is not None:
                self.cache.append(metric, "node", now, psi[0])
                ok = True
        return ok
