"""Metrics-advisor collectors: node cpu/memory/PSI via the native library.

Rebuild of ``pkg/koordlet/metricsadvisor/`` (``framework/plugin.go:28-45``
Collector interface + the 12 collectors under ``collectors/``): each
collector samples a source on a timer and appends to the MetricCache. The
procfs/PSI readers are the native C++ component
(``runtime/csrc/telemetry.cpp``, the analog of the reference's cgo→libpfm4
binding) loaded over ctypes, with a pure-Python fallback when the shared
library hasn't been built (or on non-Linux dev machines).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metriccache as mc

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "runtime",
    "build",
    "libkoordtelemetry.so",
)


class _CpuTimes(ctypes.Structure):
    _fields_ = [
        (name, ctypes.c_double)
        for name in (
            "user",
            "nice_",
            "system_",
            "idle",
            "iowait",
            "irq",
            "softirq",
            "steal",
        )
    ]


def _build_native() -> bool:
    """One-shot lazy build of the telemetry library (make -C runtime).
    The .so is a build artifact (gitignored), so a fresh checkout arms the
    native path on first use; failure is fine — the pure-Python readers
    take over."""
    import subprocess

    runtime_dir = os.path.dirname(os.path.dirname(_LIB_PATH))
    if not os.path.exists(os.path.join(runtime_dir, "Makefile")):
        return False
    try:
        return (
            subprocess.run(
                ["make", "-C", runtime_dir],
                capture_output=True,
                timeout=60,
            ).returncode
            == 0
        )
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load_native() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB_PATH) and not _build_native():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.koord_read_cpu_times.argtypes = [ctypes.POINTER(_CpuTimes)]
    lib.koord_read_cpu_times.restype = ctypes.c_int
    lib.koord_read_meminfo.argtypes = [
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_meminfo.restype = ctypes.c_int
    lib.koord_read_psi.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_psi.restype = ctypes.c_int
    lib.koord_read_cgroup_cpu_ns.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.koord_read_cgroup_cpu_ns.restype = ctypes.c_int
    dbl = ctypes.POINTER(ctypes.c_double)
    for name, argtypes in (
        ("koord_cpi_open", []),
        ("koord_cpi_read", [dbl, dbl]),
        ("koord_read_pagecache_kib", [dbl]),
        ("koord_read_cgroup_throttled", [ctypes.c_char_p, ctypes.c_char_p, dbl, dbl]),
        ("koord_read_diskstats", [dbl, dbl]),
    ):
        if hasattr(lib, name):
            fn = getattr(lib, name)
            fn.argtypes = argtypes
            fn.restype = ctypes.c_int
    return lib


_NATIVE = _load_native()


def native_available() -> bool:
    return _NATIVE is not None


@dataclasses.dataclass
class CpuTimes:
    busy: float
    total: float


def read_cpu_times() -> Optional[CpuTimes]:
    if _NATIVE is not None:
        out = _CpuTimes()
        if _NATIVE.koord_read_cpu_times(ctypes.byref(out)) == 0:
            busy = (
                out.user
                + out.nice_
                + out.system_
                + out.irq
                + out.softirq
                + out.steal
            )
            total = busy + out.idle + out.iowait
            return CpuTimes(busy=busy, total=total)
        return None
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu "):
                    v = [float(x) for x in line.split()[1:9]]
                    busy = v[0] + v[1] + v[2] + v[5] + v[6] + v[7]
                    return CpuTimes(busy=busy, total=busy + v[3] + v[4])
    except OSError:
        pass
    return None


def read_meminfo() -> Optional[Tuple[float, float]]:
    """(total_mib, available_mib)."""
    if _NATIVE is not None:
        total = ctypes.c_double()
        avail = ctypes.c_double()
        if (
            _NATIVE.koord_read_meminfo(
                ctypes.byref(total), ctypes.byref(avail)
            )
            == 0
        ):
            return total.value / 1024.0, avail.value / 1024.0
        return None
    try:
        total = avail = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1]) / 1024.0
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1]) / 1024.0
        if total is not None and avail is not None:
            return total, avail
    except OSError:
        pass
    return None


def read_psi(resource: str) -> Optional[Tuple[float, float]]:
    """(some_avg10, full_avg10) from /proc/pressure/<resource>."""
    if _NATIVE is not None:
        some = ctypes.c_double()
        full = ctypes.c_double()
        if (
            _NATIVE.koord_read_psi(
                resource.encode(), ctypes.byref(some), ctypes.byref(full)
            )
            == 0
        ):
            return some.value, full.value
        return None
    try:
        some = full = 0.0
        with open(f"/proc/pressure/{resource}") as f:
            found = False
            for line in f:
                parts = dict(
                    kv.split("=") for kv in line.split()[1:] if "=" in kv
                )
                if line.startswith("some"):
                    some = float(parts.get("avg10", 0.0))
                    found = True
                elif line.startswith("full"):
                    full = float(parts.get("avg10", 0.0))
        return (some, full) if found else None
    except OSError:
        return None


def read_cgroup_cpu_ns(root: str, group: str) -> Optional[float]:
    """Cumulative cpu usage of a cgroup in nanoseconds (v1 cpuacct.usage
    or v2 cpu.stat usage_usec)."""
    if _NATIVE is not None and hasattr(_NATIVE, "koord_read_cgroup_cpu_ns"):
        out = ctypes.c_double()
        if (
            _NATIVE.koord_read_cgroup_cpu_ns(
                root.encode(), group.encode(), ctypes.byref(out)
            )
            == 0
        ):
            return out.value
        return None
    for path, scale in (
        (os.path.join(root, group, "cpuacct.usage"), 1.0),
        (os.path.join(root, group, "cpu.stat"), 1000.0),
    ):
        try:
            with open(path) as f:
                if path.endswith("cpuacct.usage"):
                    return float(f.read().strip()) * scale
                for line in f:
                    if line.startswith("usage_usec"):
                        return float(line.split()[1]) * scale
        except (OSError, ValueError):
            continue
    return None


def read_cgroup_memory_mib(root: str, group: str) -> Optional[float]:
    for name in ("memory.current", "memory.usage_in_bytes"):
        try:
            with open(os.path.join(root, group, name)) as f:
                return float(f.read().strip()) / (1024.0 * 1024.0)
        except (OSError, ValueError):
            continue
    return None


class BETierCollector:
    """beresource collector: the BE tier cgroup's cpu/memory usage
    (collectors/beresource). Prod usage is derived as node − BE — exact
    when the tiers partition all pods, which is how the reference's
    kubepods hierarchy is laid out."""

    BE_GROUP = "kubepods/besteffort"

    def __init__(self, cache: mc.MetricCache, cgroup_root: str):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self._last: Optional[Tuple[float, float]] = None  # (ts, cpu_ns)

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        cpu_ns = read_cgroup_cpu_ns(self.cgroup_root, self.BE_GROUP)
        if cpu_ns is not None:
            if self._last is not None:
                last_ts, last_ns = self._last
                dt = now - last_ts
                if dt > 0 and cpu_ns >= last_ns:
                    milli = (cpu_ns - last_ns) / dt / 1e6  # ns/s → milli-cores
                    self.cache.append(mc.BE_CPU_USAGE, "node", now, milli)
                    ok = True
            self._last = (now, cpu_ns)
        mem = read_cgroup_memory_mib(self.cgroup_root, self.BE_GROUP)
        if mem is not None:
            self.cache.append("be_memory_usage", "node", now, mem)
            ok = True
        return ok


class NodeResourceCollector:
    """noderesource collector: cpu (delta of jiffies → milli-cores) and
    memory usage into the cache (collectors/noderesource)."""

    def __init__(self, cache: mc.MetricCache, n_cpus: Optional[int] = None):
        self.cache = cache
        self.n_cpus = n_cpus or os.cpu_count() or 1
        self._last: Optional[Tuple[float, CpuTimes]] = None

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        times = read_cpu_times()
        mem = read_meminfo()
        ok = False
        if times is not None:
            if self._last is not None:
                _last_ts, last = self._last
                dbusy = times.busy - last.busy
                dtotal = times.total - last.total
                if dtotal > 0:
                    util = max(min(dbusy / dtotal, 1.0), 0.0)
                    self.cache.append(
                        mc.NODE_CPU_USAGE,
                        "node",
                        now,
                        util * self.n_cpus * 1000.0,
                    )
                    ok = True
            self._last = (now, times)
        if mem is not None:
            total, avail = mem
            self.cache.append(
                mc.NODE_MEMORY_USAGE, "node", now, max(total - avail, 0.0)
            )
            ok = True
        return ok


class PerformanceCollector:
    """performance collector: PSI pressure gauges + CPI via the native
    perf_event_open group (reference
    ``collectors/performance`` — CPI through cgo→libpfm4, PSI through
    /proc/pressure). CPI silently degrades when perf is unavailable
    (unprivileged container), exactly like the reference's feature gate."""

    def __init__(self, cache: mc.MetricCache):
        self.cache = cache
        self._cpi_armed = False
        self._cpi_last: Optional[Tuple[float, float]] = None  # (cycles, instr)
        if _NATIVE is not None and hasattr(_NATIVE, "koord_cpi_open"):
            self._cpi_armed = _NATIVE.koord_cpi_open() == 0

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        for resource, metric in (
            ("cpu", mc.NODE_PSI_CPU),
            ("memory", mc.NODE_PSI_MEM),
            ("io", mc.NODE_PSI_IO),
        ):
            psi = read_psi(resource)
            if psi is not None:
                self.cache.append(metric, "node", now, psi[0])
                ok = True
        if self._cpi_armed:
            cycles = ctypes.c_double()
            instr = ctypes.c_double()
            if _NATIVE.koord_cpi_read(ctypes.byref(cycles), ctypes.byref(instr)) == 0:
                if self._cpi_last is not None:
                    dc = cycles.value - self._cpi_last[0]
                    di = instr.value - self._cpi_last[1]
                    if di > 0:
                        self.cache.append(mc.NODE_CPI, "node", now, dc / di)
                        ok = True
                self._cpi_last = (cycles.value, instr.value)
        return ok


class PodResourceCollector:
    """podresource collector: per-pod cgroup cpu/memory usage
    (``collectors/podresource``). Pods come from the statesinformer via a
    callable so the collector never holds a stale list."""

    def __init__(self, cache: mc.MetricCache, cgroup_root: str, pods_fn):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self.pods_fn = pods_fn
        self._last: Dict[str, Tuple[float, float]] = {}  # uid -> (ts, cpu_ns)

    def collect(self, now: Optional[float] = None) -> bool:
        from .runtimehooks import pod_cgroup

        now = now if now is not None else time.time()
        ok = False
        live = set()
        for pod in self.pods_fn():
            uid = pod.meta.uid
            live.add(uid)
            group = pod_cgroup(pod)
            cpu_ns = read_cgroup_cpu_ns(self.cgroup_root, group)
            if cpu_ns is not None:
                last = self._last.get(uid)
                if last is not None and now > last[0] and cpu_ns >= last[1]:
                    milli = (cpu_ns - last[1]) / (now - last[0]) / 1e6
                    self.cache.append(mc.POD_CPU_USAGE, uid, now, milli)
                    ok = True
                self._last[uid] = (now, cpu_ns)
            mem = read_cgroup_memory_mib(self.cgroup_root, group)
            if mem is not None:
                self.cache.append(mc.POD_MEMORY_USAGE, uid, now, mem)
                ok = True
        for uid in list(self._last):
            if uid not in live:
                del self._last[uid]
        return ok


class SysResourceCollector:
    """sysresource collector: system (non-pod) usage = node usage − kubepods
    tier usage (``collectors/sysresource`` computes the same residual)."""

    KUBEPODS = "kubepods"

    def __init__(self, cache: mc.MetricCache, cgroup_root: str):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self._last: Optional[Tuple[float, float]] = None

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        cpu_ns = read_cgroup_cpu_ns(self.cgroup_root, self.KUBEPODS)
        pods_milli = None
        if cpu_ns is not None:
            if self._last is not None and now > self._last[0] and cpu_ns >= self._last[1]:
                pods_milli = (cpu_ns - self._last[1]) / (now - self._last[0]) / 1e6
            self._last = (now, cpu_ns)
        node = self.cache.latest(mc.NODE_CPU_USAGE, "node")
        if pods_milli is None or node is None:
            return False
        self.cache.append(
            mc.SYS_CPU_USAGE, "node", now, max(node[1] - pods_milli, 0.0)
        )
        return True


class ResctrlCollector:
    """resctrl collector: RDT last-level-cache occupancy and memory
    bandwidth from the resctrl filesystem (``collectors/resctrl`` reading
    ``mon_data/mon_L3_**/{llc_occupancy,mbm_total_bytes}``)."""

    def __init__(self, cache: mc.MetricCache, resctrl_root: str = "/sys/fs/resctrl"):
        self.cache = cache
        self.resctrl_root = resctrl_root

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        mon = os.path.join(self.resctrl_root, "mon_data")
        try:
            domains = sorted(os.listdir(mon))
        except OSError:
            return False
        llc_total = 0.0
        mbm_total = 0.0
        found = False
        for dom in domains:
            for fname, acc in (("llc_occupancy", "llc"), ("mbm_total_bytes", "mbm")):
                try:
                    with open(os.path.join(mon, dom, fname)) as f:
                        v = float(f.read().strip())
                except (OSError, ValueError):
                    continue
                found = True
                if acc == "llc":
                    llc_total += v
                else:
                    mbm_total += v
        if not found:
            return False
        self.cache.append(mc.NODE_LLC_OCCUPANCY, "node", now, llc_total)
        self.cache.append(mc.NODE_MBM_TOTAL, "node", now, mbm_total)
        return True


class ColdMemoryCollector:
    """coldmemoryresource collector: kidled idle-page stats
    (``collectors/coldmemoryresource`` reads
    ``memory.idle_page_stats`` exported by the Anolis kidled kernel); cold
    memory feeds the batchresource overcommit as reclaimable."""

    def __init__(self, cache: mc.MetricCache, cgroup_root: str):
        self.cache = cache
        self.cgroup_root = cgroup_root

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        path = os.path.join(self.cgroup_root, "memory.idle_page_stats")
        try:
            cold_bytes = 0.0
            with open(path) as f:
                for line in f:
                    # kidled rows: csei/dsei/cfei/dfei <age buckets…>; cold
                    # = pages idle longer than the youngest bucket
                    parts = line.split()
                    if len(parts) > 2 and parts[0] in ("csei", "dsei", "cfei", "dfei"):
                        cold_bytes += sum(float(x) for x in parts[2:])
        except OSError:
            return False
        self.cache.append(
            mc.NODE_COLD_MEMORY, "node", now, cold_bytes / (1024.0 * 1024.0)
        )
        return True


class PagecacheCollector:
    """pagecache collector: Cached bytes from /proc/meminfo
    (``collectors/pagecache``)."""

    def __init__(self, cache: mc.MetricCache):
        self.cache = cache

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        cached_mib: Optional[float] = None
        if _NATIVE is not None and hasattr(_NATIVE, "koord_read_pagecache_kib"):
            out = ctypes.c_double()
            if _NATIVE.koord_read_pagecache_kib(ctypes.byref(out)) == 0:
                cached_mib = out.value / 1024.0
        else:
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        if line.startswith("Cached:"):
                            cached_mib = float(line.split()[1]) / 1024.0
                            break
            except OSError:
                pass
        if cached_mib is None:
            return False
        self.cache.append(mc.NODE_PAGECACHE, "node", now, cached_mib)
        return True


class PodThrottledCollector:
    """podthrottled collector: per-pod CFS throttle ratio
    (``collectors/podthrottled``: nr_throttled / nr_periods deltas)."""

    def __init__(self, cache: mc.MetricCache, cgroup_root: str, pods_fn):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self.pods_fn = pods_fn
        self._last: Dict[str, Tuple[float, float]] = {}  # uid -> (periods, throttled)

    def _read(self, group: str) -> Optional[Tuple[float, float]]:
        if _NATIVE is not None and hasattr(_NATIVE, "koord_read_cgroup_throttled"):
            periods = ctypes.c_double()
            throttled = ctypes.c_double()
            if (
                _NATIVE.koord_read_cgroup_throttled(
                    self.cgroup_root.encode(),
                    group.encode(),
                    ctypes.byref(periods),
                    ctypes.byref(throttled),
                )
                == 0
            ):
                return periods.value, throttled.value
            return None
        try:
            periods = throttled = None
            with open(os.path.join(self.cgroup_root, group, "cpu.stat")) as f:
                for line in f:
                    if line.startswith("nr_periods"):
                        periods = float(line.split()[1])
                    elif line.startswith("nr_throttled"):
                        throttled = float(line.split()[1])
            if periods is not None and throttled is not None:
                return periods, throttled
        except OSError:
            pass
        return None

    def collect(self, now: Optional[float] = None) -> bool:
        from .runtimehooks import pod_cgroup

        now = now if now is not None else time.time()
        ok = False
        live = set()
        for pod in self.pods_fn():
            uid = pod.meta.uid
            live.add(uid)
            stat = self._read(pod_cgroup(pod))
            if stat is None:
                continue
            last = self._last.get(uid)
            if last is not None:
                dp = stat[0] - last[0]
                dt = stat[1] - last[1]
                if dp > 0:
                    self.cache.append(
                        mc.POD_THROTTLED_RATIO, uid, now, min(dt / dp, 1.0)
                    )
                    ok = True
            self._last[uid] = stat
        for uid in list(self._last):
            if uid not in live:
                del self._last[uid]
        return ok


class HostApplicationCollector:
    """hostapplication collector: usage of out-of-band host daemons whose
    cgroups are declared in NodeSLO ``hostApplications``
    (``collectors/hostapplication``)."""

    def __init__(self, cache: mc.MetricCache, cgroup_root: str, apps_fn):
        self.cache = cache
        self.cgroup_root = cgroup_root
        self.apps_fn = apps_fn          # () -> [(name, cgroup_dir)]
        self._last: Dict[str, Tuple[float, float]] = {}

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        for name, group in self.apps_fn():
            cpu_ns = read_cgroup_cpu_ns(self.cgroup_root, group)
            if cpu_ns is None:
                continue
            last = self._last.get(name)
            if last is not None and now > last[0] and cpu_ns >= last[1]:
                milli = (cpu_ns - last[1]) / (now - last[0]) / 1e6
                self.cache.append(mc.HOST_APP_CPU_USAGE, name, now, milli)
                ok = True
            self._last[name] = (now, cpu_ns)
            mem = read_cgroup_memory_mib(self.cgroup_root, group)
            if mem is not None:
                self.cache.append(mc.HOST_APP_MEMORY_USAGE, name, now, mem)
                ok = True
        return ok


class NodeInfoCollector:
    """nodeinfo collector: static node facts (cpu count, memory capacity)
    into the KV side of the cache (``collectors/nodeinfo``)."""

    def __init__(self, cache: mc.MetricCache, n_cpus: Optional[int] = None):
        self.cache = cache
        self.n_cpus = n_cpus or os.cpu_count() or 1

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        self.cache.set_kv("node_info/num_cpus", float(self.n_cpus))
        info = read_meminfo()
        if info is not None:
            self.cache.set_kv("node_info/memory_total_mib", info[0])
        self.cache.set_kv("node_info/last_update", now)
        return True


def _diskstats_skip(name: str) -> bool:
    """Partition / stacked-device rows whose IO the whole-disk row already
    counts (sda1, nvme0n1p1, dm-0, …) — mirror of the native filter."""
    if name.startswith(("loop", "ram", "dm-", "md")):
        return True
    stripped = name.rstrip("0123456789")
    if stripped == name:
        return False
    if stripped.endswith("p") and name.startswith(("nvme", "mmcblk")):
        return True
    return name.startswith(("sd", "hd", "vd", "xvd"))


class NodeStorageInfoCollector:
    """nodestorageinfo collector: disk IO throughput deltas from
    /proc/diskstats (``collectors/nodestorageinfo``)."""

    SECTOR_BYTES = 512.0

    def __init__(self, cache: mc.MetricCache):
        self.cache = cache
        self._last: Optional[Tuple[float, float, float]] = None

    def _read(self) -> Optional[Tuple[float, float]]:
        if _NATIVE is not None and hasattr(_NATIVE, "koord_read_diskstats"):
            r = ctypes.c_double()
            w = ctypes.c_double()
            if _NATIVE.koord_read_diskstats(ctypes.byref(r), ctypes.byref(w)) == 0:
                return r.value, w.value
            return None
        try:
            r_total = w_total = 0.0
            found = False
            with open("/proc/diskstats") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) < 10 or _diskstats_skip(parts[2]):
                        continue
                    r_total += float(parts[5])
                    w_total += float(parts[9])
                    found = True
            return (r_total, w_total) if found else None
        except OSError:
            return None

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        stat = self._read()
        if stat is None:
            return False
        ok = False
        if self._last is not None and now > self._last[0]:
            dt = now - self._last[0]
            read_bps = max(stat[0] - self._last[1], 0.0) * self.SECTOR_BYTES / dt
            write_bps = max(stat[1] - self._last[2], 0.0) * self.SECTOR_BYTES / dt
            self.cache.append(mc.NODE_DISK_READ_BPS, "node", now, read_bps)
            self.cache.append(mc.NODE_DISK_WRITE_BPS, "node", now, write_bps)
            ok = True
        self._last = (now, stat[0], stat[1])
        return ok


class DeviceCollector:
    """devices/{gpu,rdma} collectors: per-device utilization via the
    injectable prober (the reference polls NVML; TPU hosts expose usage
    through their own runtime — both reduce to a (minor, util, mem) sample
    stream)."""

    def __init__(self, cache: mc.MetricCache, sample_fn):
        self.cache = cache
        self.sample_fn = sample_fn      # () -> [(dev_type, minor, util_pct, mem_mib)]

    def collect(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.time()
        ok = False
        for dev_type, minor, util, mem in self.sample_fn():
            self.cache.append(
                mc.DEVICE_UTIL, f"{dev_type}-{minor}", now, float(util)
            )
            self.cache.append(
                mc.DEVICE_MEMORY_USED, f"{dev_type}-{minor}", now, float(mem)
            )
            ok = True
        return ok
