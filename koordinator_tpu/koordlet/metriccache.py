"""Metric cache: in-memory TSDB with vectorized percentile aggregation.

Rebuild of ``pkg/koordlet/metriccache/`` (``tsdb_storage.go:28-115`` embeds
a Prometheus TSDB; ``kv_storage.go`` holds latest values): here a fixed-size
numpy ring buffer per series gives O(1) append and vectorized window
queries — the percentile aggregation the reference computes per query
(p50/p90/p95/p99 for NodeMetric, ``states_nodemetric.go``) is one
``np.percentile`` call over the window slice.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..api.types import AGG_TYPES

#: metric ids (reference metric_resources.go typed resources)
NODE_CPU_USAGE = "node_cpu_usage"          # milli-cores
NODE_MEMORY_USAGE = "node_memory_usage"    # MiB
POD_CPU_USAGE = "pod_cpu_usage"
POD_MEMORY_USAGE = "pod_memory_usage"
BE_CPU_USAGE = "be_cpu_usage"
PROD_CPU_USAGE = "prod_cpu_usage"
PROD_MEMORY_USAGE = "prod_memory_usage"
NODE_CPI = "node_cpi"                      # cycles per instruction
NODE_PSI_CPU = "node_psi_cpu_some_avg10"
NODE_PSI_MEM = "node_psi_mem_some_avg10"
NODE_PSI_IO = "node_psi_io_some_avg10"
SYS_CPU_USAGE = "sys_cpu_usage"            # non-pod system daemons, milli
NODE_LLC_OCCUPANCY = "node_llc_occupancy"  # RDT LLC bytes
NODE_MBM_TOTAL = "node_mbm_total_bytes"    # RDT memory bandwidth
NODE_COLD_MEMORY = "node_cold_memory"      # kidled cold pages, MiB
NODE_PAGECACHE = "node_pagecache"          # Cached, MiB
POD_THROTTLED_RATIO = "pod_throttled_ratio"
HOST_APP_CPU_USAGE = "host_app_cpu_usage"
HOST_APP_MEMORY_USAGE = "host_app_memory_usage"
NODE_DISK_READ_BPS = "node_disk_read_bps"
NODE_DISK_WRITE_BPS = "node_disk_write_bps"
DEVICE_UTIL = "device_util_pct"
DEVICE_MEMORY_USED = "device_memory_used_mib"


class _Ring:
    __slots__ = ("ts", "values", "head", "count")

    def __init__(self, capacity: int):
        self.ts = np.zeros(capacity, np.float64)
        self.values = np.zeros(capacity, np.float32)
        self.head = 0
        self.count = 0

    def append(self, ts: float, value: float) -> None:
        cap = self.ts.shape[0]
        self.ts[self.head] = ts
        self.values[self.head] = value
        self.head = (self.head + 1) % cap
        self.count = min(self.count + 1, cap)

    def window(self, start: float, end: float) -> np.ndarray:
        mask = (self.ts >= start) & (self.ts <= end)
        if self.count < self.ts.shape[0]:
            valid = np.zeros_like(mask)
            valid[: self.count] = True
            mask &= valid
        return self.values[mask]

    def latest(self) -> Optional[Tuple[float, float]]:
        if self.count == 0:
            return None
        idx = (self.head - 1) % self.ts.shape[0]
        return float(self.ts[idx]), float(self.values[idx])

    def compact(self, horizon: float) -> int:
        """Invalidate samples older than ``horizon`` (retention
        truncation). The ring keeps slots — only count/ordering state
        needs repair — so this is a vectorized re-pack of the live
        samples. Returns how many samples were dropped."""
        if self.count == 0 or horizon == float("-inf"):
            return 0
        cap = self.ts.shape[0]
        order = (
            np.arange(self.head - self.count, self.head) % cap
            if self.count
            else np.empty(0, np.int64)
        )
        live = order[self.ts[order] >= horizon]
        dropped = self.count - live.size
        if dropped <= 0:
            return 0
        ts_live = self.ts[live].copy()
        val_live = self.values[live].copy()
        self.ts[: live.size] = ts_live
        self.values[: live.size] = val_live
        self.head = live.size % cap
        self.count = live.size
        return int(dropped)


@dataclasses.dataclass
class AggregateResult:
    avg: float
    count: int
    percentiles: Dict[str, float]


#: reference default: ``TSDBRetentionDuration: 12 * time.Hour``
#: (``pkg/koordlet/metriccache/config.go:50``), enforced by the embedded
#: TSDB (``tsdb_storage.go:117`` RetentionDuration)
DEFAULT_RETENTION_S = 12 * 3600.0


class MetricCache:
    """Thread-safe series store keyed by (metric, subject).

    ``retention_s`` enforces the reference's configured retention
    duration (tsdb_storage.go:117) two ways: queries clamp their window
    to ``newest_sample − retention_s`` in DATA time (synthetic clocks in
    the simulator keep working; data ≈ wall time in production), and
    :meth:`enforce_retention` physically compacts against an explicit
    ``now`` (the daemon passes wall time at report cadence) and drops
    series left empty. Nothing is destroyed on the append hot path, so a
    clock-skewed future sample can hide history only until it is itself
    swept, never erase it."""

    def __init__(
        self,
        capacity_per_series: int = 4096,
        retention_s: float = DEFAULT_RETENTION_S,
    ):
        self.capacity = capacity_per_series
        self.retention_s = float(retention_s)
        self._series: Dict[Tuple[str, str], _Ring] = {}
        self._kv: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _ring(self, metric: str, subject: str) -> _Ring:
        key = (metric, subject)
        ring = self._series.get(key)
        if ring is None:
            ring = _Ring(self.capacity)
            self._series[key] = ring
        return ring

    def _horizon(self, now: float) -> float:
        if self.retention_s <= 0:
            return float("-inf")
        return now - self.retention_s

    def append(
        self, metric: str, subject: str, ts: float, value: float
    ) -> None:
        # O(1): retention is enforced at query time (aggregate's horizon
        # clamp) and by the periodic enforce_retention sweep — per-append
        # compaction keyed on a sample's own ts would both slow the hot
        # path and let one clock-skewed future timestamp wipe a series
        with self._lock:
            self._ring(metric, subject).append(ts, value)

    def append_many(
        self, samples: Sequence[Tuple[str, str, float, float]]
    ) -> None:
        with self._lock:
            for metric, subject, ts, value in samples:
                self._ring(metric, subject).append(ts, value)

    def latest(self, metric: str, subject: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get((metric, subject))
            return ring.latest() if ring else None

    def aggregate(
        self,
        metric: str,
        subject: str,
        start: float,
        end: float,
        percentiles: Sequence[str] = AGG_TYPES,
    ) -> Optional[AggregateResult]:
        """Windowed aggregate: avg + requested percentiles (p50..p99).
        The window never reaches past the series' retention horizon
        (newest sample − retention)."""
        with self._lock:
            ring = self._series.get((metric, subject))
            if ring is None:
                return None
            if self.retention_s > 0:
                newest = ring.latest()
                if newest is not None:
                    start = max(start, self._horizon(newest[0]))
            values = ring.window(start, end)
        if values.size == 0:
            return None
        pcts = [float(p[1:]) for p in percentiles]
        results = np.percentile(values, pcts) if pcts else []
        return AggregateResult(
            avg=float(values.mean()),
            count=int(values.size),
            percentiles={
                name: float(v) for name, v in zip(percentiles, results)
            },
        )

    # KV store (reference kv_storage.go) for non-timeseries state
    def set_kv(self, key: str, value: object) -> None:
        with self._lock:
            self._kv[key] = value

    def get_kv(self, key: str) -> Optional[object]:
        with self._lock:
            return self._kv.get(key)

    def gc(self, before: float) -> int:
        """Drop series whose newest sample predates ``before``."""
        with self._lock:
            dead = [
                k
                for k, ring in self._series.items()
                if (ring.latest() or (0.0, 0.0))[0] < before
            ]
            for k in dead:
                del self._series[k]
            return len(dead)

    def enforce_retention(self, now: Optional[float] = None) -> Tuple[int, int]:
        """Retention sweep (the TSDB's periodic head/block truncation):
        compact every series to ``now − retention`` and drop those left
        empty. ``now`` defaults to wall time (the daemon calls this at
        report cadence). Returns ``(samples_dropped, series_dropped)``."""
        if now is None:
            import time as _t

            now = _t.time()
        horizon = self._horizon(now)
        samples = 0
        with self._lock:
            dead = []
            for key, ring in self._series.items():
                samples += ring.compact(horizon)
                if ring.count == 0:
                    dead.append(key)
            for key in dead:
                del self._series[key]
            return samples, len(dead)

    # ---- checkpoint / restore ----
    # The reference embeds a Prometheus TSDB with an on-disk WAL
    # (tsdb_storage.go), which is what makes koordlet stateless-restartable
    # (SURVEY §5). The rebuild's analog: snapshot every ring to one
    # atomic npz; the KV side is ephemeral (it mirrors /proc facts that
    # re-collect on the first tick).

    def checkpoint(self, path: str) -> None:
        import json
        import os

        with self._lock:
            keys = list(self._series)
            arrays = {}
            for i, key in enumerate(keys):
                ring = self._series[key]
                # copy under the lock: serialization happens outside it,
                # and a concurrent insert mutating the live rings would
                # tear the checkpoint (values vs saved head/count)
                arrays[f"ts_{i}"] = ring.ts.copy()
                arrays[f"values_{i}"] = ring.values.copy()
                arrays[f"state_{i}"] = np.asarray([ring.head, ring.count])
            arrays["keys"] = np.frombuffer(
                json.dumps(keys).encode(), dtype=np.uint8
            )
        # unique temp name: concurrent checkpoints to the same path must
        # not race on a shared ".tmp" (both writing, one os.replace
        # winning and the other crashing on the vanished file)
        import tempfile

        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(path) + ".", dir=os.path.dirname(path) or "."
        )
        try:
            # mkstemp creates 0600; restore open()'s umask-default mode so
            # sidecar readers keep access after os.replace carries it over
            os.fchmod(fd, 0o644)
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(f, **arrays)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    @classmethod
    def restore(
        cls,
        path: str,
        capacity_per_series: int = 4096,
        retention_s: float = DEFAULT_RETENTION_S,
    ) -> "MetricCache":
        """Rebuild from a checkpoint; an unreadable file yields an empty
        cache (a restart must never be blocked on history)."""
        import json

        cache = cls(
            capacity_per_series=capacity_per_series, retention_s=retention_s
        )
        try:
            with np.load(path) as data:
                keys = json.loads(bytes(data["keys"]).decode())
                for i, key in enumerate(keys):
                    ring = _Ring(data[f"ts_{i}"].shape[0])
                    ring.ts = data[f"ts_{i}"].copy()
                    ring.values = data[f"values_{i}"].copy()
                    head, count = (int(x) for x in data[f"state_{i}"])
                    ring.head, ring.count = head, count
                    cache._series[tuple(key)] = ring
        except (OSError, KeyError, ValueError):
            return cls(
                capacity_per_series=capacity_per_series,
                retention_s=retention_s,
            )
        return cache
