"""Koordlet daemon: wiring of collectors → cache → reporter → QoS loops.

Rebuild of ``pkg/koordlet/koordlet.go:63-210`` (construct in dependency
order: executor → metriccache → statesinformer → metricsadvisor →
predictServer → qosmanager → runtimehooks) and the NodeMetric reporter
(``statesinformer/impl/states_nodemetric.go:212``: every report interval,
aggregate the TSDB window into NodeMetric.status).

The daemon is tick-driven rather than timer-thread-driven so tests (and
the simulator) advance it deterministically; ``run()`` wraps ticks in a
wall-clock loop for real deployment.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import (
    AGG_TYPES,
    NodeMetric,
    NodeSLO,
    ObjectMeta,
    Pod,
    ResourceMetric,
)
from ..api import extension as ext
from . import collectors as col
from . import metriccache as mc
from . import qosmanager as qos
from . import resourceexecutor as rex
from . import runtimehooks as hooks
from .pleg import InotifyPleg
from .prediction import PeakPredictor
from .server import KoordletServer, koordlet_registry
from .statesinformer import StatesInformer, StateType


@dataclasses.dataclass
class KoordletConfig:
    node_name: str = "node-local"
    collect_interval_s: float = 1.0
    report_interval_s: float = 60.0          # states_nodemetric.go:61-66
    aggregate_window_s: float = 300.0
    cgroup_root: str = "/sys/fs/cgroup"
    proc_root: str = "/proc"
    sys_root: str = "/sys"
    #: kubelet /pods pull source ("" disables; see statesinformer.KubeletStub)
    kubelet_addr: str = ""
    kubelet_port: int = 10255
    n_cpus: Optional[int] = None
    node_allocatable_milli: float = 0.0      # 0 = n_cpus × 1000
    node_memory_capacity_mib: float = 0.0
    #: directory for TSDB + prediction checkpoints ("" disables — the
    #: agent then restarts with empty history, like the reference without
    #: its WAL dir); checkpoints land on every report tick
    checkpoint_dir: str = ""


class NodeMetricReporter:
    """Aggregates the cache window into a NodeMetric object."""

    def __init__(self, cache: mc.MetricCache, config: KoordletConfig):
        self.cache = cache
        self.config = config

    def report(self, now: Optional[float] = None) -> Optional[NodeMetric]:
        now = now if now is not None else time.time()
        start = now - self.config.aggregate_window_s
        cpu = self.cache.aggregate(mc.NODE_CPU_USAGE, "node", start, now)
        mem = self.cache.aggregate(mc.NODE_MEMORY_USAGE, "node", start, now)
        if cpu is None and mem is None:
            return None

        def usage(res, agg):
            return {} if agg is None else {res: agg.avg}

        aggregated = {}
        for pct in AGG_TYPES:
            aggregated[pct] = ResourceMetric(
                usage={
                    **(
                        {ext.RES_CPU: cpu.percentiles[pct]}
                        if cpu is not None
                        else {}
                    ),
                    **(
                        {ext.RES_MEMORY: mem.percentiles[pct]}
                        if mem is not None
                        else {}
                    ),
                }
            )
        prod_cpu = self.cache.aggregate(mc.PROD_CPU_USAGE, "node", start, now)
        prod_mem = self.cache.aggregate(mc.PROD_MEMORY_USAGE, "node", start, now)
        return NodeMetric(
            meta=ObjectMeta(name=self.config.node_name),
            node_usage=ResourceMetric(
                usage={
                    **usage(ext.RES_CPU, cpu),
                    **usage(ext.RES_MEMORY, mem),
                }
            ),
            prod_usage=ResourceMetric(
                usage={
                    **usage(ext.RES_CPU, prod_cpu),
                    **usage(ext.RES_MEMORY, prod_mem),
                }
            ),
            aggregated=aggregated,
            update_time=now,
            report_interval_s=self.config.report_interval_s,
            aggregate_window_s=self.config.aggregate_window_s,
        )


class Koordlet:
    """The node agent. Construction order mirrors koordlet.go:75-137."""

    def __init__(
        self, config: Optional[KoordletConfig] = None, chaos=None
    ):
        from ..chaos import NULL_INJECTOR
        from ..utils.retry import RetryPolicy

        self.config = config or KoordletConfig()
        #: fault injector (chaos points ``koordlet.collect_tick`` /
        #: ``koordlet.qos_tick``); NULL when no chaos is wired
        self.chaos = chaos or NULL_INJECTOR
        #: backoff for the wall-clock loop after consecutive tick
        #: failures (shared RetryPolicy; effectively unlimited attempts
        #: — the agent must keep trying, just not hot-spin)
        self.tick_retry = RetryPolicy(
            max_attempts=1 << 30, base_delay_s=0.5, max_delay_s=30.0
        )
        import os

        n_cpus = self.config.n_cpus or os.cpu_count() or 1
        alloc_milli = self.config.node_allocatable_milli or n_cpus * 1000.0
        mem_cap = self.config.node_memory_capacity_mib
        if mem_cap <= 0:
            info = col.read_meminfo()
            mem_cap = info[0] if info else 1024.0

        from ..obs import Tracer

        self.executor = rex.ResourceExecutor(self.config.cgroup_root)
        self.metric_cache = mc.MetricCache()
        self.registry = koordlet_registry()
        #: agent-wide cycle tracer (sampling off by default; the server's
        #: POST /trace flips it) — collector and QoS loops feed it
        self.tracer = Tracer(enabled=False)
        self.server = KoordletServer(
            self.registry, self.executor.auditor, tracer=self.tracer
        )
        # inotify watcher (kernel-latency lifecycle events, reference
        # watcher_linux.go); collect_tick's polling diff stays as the
        # periodic resync and as the full fallback when start() fails
        self.pleg = InotifyPleg(self.config.cgroup_root, registry=self.registry)
        # statesinformer is the single state source; the daemon's loops are
        # its registered consumers (koordlet.go wires the same dependency).
        self.informer = StatesInformer(self.config.node_name)
        self.informer.callbacks.register(
            StateType.ALL_PODS, "qos-reconciler", self._on_pods
        )
        self.informer.callbacks.register(
            StateType.NODE_SLO, "qos-strategy", self._on_node_slo
        )
        #: out-of-band host daemon cgroups (NodeSLO hostApplications) and
        #: accelerator samplers are injectable; defaults are empty.
        self.host_apps: List[Tuple[str, str]] = []
        self.device_sampler = lambda: []
        root = self.config.cgroup_root
        self.collectors = [
            col.NodeResourceCollector(self.metric_cache, n_cpus),
            col.PerformanceCollector(self.metric_cache),
            col.BETierCollector(self.metric_cache, root),
            col.PodResourceCollector(self.metric_cache, root, self.informer.pods),
            col.SysResourceCollector(self.metric_cache, root),
            col.ResctrlCollector(self.metric_cache),
            col.ColdMemoryCollector(self.metric_cache, root),
            col.PagecacheCollector(self.metric_cache),
            col.PodThrottledCollector(self.metric_cache, root, self.informer.pods),
            col.HostApplicationCollector(
                self.metric_cache, root, lambda: self.host_apps
            ),
            col.NodeInfoCollector(self.metric_cache, n_cpus),
            col.NodeStorageInfoCollector(self.metric_cache),
            col.DeviceCollector(self.metric_cache, lambda: self.device_sampler()),
        ]
        self.predictor = PeakPredictor()
        self.reporter = NodeMetricReporter(self.metric_cache, self.config)
        self.qos = qos.QoSManager(
            self.executor,
            total_cpus=n_cpus,
            node_allocatable_milli=alloc_milli,
            node_memory_capacity_mib=mem_cap,
            tracer=self.tracer,
        )
        #: collect-tick counter: the cycle_id stamped on collector spans
        self._collect_seq = 0
        # kernel feature probes gate hook plans on host support
        # (system.InitSupportConfigs analog, koordlet.go:84)
        from .system import KernelProbes, SystemConfig

        self.probes = KernelProbes(
            SystemConfig(
                proc_root=self.config.proc_root,
                sys_root=self.config.sys_root,
                cgroup_root=self.config.cgroup_root,
            )
        )
        self.reconciler = hooks.Reconciler(self.executor, probes=self.probes)
        #: lifecycle-path NRI server sharing the executor; kept in sync
        #: with the reconciler's cpuset rule below so pre-start writes use
        #: the same shared pools as the periodic reconcile
        self.nri = hooks.NRIServer(self.executor)
        # the cpuset shared-pool rule re-parses on every topology report
        # (reference hooks/cpuset parseRule on the NodeTopology callback)

        def _on_topology(topo):
            self.reconciler.set_topology(topo)
            self.nri.set_topology(topo)

        self.informer.callbacks.register(
            StateType.NODE_TOPOLOGY, "cpuset-rule", _on_topology
        )
        self.node_slo: NodeSLO = NodeSLO(meta=ObjectMeta(name=self.config.node_name))
        self.pods: List[Pod] = []
        self._last_report = 0.0

    # ---- state inputs (statesinformer callbacks) ----

    def _on_node_slo(self, slo: object) -> None:
        self.node_slo = slo  # type: ignore[assignment]

    def _on_pods(self, pods: object) -> None:
        self.pods = list(pods)  # type: ignore[arg-type]
        self.reconciler.reconcile(self.pods)

    def update_node_slo(self, slo: NodeSLO) -> None:
        self.informer.set_node_slo(slo)

    def update_pods(self, pods: Sequence[Pod]) -> None:
        self.informer.set_pods(pods)

    # ---- loops ----

    def collect_tick(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        self.chaos.fire("koordlet.collect_tick")
        self._collect_seq += 1
        tick = self._collect_seq
        tr = self.tracer
        with tr.span("collect_tick", cat="koordlet", cycle=tick):
            self.pleg.tick()
            for collector in self.collectors:
                name = type(collector).__name__
                # False means "nothing to collect" (no RDT, first delta
                # tick, empty sampler, …) — only an exception is a
                # collector failure.
                with tr.span(
                    f"collect:{name}", cat="koordlet", cycle=tick
                ):
                    try:
                        ok = collector.collect(now)
                    except Exception as exc:  # noqa: BLE001 — degrade, counted
                        from ..obs.errors import report_exception

                        report_exception(
                            f"koordlet.collector.{name}",
                            exc,
                            registry=self.registry,
                        )
                        self.registry.get("collect_errors_total").labels(
                            collector=name
                        ).inc()
                        continue
                if ok:
                    self.registry.get("collector_last_collect_ts").set(
                        now, collector=name
                    )
        latest = self.metric_cache.latest(mc.NODE_CPU_USAGE, "node")
        if latest is not None:
            self.predictor.observe(f"node/{self.config.node_name}", latest[1], now)
            self.registry.get("node_cpu_usage_milli").set(latest[1])
        mem_latest = self.metric_cache.latest(mc.NODE_MEMORY_USAGE, "node")
        if mem_latest is not None:
            self.registry.get("node_memory_usage_bytes").set(mem_latest[1])
        be_latest = self.metric_cache.latest(mc.BE_CPU_USAGE, "node")
        if be_latest is not None:
            self.registry.get("be_cpu_usage_milli").set(be_latest[1])
        # derive prod tier = node − BE (exact when the kubepods hierarchy
        # partitions pods into tiers, as the reference's layout does)
        be = self.metric_cache.latest(mc.BE_CPU_USAGE, "node")
        if latest is not None:
            be_v = be[1] if be is not None and be[0] >= latest[0] - 5 else 0.0
            self.metric_cache.append(
                mc.PROD_CPU_USAGE, "node", now, max(latest[1] - be_v, 0.0)
            )
        node_mem = self.metric_cache.latest(mc.NODE_MEMORY_USAGE, "node")
        be_mem = self.metric_cache.latest("be_memory_usage", "node")
        if node_mem is not None:
            be_v = (
                be_mem[1]
                if be_mem is not None and be_mem[0] >= node_mem[0] - 5
                else 0.0
            )
            self.metric_cache.append(
                mc.PROD_MEMORY_USAGE, "node", now, max(node_mem[1] - be_v, 0.0)
            )

    def qos_tick(self, now: Optional[float] = None) -> Dict[str, object]:
        now = now if now is not None else time.time()
        self.chaos.fire("koordlet.qos_tick")
        window = now - 30.0
        cpu = self.metric_cache.aggregate(mc.NODE_CPU_USAGE, "node", window, now)
        mem = self.metric_cache.aggregate(mc.NODE_MEMORY_USAGE, "node", window, now)
        be = self.metric_cache.aggregate(mc.BE_CPU_USAGE, "node", window, now)
        be_pods = [p for p in self.pods if p.qos == ext.QoSClass.BE]
        be_pods_mem = [
            (
                p.meta.uid,
                p.spec.requests.get(ext.RES_BATCH_MEMORY, 0.0),
                p.spec.priority or 0,
            )
            for p in be_pods
        ]
        be_pods_cpu = [
            (
                p.meta.uid,
                p.spec.requests.get(
                    ext.RES_BATCH_CPU, p.spec.requests.get(ext.RES_CPU, 0.0)
                ),
                p.spec.priority or 0,
            )
            for p in be_pods
        ]
        from . import runtimehooks as hooks

        ls_pod_limits = [
            (hooks.pod_cgroup(p), p.spec.limits.get(ext.RES_CPU, 0.0))
            for p in self.pods
            if p.qos == ext.QoSClass.LS and p.spec.limits.get(ext.RES_CPU, 0.0) > 0
        ]
        return self.qos.run_once(
            self.node_slo,
            node_used_milli=cpu.avg if cpu else 0.0,
            be_used_milli=be.avg if be else 0.0,
            node_memory_used_mib=mem.avg if mem else 0.0,
            be_pods_mem=be_pods_mem,
            be_pods_cpu=be_pods_cpu,
            ls_pod_limits=ls_pod_limits,
        )

    def report_tick(self, now: Optional[float] = None) -> Optional[NodeMetric]:
        now = now if now is not None else time.time()
        if now - self._last_report < self.config.report_interval_s:
            return None
        self._last_report = now
        # retention sweep at report cadence (the TSDB's periodic
        # truncation, tsdb_storage.go:117 RetentionDuration)
        self.metric_cache.enforce_retention(now)
        self._checkpoint()
        return self.reporter.report(now)

    def _checkpoint(self) -> None:
        """Persist TSDB rings + prediction histograms so a restart resumes
        with history (reference: tsdb WAL + prediction/checkpoint.go)."""
        import os

        cdir = self.config.checkpoint_dir
        if not cdir:
            return
        os.makedirs(cdir, exist_ok=True)
        try:
            self.metric_cache.checkpoint(os.path.join(cdir, "tsdb.npz"))
            self.predictor.checkpoint(os.path.join(cdir, "prediction.npz"))
        except OSError:
            pass  # a full disk must not kill the QoS loops

    def restore_checkpoints(self) -> bool:
        """Adopt checkpointed state if present; returns True if any was."""
        import os

        cdir = self.config.checkpoint_dir
        if not cdir:
            return False
        restored = False
        tsdb = os.path.join(cdir, "tsdb.npz")
        if os.path.exists(tsdb):
            cache = mc.MetricCache.restore(tsdb)
            self.metric_cache._series = cache._series
            restored = True
        pred = os.path.join(cdir, "prediction.npz")
        if os.path.exists(pred):
            try:
                self.predictor = PeakPredictor.restore(pred)
                restored = True
            except (OSError, ValueError, KeyError):
                pass
        return restored

    def run(self, duration_s: float = float("inf")) -> None:
        """Wall-clock loop for real deployment. With a kubelet address
        configured, each report interval also re-pulls the pod list from
        the kubelet's /pods endpoint (impl/kubelet_stub.go flow); a failed
        pull keeps the previous view."""
        from .statesinformer import KubeletStub

        stub = None
        if self.config.kubelet_addr:
            stub = KubeletStub(
                addr=self.config.kubelet_addr,
                port=self.config.kubelet_port,
                registry=self.registry,
            )
        deadline = time.time() + duration_s
        last_pull = 0.0
        # kernel-latency lifecycle events between ticks; the per-tick
        # polling diff doubles as the periodic resync (and the only
        # source when inotify is unavailable)
        inotify_on = self.pleg.start()
        #: consecutive tick failures — drives the RetryPolicy backoff (a
        #: persistently failing tick must degrade to a slow retry loop,
        #: never a hot spin and never a dead agent)
        tick_failures = 0
        try:
            while time.time() < deadline:
                now = time.time()
                if (
                    stub is not None
                    and now - last_pull >= self.config.report_interval_s
                ):
                    # retry at the collect cadence until a pull succeeds —
                    # a transient kubelet outage must not blind the pod
                    # view for a whole report interval
                    if stub.sync_into(self.informer):
                        last_pull = now
                try:
                    self.collect_tick(now)
                    self.qos_tick(now)
                    self.report_tick(now)
                except Exception as exc:  # noqa: BLE001 — degrade, counted
                    from ..obs.errors import report_exception

                    report_exception(
                        "koordlet.tick", exc, registry=self.registry
                    )
                    tick_failures += 1
                    retries = self.registry.get("retry_attempts_total")
                    if retries is not None:
                        retries.labels(site="koordlet.tick").inc()
                    time.sleep(
                        self.tick_retry.delay_for(tick_failures - 1)
                    )
                    continue
                tick_failures = 0
                time.sleep(self.config.collect_interval_s)
        finally:
            if inotify_on:
                self.pleg.stop()
