"""Kernel/system feature probing.

Rebuild of the reference's ``pkg/koordlet/util/system`` probe layer
(``core_sched.go:275-294`` IsCoreSchedSupported, sysctl helpers, PSI /
resctrl / kidled availability checks): node features are PROBED once and
hooks that need an unsupported kernel interface are gated off, instead of
emitting writes that fail or silently no-op on the host
(VERDICT r1: the rebuild's hooks emitted core-sched writes
unconditionally).

All roots are injectable so tests run against a fake filesystem, exactly
like the reference's fake cgroupfs test helpers (SURVEY §4).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class SystemConfig:
    proc_root: str = "/proc"
    sys_root: str = "/sys"
    cgroup_root: str = "/sys/fs/cgroup"


class KernelProbes:
    """Lazy, cached feature probes against the (possibly fake) host fs."""

    def __init__(self, config: Optional[SystemConfig] = None):
        self.config = config or SystemConfig()
        self._cache: dict = {}

    def _cached(self, key, fn):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]

    # ---- raw helpers ----

    def sysctl_path(self, name: str) -> str:
        """/proc/sys path for a dotted sysctl name (kernel.sched_core →
        /proc/sys/kernel/sched_core)."""
        return os.path.join(
            self.config.proc_root, "sys", *name.split(".")
        )

    def read_sysctl(self, name: str) -> Optional[str]:
        try:
            with open(self.sysctl_path(name)) as f:
                return f.read().strip()
        except OSError:
            return None

    def _sched_features(self) -> Optional[str]:
        for root in (
            os.path.join(self.config.sys_root, "kernel", "debug"),
            os.path.join(self.config.proc_root, ".."),  # unlikely fallback
        ):
            try:
                with open(os.path.join(root, "sched_features")) as f:
                    return f.read()
            except OSError:
                continue
        return None

    # ---- feature probes (each mirrors a reference gate) ----

    def core_sched_supported(self) -> tuple[bool, str]:
        """IsCoreSchedSupported (``core_sched.go:275-294``): sysctl
        ``kernel.sched_core`` exists, or sched_features carries
        CORE_SCHED/NO_CORE_SCHED."""

        def probe():
            if os.path.exists(self.sysctl_path("kernel.sched_core")):
                return True, "sysctl supported"
            feats = self._sched_features()
            if feats is None:
                return False, "sched_features unavailable"
            if "CORE_SCHED" in feats:  # matches NO_CORE_SCHED too
                return True, "sched_features supported"
            return False, "not supported neither by sysctl nor by sched_features"

        return self._cached("core_sched", probe)

    def psi_supported(self) -> bool:
        """/proc/pressure present (psi.go probe; CPI/PSI collectors)."""
        return self._cached(
            "psi",
            lambda: os.path.exists(
                os.path.join(self.config.proc_root, "pressure", "cpu")
            ),
        )

    def resctrl_supported(self) -> bool:
        """resctrl filesystem mounted with a schemata file (resctrl.go)."""
        return self._cached(
            "resctrl",
            lambda: os.path.exists(
                os.path.join(self.config.sys_root, "fs", "resctrl", "schemata")
            ),
        )

    def kidled_supported(self) -> bool:
        """Anolis kidled cold-page tracking (kidled_util.go)."""
        return self._cached(
            "kidled",
            lambda: os.path.exists(
                os.path.join(
                    self.config.sys_root,
                    "kernel",
                    "mm",
                    "kidled",
                    "scan_period_in_seconds",
                )
            ),
        )

    def bvt_supported(self) -> bool:
        """group-identity bvt interface (cpu.bvt_warp_ns in cgroupfs)."""
        return self._cached(
            "bvt",
            lambda: os.path.exists(
                os.path.join(self.config.cgroup_root, "cpu.bvt_warp_ns")
            )
            or os.path.exists(
                os.path.join(self.config.cgroup_root, "cpu", "cpu.bvt_warp_ns")
            ),
        )

    def cgroup_v2(self) -> bool:
        """Unified hierarchy probe (cgroup-driver InitSupportConfigs)."""
        return self._cached(
            "cgv2",
            lambda: os.path.exists(
                os.path.join(self.config.cgroup_root, "cgroup.controllers")
            ),
        )

    def unsupported_plan_files(self) -> Optional[frozenset]:
        """The cgroup file names whose writes the kernel would NOT accept
        (a blocklist), or None when every probe passes (no filtering
        needed). The runtimehooks reconciler drops plan entries whose
        file is in this set."""
        from . import resourceexecutor as rex

        blocked = set()
        if not self.core_sched_supported()[0]:
            blocked.add(rex.CORE_SCHED_COOKIE)
        if not self.bvt_supported():
            blocked.add(rex.CPU_BVT)
        if not self.resctrl_supported():
            blocked.add("resctrl.group")
        if not blocked:
            return None
        return frozenset(blocked)
