"""Resource executor: serialized, audited cgroup reads/writes.

Rebuild of ``pkg/koordlet/resourceexecutor/`` (``executor.go``,
``updater.go`` merge/leveled updates, ``cgroup.go``) + the audit subsystem
(``pkg/koordlet/audit/auditor.go:56,130-160,230``): every cgroup mutation
goes through one executor that caches current values (skip no-op writes),
records an audit event in a ring buffer, and writes through a pluggable
cgroup root — tests point it at a temp dir exactly like the reference's
fake cgroupfs helpers (SURVEY §4).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple
from collections import deque

# cgroup v1-style resource files (reference util/system resource types)
CPU_SHARES = "cpu.shares"
CPU_CFS_QUOTA = "cpu.cfs_quota_us"
CPU_CFS_PERIOD = "cpu.cfs_period_us"
CPU_BURST = "cpu.cfs_burst_us"
CPU_BVT = "cpu.bvt_warp_ns"            # group identity (Anolis bvt)
CPUSET_CPUS = "cpuset.cpus"
MEMORY_LIMIT = "memory.limit_in_bytes"
MEMORY_WMARK_RATIO = "memory.wmark_ratio"
CORE_SCHED_COOKIE = "core_sched.cookie"


@dataclasses.dataclass
class AuditEvent:
    ts: float
    group: str       # cgroup relative dir (e.g. kubepods/burstable/pod-x)
    file: str
    old: Optional[str]
    new: str
    reason: str


class Auditor:
    """Ring-buffer audit log with query API (auditor.go)."""

    def __init__(self, capacity: int = 2048):
        self._events: Deque[AuditEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, event: AuditEvent) -> None:
        with self._lock:
            self._events.append(event)

    def query(
        self, since: float = 0.0, group_prefix: str = ""
    ) -> List[AuditEvent]:
        with self._lock:
            return [
                e
                for e in self._events
                if e.ts >= since and e.group.startswith(group_prefix)
            ]


class ResourceExecutor:
    """Cached, audited writer over a cgroup filesystem root."""

    def __init__(self, cgroup_root: str, auditor: Optional[Auditor] = None):
        self.cgroup_root = cgroup_root
        self.auditor = auditor or Auditor()
        self._cache: Dict[Tuple[str, str], str] = {}
        self._lock = threading.Lock()

    def _path(self, group: str, file: str) -> str:
        return os.path.join(self.cgroup_root, group, file)

    def read(self, group: str, file: str) -> Optional[str]:
        try:
            with open(self._path(group, file)) as f:
                return f.read().strip()
        except OSError:
            return None

    def write(
        self, group: str, file: str, value: str, reason: str = ""
    ) -> bool:
        """Write-through with no-op suppression; returns True if written."""
        value = str(value)
        with self._lock:
            key = (group, file)
            cached = self._cache.get(key)
            if cached is None:
                cached = self.read(group, file)
            if cached == value:
                self._cache[key] = value
                return False
            path = self._path(group, file)
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "w") as f:
                    f.write(value)
            except OSError as e:
                # a kernel/cgroup rejection (EINVAL on cpuset, missing
                # cfs_burst support, …) must not kill the QoS loops —
                # record it and move on (the reference logs + continues)
                self.auditor.record(
                    AuditEvent(
                        ts=time.time(),
                        group=group,
                        file=file,
                        old=cached,
                        new=value,
                        reason=f"WRITE-FAILED: {e}",
                    )
                )
                return False
            self._cache[key] = value
            self.auditor.record(
                AuditEvent(
                    ts=time.time(),
                    group=group,
                    file=file,
                    old=cached,
                    new=value,
                    reason=reason,
                )
            )
            return True

    def apply(self, plan: Sequence[Tuple[str, str, str]], reason: str = "") -> int:
        """Apply a write plan [(group, file, value)]; returns writes done."""
        done = 0
        for group, file, value in plan:
            if self.write(group, file, value, reason=reason):
                done += 1
        return done

    def gc_group(self, group: str, reason: str = "") -> None:
        """Drop cache entries for a removed cgroup (pod teardown GC —
        the kernel dir is gone; stale cache must not suppress writes if
        the same pod name reappears)."""
        with self._lock:
            # boundary-aware prefix: pod-web-1 must not GC pod-web-10
            for key in [
                k
                for k in self._cache
                if k[0] == group or k[0].startswith(group + "/")
            ]:
                del self._cache[key]
            self.auditor.record(
                AuditEvent(
                    ts=time.time(),
                    group=group,
                    file="*",
                    old=None,
                    new="<gc>",
                    reason=reason,
                )
            )
