"""Cross-cycle solve pipelining (perf PR 4 tentpole).

The serial scheduling cycle is a strictly sequential host-lower →
device-solve → host-commit chain: the device idles during Reserve and the
host idles during the solve. Round-based cluster schedulers (Gavel,
Synergy) get their throughput from keeping the solver saturated across
rounds; this module applies the same overlap discipline to the batch
scheduler, using the chaining trick ``_dispatch_pipelined`` already uses
WITHIN a cycle — extended across the cycle boundary:

* a **prepare worker** (host thread) lowers cycle N+1's pod batch and
  constraint masks while cycle N's solve is still in flight on the
  device (``prepare`` span);
* cycle N+1's solves are **dispatched off the device-chained capacity
  state** of cycle N's solve, before cycle N's host Reserve has run —
  the solver's own commit state stands in for the not-yet-applied host
  commit (``overlap`` span ties dispatch to consume);
* cycle N's host Reserve then **trails behind** under the existing
  transactional ``_ReserveJournal``: a mid-pipeline failure rolls the
  chunk back bit-exactly and the speculation is discarded.

Decision identity with the serial path is a *validation* property, not
an assumption: the consuming cycle re-derives its chunking and compares
it (plus snapshot version and node epoch) against what the speculation
used — any mismatch, any Reserve rejection, rollback, deferral or
preemption discards the in-flight solve and the cycle re-dispatches from
the refreshed host state. A kept speculation used inputs equal to what
the serial path would have lowered (bit-exact for the integral
milli-CPU/MiB values k8s specs carry), so placements match either way.

Failure domain (ROADMAP rule): the prepare worker is a named chaos point
``pipeline.worker_stall``; a stalled/dead worker degrades the cycle to
the serial path (counted in ``pipeline_prepare_stalls_total``, surfaced
as the ``pipeline`` row on /healthz) instead of wedging the pump.
"""

from __future__ import annotations

import dataclasses
import queue as _queue
import threading as _threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from ..obs import report_exception
from .batch_solver import (
    BatchScheduler,
    ScheduleOutcome,
    SpeculativeSolve,
    num_nodes_to_score,
)


@dataclasses.dataclass
class PreparedCycle:
    """The prepare worker's output for one upcoming batch: chunked device
    batches + host rows + constraint masks, stamped with the snapshot
    state they were lowered against."""

    chunks: List[List[Pod]]
    chunk_uids: Tuple[Tuple[str, ...], ...]
    #: [(PodBatch, LoweredRows, node_mask)] per chunk
    triples: list
    #: NaN-guard verdicts collected during lowering (merged at consume)
    quarantine: Dict[str, tuple]
    version: int
    node_epoch: int
    #: frozen per-gang lowering inputs (open-the-gates PR): what the
    #: live min-member/nonstrict views said when the rows were lowered —
    #: consume-time validation re-derives and compares
    gang_view: tuple = ()
    #: quota TREE shape the rows' chains were lowered against; a tree
    #: mutation between prepare and dispatch refuses the speculation
    quota_tree_version: int = -1
    #: prepare-time reservation fast-path plan (open the last gates PR):
    #: the chunks above already EXCLUDE its predicted fast-path binds
    #: and required-affinity refusals; the dispatch TRUSTS the plan when
    #: ``resv_chain`` is the very chain it dispatches off (identity),
    #: else re-previews and reuses these triples only when the plans
    #: still agree. None = reservations absent or refused.
    resv_plan: object = None
    #: the ChainCarry the plan was previewed against (None = live/fresh)
    resv_chain: object = None


def _merge_outcomes(outs: List[ScheduleOutcome]) -> Optional[ScheduleOutcome]:
    """Fold several cycles' outcomes into one (feed's tail drain and the
    handoff drain both return multiple commits per call at depth>1).
    Single source of truth so a future ScheduleOutcome field cannot be
    dropped by one of two hand-rolled merge loops."""
    if not outs:
        return None
    if len(outs) == 1:
        return outs[0]
    merged = ScheduleOutcome(bound=[], unschedulable=[])
    for o in outs:
        merged.bound.extend(o.bound)
        merged.unschedulable.extend(o.unschedulable)
        merged.rounds_used += o.rounds_used
        merged.preempted.extend(o.preempted)
    return merged


@dataclasses.dataclass
class _InFlight:
    """One pending pipeline entry: a fed batch whose trailing commit has
    not run yet, plus its speculative solve (None = serial) and the gate
    verdicts evaluated for it at feed time."""

    batch: List[Pod]
    spec: object
    span: object
    gates: Dict[str, object]


class _PrepareWorker:
    """Single background thread lowering upcoming batches. Jobs flow
    through a queue; results land in a dict under a condition variable.
    The ``pipeline.worker_stall`` chaos point makes the thread wedge
    (die without acking) so the pump's collect deadline is exercised."""

    def __init__(self, sched: BatchScheduler):
        self.sched = sched
        self._req: "_queue.Queue" = _queue.Queue()
        #: worker thread writes results, pump thread collects them
        self._results: Dict[int, Optional[PreparedCycle]] = {}  # guarded-by: self._cond
        self._cond = _threading.Condition()
        self._seq = 0
        self._thread: Optional[_threading.Thread] = None
        self._spawn()

    def _spawn(self) -> None:
        self._thread = _threading.Thread(
            target=self._run, name="pipeline-prepare", daemon=True
        )
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    #: sentinel result for warm-only jobs (intern cache primed, nothing
    #: to dispatch) — distinct from None, which means stall/error
    WARMED = object()

    def submit(
        self,
        batch: Sequence[Pod],
        warm_only: bool = False,
        stall: bool = False,
        resv_ctx: Optional[tuple] = None,
    ) -> int:
        """``stall=True`` (decided by the PUMP thread's chaos evaluation
        — firing from the worker thread would make the injector's fault
        trace order race the pump's own points and break same-seed
        determinism) makes the worker wedge on this job: never acked,
        thread dies. ``resv_ctx`` is the newest in-flight speculation's
        ``(chain_out, carry)`` (open the last gates PR): the prepare-time
        reservation preview runs against the CHAINED predicted state so
        its plan agrees with the dispatch-time re-preview and the
        prepared triples stay reusable (a live-state plan would diverge
        every time the upstream cycle consumed a reservation, forcing
        cold inline re-lowering on the pump thread)."""
        self._seq += 1
        self._req.put((self._seq, list(batch), warm_only, stall, resv_ctx))
        return self._seq

    def collect(
        self, job: int, timeout_s: float
    ) -> Optional[PreparedCycle]:
        """Wait up to ``timeout_s`` for the prepared lowering; None on
        stall/death/error (the caller degrades to the serial path)."""
        deadline = _time.monotonic() + timeout_s
        with self._cond:
            # purge results nobody will ever collect (jobs abandoned when
            # a chaos-killed worker was respawned mid-queue)
            for stale in [k for k in self._results if k < job]:
                del self._results[stale]
            while job not in self._results:
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self.alive:
                    return self._results.pop(job, None)
                self._cond.wait(min(remaining, 0.05))
            return self._results.pop(job, None)

    def close(self) -> None:
        """Stop the worker and wait for it: a daemon thread torn down by
        interpreter exit while inside a device transfer aborts the whole
        process (std::terminate in XLA) — the join drains any in-flight
        prepare first."""
        try:
            while True:
                self._req.get_nowait()
        except _queue.Empty:
            pass
        self._req.put((None, None, False, False, None))
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def _run(self) -> None:
        sched = self.sched
        while True:
            job, batch, warm_only, stall, resv_ctx = self._req.get()
            if job is None:
                return
            if stall:
                # simulated wedge: the job is never acked and the thread
                # dies — the pump's collect deadline surfaces it and the
                # cycle degrades to serial
                return
            try:
                if warm_only:
                    self._warm(batch)
                    prep = self.WARMED
                else:
                    prep = self._prepare(batch, resv_ctx)
            except Exception as exc:  # noqa: BLE001 — degrade, don't die
                report_exception(
                    "scheduler.pipeline.prepare",
                    exc,
                    registry=sched.extender.registry,
                )
                prep = None
            with self._cond:
                self._results[job] = prep
                self._cond.notify_all()

    def _warm(self, batch: Sequence[Pod]) -> None:
        """Gated cycles (transformers/sampling/cold gangs/unhealthy
        ladder) can't take the chained fast path, but
        the prepare worker still pays their per-pod parse ahead of time:
        one throwaway lowering primes the interned-row cache so the
        serial cycle's own ``build_pods`` hits it.
        ``inject=False`` keeps scheduled NaN faults for the real
        lowering."""
        sched = self.sched
        with sched.snapshot.lock:
            with sched.extender.tracer.span(
                "prepare", cat="pipeline", pods=len(batch), warm_only=True
            ):
                sched._lower_rows(
                    batch, stash=False, quarantine={}, inject=False
                )

    def _prepare(
        self, batch: Sequence[Pod], resv_ctx: Optional[tuple] = None
    ) -> PreparedCycle:
        sched = self.sched
        snap = sched.snapshot
        with snap.lock:
            with sched.extender.tracer.span(
                "prepare", cat="pipeline", pods=len(batch)
            ):
                quarantine: Dict[str, tuple] = {}
                # captured BEFORE lowering: a quota-tree mutation racing
                # the prepare bumps it, and the dispatch-time compare
                # then refuses the speculation (stale lowered chains)
                tree_v = sched.quotas.tree_version
                # reservation carry (open the last gates PR): the P1
                # preview predicts which pods the consuming cycle's
                # fast path will bind — they must not be lowered into
                # the solver chunks. CHAIN-seeded when an upstream
                # speculation is in flight (resv_ctx): the preview runs
                # against the predicted post state (reservation overlay
                # + carried quota rows + chained node table, fetched on
                # THIS worker thread so the pump never blocks on them),
                # which makes it agree with the dispatch-time re-preview
                # in the common case — the prepared triples stay
                # reusable. Pure either way (overlay view + copies).
                resv_plan = None
                resv_chain = None
                pods_in = batch
                if sched.reservations is not None:
                    base_view = None
                    chain_nodes = None
                    quota_prev = None
                    if resv_ctx is not None:
                        chain_out, carry_meta = resv_ctx
                        resv_chain = chain_out
                        base_view = chain_out.resv_view
                        chain_nodes = chain_out.nodes
                        if chain_out.quota_used is not None:
                            quota_prev = (
                                sched._quota_fastpath_preview_chain(
                                    chain_out.quota_used, carry_meta
                                )
                            )
                    if (
                        quota_prev is None
                        and sched.quotas.quota_count > 0
                    ):
                        quota_prev = sched._quota_fastpath_preview_live()
                    resv_plan = sched._reservation_fastpath_preview(
                        batch,
                        base_view=base_view,
                        quota_prev=quota_prev,
                        chain_nodes=chain_nodes,
                    )
                    if resv_plan is not None:
                        excluded = resv_plan.taken | set(
                            resv_plan.affinity_unsched
                        )
                        pods_in = [
                            p
                            for p in batch
                            if p.meta.uid not in excluded
                        ]
                # idempotent for warm-gang batches (the _prepare_ok
                # gate): pending registries rebuild from the same batch
                # at consume, no state creation beyond what the serial
                # cycle would do, and no timeout branch can fire. The
                # gang-state mutation is also SERIALIZED against the
                # pump thread's trailing commit: this whole prepare runs
                # under snap.lock (above), the same lock schedule()
                # holds for its begin_and_order/Permit — the two
                # interleave atomically, never mid-rebuild
                eligible = sched.pod_groups.begin_and_order(pods_in)
                chunks = sched._chunks(eligible)
                triples = []
                for chunk in chunks:
                    # inject=False: chaos points must fire on the PUMP
                    # thread in program order (same-seed trace
                    # determinism), and a scheduled NaN hit consumed by a
                    # lowering whose speculation is later discarded would
                    # be silently spent — the serial/degrade paths keep
                    # firing it
                    pods, rows = sched._lower_chunk(
                        chunk,
                        stash=False,
                        quarantine=quarantine,
                        inject=False,
                    )
                    mask = sched._node_constraint_mask(
                        chunk, pods.requests.shape[0], None
                    )
                    triples.append((pods, rows, mask))
                return PreparedCycle(
                    chunks=chunks,
                    chunk_uids=tuple(
                        tuple(p.meta.uid for p in c) for c in chunks
                    ),
                    triples=triples,
                    quarantine=quarantine,
                    version=snap.version,
                    node_epoch=snap.node_epoch,
                    gang_view=sched.pod_groups.gang_view(eligible),
                    quota_tree_version=tree_v,
                    resv_plan=resv_plan,
                    resv_chain=resv_chain,
                )


class _DepthController:
    """Per-cycle pipeline-depth feedback controller (adaptive-depth PR).

    The configured depth is a CEILING, not a setpoint: each feed picks
    an effective depth in ``1..max_depth`` from the recent speculation
    discard rate — the same signal the flight recorder records per
    cycle (``speculation`` kept/discarded), so every choice is
    explainable post-hoc from the black box. A high-churn window (most
    consumes discarding on the version/carry guards) degrades to depth
    1 BEFORE more deep dispatches are wasted; a quiet stretch (no
    discard for :data:`QUIET_FEEDS` consecutive feeds — idle feeds
    count, so a drain tail recovers) restores the ceiling and expires
    the stale churn evidence. Deterministic: no clocks, no randomness —
    the same outcome sequence always yields the same depth trace
    (same-seed soak contract)."""

    #: sliding window of recent speculative consume outcomes
    WINDOW = 12
    #: minimum outcomes before the rate is trusted
    EVIDENCE = 4
    #: discard rate at/above which depth degrades to 1
    DEGRADE_RATE = 0.5
    #: discard rate at/below which the ceiling is restored
    RESTORE_RATE = 0.2
    #: consecutive discard-free feeds that restore the ceiling
    QUIET_FEEDS = 8

    def __init__(self, max_depth: int, seed_outcomes: Sequence[bool] = ()):
        self.max_depth = max(1, int(max_depth))
        self._win: "deque[bool]" = deque(maxlen=self.WINDOW)
        for kept in seed_outcomes:
            self._win.append(bool(kept))
        self._quiet = 0
        self._depth = self.max_depth
        #: decision observatory (obs.decisions.DecisionLedger), wired by
        #: the pipeline from sched.decision_ledger each feed. None =
        #: disabled; the record site is one attribute-is-None check.
        self.decisions = None
        self._ticks = 0

    @property
    def discard_rate(self) -> float:
        if not self._win:
            return 0.0
        return sum(1 for k in self._win if not k) / len(self._win)

    @property
    def depth(self) -> int:
        return self._depth

    def note_outcome(self, kept: bool) -> None:
        """One speculative consume settled (kept / discarded)."""
        self._win.append(bool(kept))

    def note_feed(self, had_discard: bool) -> None:
        """One feed() completed; quiet feeds accumulate toward
        restoration, any discard resets the streak."""
        self._quiet = 0 if had_discard else self._quiet + 1

    def snapshot(self) -> Dict[str, object]:
        """The COMPLETE evidence :meth:`decide` reads, as one pure dict
        (decision-observatory contract: the recorded inputs alone must
        reproduce the decision)."""
        return {
            "max_depth": self.max_depth,
            "depth": self._depth,
            "window": [bool(k) for k in self._win],
            "discard_rate": round(self.discard_rate, 4),
            "quiet_feeds": self._quiet,
        }

    @staticmethod
    def decide(inputs: Dict[str, object]):
        """Pure depth decision from a snapshot — ``(action, state)``.

        Deterministic and side-effect-free so a shadow policy or
        ``tools/decision_replay.py`` re-deciding from a RECORDED
        snapshot reproduces the acting choice bit-exactly."""
        max_depth = int(inputs["max_depth"])
        depth = int(inputs["depth"])
        window = list(inputs["window"])
        quiet = int(inputs["quiet_feeds"])
        clear_window = False
        if max_depth <= 1:
            depth = 1
        elif quiet >= _DepthController.QUIET_FEEDS:
            if depth < max_depth:
                # quiet restoration also expires the window: the churn
                # it recorded is evidence about a world that stopped
                # producing discards QUIET_FEEDS feeds ago
                clear_window = True
            depth = max_depth
        elif len(window) >= _DepthController.EVIDENCE:
            rate = sum(1 for k in window if not k) / len(window)
            if rate >= _DepthController.DEGRADE_RATE:
                depth = 1
            elif rate <= _DepthController.RESTORE_RATE:
                depth = max_depth
        action = {"depth": depth}
        state = {
            "depth": depth,
            "cleared_window": clear_window,
            "window_len": 0 if clear_window else len(window),
        }
        return action, state

    def choose(self) -> int:
        """Effective depth for the NEXT feed: snapshot once, decide
        purely FROM the snapshot, apply, record."""
        self._ticks += 1
        inputs = self.snapshot()
        action, state = self.decide(inputs)
        if state["cleared_window"]:
            self._win.clear()
        self._depth = int(action["depth"])
        dl = self.decisions
        if dl is not None:
            dl.record(
                "depth", self._ticks, inputs, action, state,
                outcome={"discard_rate": inputs["discard_rate"]},
            )
        return self._depth

    def info(self) -> Dict[str, object]:
        return {
            "max_depth": self.max_depth,
            "depth": self._depth,
            "discard_rate": round(self.discard_rate, 4),
            "window": len(self._win),
            "quiet_feeds": self._quiet,
        }


class CyclePipeline:
    """Pipelined cycle runner over a :class:`BatchScheduler`.

    ``feed(batch)`` dispatches ``batch``'s solves (speculatively, off the
    newest in-flight cycle's device-chained state when valid) and — once
    ``depth`` batches are in flight — runs the OLDEST batch's trailing
    commit, returning its :class:`ScheduleOutcome` (results lag up to
    ``depth`` feeds). ``feed([])`` / :meth:`flush` drain one tail entry
    per call. Cycles that fail any pipeline gate (transformers, node
    sampling, cold gangs, an unhealthy ladder)
    or whose prepare worker stalls simply run the serial path — same
    decisions, no overlap. Open-the-gates PR: quota-, NUMA-, device-
    and warm-gang-bearing batches take the speculative path too — their
    tables ride the device chain with bit-exact consume-time validation
    (``BatchScheduler._carry_consume_ok``); the first-class-multichip PR
    opened ``mesh`` and ``reservations`` the same way (sharded carries
    validated by value, a mesh attach/detach discards via the mode-flag
    comparison).

    ``depth`` > 1 (multi-queue streams) holds that many speculative
    solves in flight: batch k+1 chains off batch k's post-solve tables
    before EITHER trailing commit has run, and the trailing-commit
    validation generalizes to a chain — an unclean commit (or any
    consume-guard miss) discards EVERY pending speculation downstream of
    it, never just the head. Observable via ``solver_pipeline_depth``.

    Adaptive depth (open the last gates PR): ``depth`` is the CEILING —
    a :class:`_DepthController` picks the effective in-flight window
    per feed from the recent discard rate (high churn degrades to 1
    before wasting deep dispatches, a quiet drain restores the max),
    composed with the brownout L1 cap (effective = min of both; the
    ladder always dominates while browning). ``adaptive=False`` pins
    the configured depth. The chosen depth + its discard-rate input are
    stamped on every flight-recorder cycle record and served at
    ``/debug/pipeline``."""

    def __init__(
        self,
        sched: BatchScheduler,
        prepare_timeout_s: float = 5.0,
        depth: int = 1,
        adaptive: bool = True,
    ):
        self.sched = sched
        self.prepare_timeout_s = prepare_timeout_s
        self.depth = max(1, int(depth))
        self.adaptive = bool(adaptive)
        # seed the controller's window from an adopted flight-recorder
        # tail (takeover: the dead writer's churn evidence carries over)
        seed: list = []
        fr = sched.flight_recorder
        if fr is not None:
            for rec in fr.last(_DepthController.WINDOW):
                outcome = rec.get("speculation")
                if outcome in ("kept", "discarded"):
                    seed.append(outcome == "kept")
        self._controller = _DepthController(self.depth, seed)
        self._controller.decisions = sched.decision_ledger
        #: the cap the most recent feed ran under (min of the adaptive
        #: choice and the brownout ladder's cap) + the adaptive choice
        #: itself — sampled by the soaks' interplay assertions
        self.last_depth_cap = self.depth
        self.last_adaptive_depth = self.depth
        self._worker = _PrepareWorker(sched)
        #: in-flight entries, oldest first (≤ depth of them)
        self._pending: "deque[_InFlight]" = deque()
        self._degraded = False
        #: gate introspection (distributed-observability PR): the most
        #: recent _gates_ok evaluation — which named gate kept the cycle
        #: serial — served at /debug/pipeline and counted per gate in
        #: pipeline_gate_closed_total{gate}
        self.last_gate_report: Dict[str, object] = {}
        self._gated_cycles = 0
        self._fast_cycles = 0
        sched.extender.services.gate_info = self.gate_info
        #: interpreter-exit safety net for pipelines nobody close()s —
        #: the worker must never be torn down mid-device-transfer
        import weakref

        self._finalizer = weakref.finalize(self, self._worker.close)
        sched.extender.health.set("pipeline", True)

    # ---- public surface ----

    @property
    def inflight(self) -> bool:
        return bool(self._pending)

    def inflight_pods(self) -> List[Pod]:
        """Every pod currently inside the pipeline (fed, trailing commit
        not yet returned) — with depth>1 this spans SEVERAL batches, so
        crash drivers must orphan all of them, not just the last fed."""
        return [p for e in self._pending for p in e.batch]

    def close(self) -> None:
        self._finalizer()

    def flush(self) -> Optional[ScheduleOutcome]:
        """Complete the OLDEST in-flight cycle (trailing commit) and
        return its outcome; None when nothing was in flight. With
        depth>1 call repeatedly (``while pipe.inflight``) to drain."""
        return self.feed([])

    def drain_for_handoff(self) -> Optional[ScheduleOutcome]:
        """Leadership loss mid-pipeline (HA failover PR): every in-flight
        speculative solve was dispatched under an epoch that no longer
        holds — DISCARD the whole pending chain (counted in
        ``pipeline_speculation_total{outcome="discarded"}``), then flush
        every trailing commit so each runs through the commit-boundary
        fencing check: with the grant revoked every chunk is rejected
        with STALE_LEADER_EPOCH and the batches' pods surface as
        unschedulable for the new leader to place. Returns the MERGED
        outcome across the drained entries. The /healthz ``pipeline``
        row carries the handoff state while the drain runs."""
        sched = self.sched
        health = sched.extender.health
        if not self._pending:
            return None
        health.set("pipeline", False, "leadership handoff: draining")
        counter = sched.extender.registry.get("pipeline_speculation_total")
        for entry in self._pending:
            if entry.spec is not None:
                counter.labels(outcome="discarded").inc()
                if entry.span is not None:
                    entry.span.__exit__(None, None, None)
                entry.spec = None
                entry.span = None
        drained: List[ScheduleOutcome] = []
        try:
            while self._pending:
                out = self.feed([])
                if out is not None:
                    drained.append(out)
        finally:
            health.set("pipeline", True, "handoff drained")
        return _merge_outcomes(drained)

    def feed(self, batch: Sequence[Pod]) -> Optional[ScheduleOutcome]:
        sched = self.sched
        reg = sched.extender.registry
        tracer = sched.extender.tracer
        batch = list(batch)
        job = None
        full_ok = False
        this_gates: Dict[str, object] = {}
        if batch:
            if self._prepare_ok(batch):
                # prepare stage: the worker lowers THIS batch while the
                # in-flight cycles' solves are still on device and while
                # the oldest one's trailing commit runs below. Gated
                # cycles still prepare in warm-only mode (intern-cache
                # priming) so the serial path's own lowering gets the
                # hit.
                full_ok = self._gates_ok(batch)
                this_gates = self.last_gate_report
                stall = sched.chaos.enabled and sched.chaos.fire(
                    "pipeline.worker_stall"
                )
                # chain context for the prepare-time reservation preview
                # (the dispatch below will chain off this same newest
                # spec, so the worker's plan and the dispatch's agree)
                resv_ctx = None
                if (
                    full_ok
                    and sched.reservations is not None
                    and self._pending
                    and self._pending[-1].spec is not None
                ):
                    spec0 = self._pending[-1].spec
                    resv_ctx = (spec0.chain_out, spec0.carry)
                job = self._worker.submit(
                    batch,
                    warm_only=not full_ok,
                    stall=stall,
                    resv_ctx=resv_ctx,
                )
            else:
                # prepare refused (cold gangs / pod transformers): still
                # evaluate and record the gate verdicts so /debug/
                # pipeline and pipeline_gate_closed_total name WHY the
                # cycle ran serial — introspection must not go dark on
                # exactly the cycles that need explaining
                self._gates_ok(batch)
                this_gates = self.last_gate_report
        out: Optional[ScheduleOutcome] = None
        spec_new: Optional[SpeculativeSolve] = None
        if self._pending:
            newest = self._pending[-1]
            if job is not None and full_ok and newest.spec is not None:
                # deep speculation: dispatch batch k's solves off the
                # NEWEST in-flight cycle's chained state BEFORE any
                # trailing commit — with depth>1 that chain is itself
                # speculative, so this solve rides a chain of pending
                # validations
                prep = self._collect(job)
                job = None
                if prep is not None and prep is not _PrepareWorker.WARMED:
                    spec_new = self._dispatch(
                        prep,
                        batch,
                        chain=newest.spec.chain_out,
                        chain_version=newest.spec.version,
                        chain_meta=newest.spec.carry,
                    )
        # adaptive depth (open the last gates PR): the controller picks
        # the in-flight window from the recent discard rate, composed
        # with the brownout L1 cap (overload-control PR: a storm's churn
        # discards chained speculation anyway — stop paying for deep
        # dispatches it will throw away). The ladder's cap DOMINATES
        # while browning; the controller's choice resumes at L0.
        # late attach (a runtime may wire the ledger after pipeline
        # construction): resync the controller's ledger handle per feed
        self._controller.decisions = sched.decision_ledger
        chosen = self._controller.choose() if self.adaptive else self.depth
        depth_cap = chosen
        bo = sched.brownout
        if bo is not None:
            depth_cap = min(depth_cap, bo.pipeline_depth_cap())
        self.last_adaptive_depth = chosen
        self.last_depth_cap = depth_cap
        had_discard = False
        outs: List[ScheduleOutcome] = []
        while self._pending and (
            not batch
            or len(self._pending) >= depth_cap
            # a serial newest entry caps the chain: nothing can dispatch
            # off it, so holding depth only delays results — drain the
            # tail now so the NEXT feed re-bootstraps speculation off
            # fully-committed state
            or self._pending[-1].spec is None
        ):
            # trailing commit of the OLDEST entry under the Reserve
            # journal; the scheduler consumes its solves when the guards
            # hold. The gate verdicts handed to the flight recorder are
            # the ones evaluated FOR that batch at its feed — not this
            # call's fresher evaluation (off-by-one would put the next
            # batch's gates on the completed cycle's record)
            entry = self._pending.popleft()
            sched.last_gate_report = entry.gates
            sched._speculative = entry.spec
            sched._depth_decision = (
                depth_cap,
                self.depth,
                round(self._controller.discard_rate, 4),
            )
            outs.append(sched.schedule(entry.batch))
            if entry.span is not None:
                entry.span.__exit__(None, None, None)
            kept = entry.spec is not None and sched._cycle_used_spec
            clean = kept and sched.last_cycle_spec_safe()
            if entry.spec is not None:
                # feed the depth controller the same per-cycle outcome
                # the flight recorder records
                self._controller.note_outcome(kept)
                had_discard = had_discard or not kept
            if clean:
                # retroactively valid: the commit applied exactly the
                # deltas the chain already carried — re-stamp EVERY
                # still-pending speculation (they chained transitively)
                # to the post-commit version so the consume guards match
                for e in self._pending:
                    if e.spec is not None:
                        e.spec.version = sched._post_cycle_version
                if spec_new is not None:
                    spec_new.version = sched._post_cycle_version
            else:
                # an unvalidated commit poisons the WHOLE chain: every
                # pending speculation downstream consumed state this
                # commit did not prove — discard them all, not just the
                # head (depth>1 correctness rule)
                discards = sum(
                    1 for e in self._pending if e.spec is not None
                ) + (1 if spec_new is not None else 0)
                if discards:
                    counter = reg.get("pipeline_speculation_total")
                    for _ in range(discards):
                        counter.labels(outcome="discarded").inc()
                        self._controller.note_outcome(False)
                    had_discard = True
                for e in self._pending:
                    if e.span is not None:
                        e.span.__exit__(None, None, None)
                    e.spec = None
                    e.span = None
                spec_new = None
            if not batch:
                # flush contract: drain exactly one entry per call
                break
        if outs:
            out = _merge_outcomes(outs)
        if job is not None:
            # collect regardless of whether a dispatch can use it: the
            # warm-only ack IS the worker liveness probe (a stalled/dead
            # worker must degrade visibly, not silently), and a full prep
            # bootstraps speculation off the refreshed post-commit state
            prep = self._collect(job)
            if (
                batch
                and spec_new is None
                and full_ok
                # a fresh (post-commit) dispatch consumes the RESIDENT
                # host state, which is only the truth when no trailing
                # commit is still pending. By construction this holds
                # whenever control reaches here with a live job (a
                # chained attempt consumes the job, and a serial newest
                # entry drains the window) — the guard makes the
                # invariant explicit rather than emergent
                and not self._pending
                and prep is not None
                and prep is not _PrepareWorker.WARMED
            ):
                spec_new = self._dispatch(prep, batch, chain=None)
        span = None
        if spec_new is not None:
            # the overlap span ties dispatch to consume: its duration is
            # the window the device solve ran concurrently with host work
            span = tracer.span("overlap", cat="pipeline", pods=len(batch))
            span.__enter__()
        if batch:
            self._pending.append(
                _InFlight(
                    batch=batch, spec=spec_new, span=span, gates=this_gates
                )
            )
        self._controller.note_feed(had_discard)
        depth = sum(
            1 + (1 if e.spec is not None else 0) for e in self._pending
        )
        reg.get("solver_pipeline_depth").set(float(depth))
        return out

    # ---- internals ----

    def _collect(self, job: int):
        prep = self._worker.collect(job, self.prepare_timeout_s)
        if prep is None:
            self._on_stall()
        elif self._degraded:
            # a successful collect IS the worker liveness probe: the
            # respawned worker is preparing again — recover /healthz
            self._degraded = False
            self.sched.extender.health.set("pipeline", True)
        return prep

    def _on_stall(self) -> None:
        sched = self.sched
        sched.extender.registry.get("pipeline_prepare_stalls_total").inc()
        self._degraded = True
        sched.extender.health.set(
            "pipeline",
            False,
            "prepare worker stalled/died; cycle degraded to serial",
        )
        if not self._worker.alive:
            self._worker._spawn()

    def _dispatch(
        self,
        prep: PreparedCycle,
        batch: Sequence[Pod],
        chain,
        chain_version: Optional[int] = None,
        chain_meta=None,
    ) -> Optional[SpeculativeSolve]:
        """Dispatch the prepared chunks chained off ``chain`` (a
        :class:`~.batch_solver.ChainCarry`, or off the refreshed resident
        state when None), under the snapshot lock so the version stamp is
        exact. ``batch`` is the FULL fed batch — the reservation carry
        re-previews the fast path against the chained state and may
        re-chunk, so the final chunk uids come from the dispatch, not
        the prepare. Returns None when the prepared lowering no longer
        matches the live snapshot."""
        from .batch_solver import ChainCarry

        sched = self.sched
        snap = sched.snapshot
        if not prep.chunks and sched.reservations is None:
            return None
        with snap.lock:
            v = snap.version
            if prep.node_epoch != snap.node_epoch:
                return None
            if (
                sched.quotas.quota_count > 0
                and prep.quota_tree_version != sched.quotas.tree_version
            ):
                # the rows' lowered quota chains describe a dead tree
                return None
            if chain is not None:
                # pre-commit dispatch: the chain AND the prepared lowering
                # must both describe the current (uncommitted) world
                if chain_version != v or prep.version != v:
                    return None
            else:
                # post-commit dispatch: prepared either after the commit
                # (same version) or before it with no other write in
                # between (the commit's own writes don't touch what the
                # lowering read — labels, presence, pod specs)
                if not (
                    prep.version == v
                    or (
                        prep.version == sched._pre_cycle_version
                        and v == sched._post_cycle_version
                    )
                ):
                    return None
                chain = ChainCarry(nodes=sched.node_state(None))
            with sched.extender.tracer.span(
                "pipeline:dispatch",
                cat="pipeline",
                chunks=len(prep.chunks),
            ):
                dispatched = sched._dispatch_chained(
                    prep.chunks,
                    chain,
                    quarantine=prep.quarantine,
                    prepared=prep.triples,
                    gang_view=prep.gang_view,
                    batch=list(batch),
                    prep_plan=prep.resv_plan,
                    chain_meta=chain_meta,
                    chained=chain_meta is not None,
                    prep_chain=prep.resv_chain,
                )
            if dispatched is None:
                # a carried table no longer matches the live shapes
                # (tree/topology reshape mid-chain), or the reservation
                # preview refused — no speculation
                return None
            solves, chain_out, carry = dispatched
            return SpeculativeSolve(
                # derived from the DISPATCHED chunks — the reservation
                # re-preview may have re-chunked past the prepared ones
                chunk_uids=tuple(
                    tuple(p.meta.uid for p in c) for c, _r, _s in solves
                ),
                sub=None,
                solves=solves,
                chain_out=chain_out,
                version=v,
                node_epoch=prep.node_epoch,
                carry=carry,
                quarantine=prep.quarantine,
                dispatched_at=_time.perf_counter(),
            )

    def _prepare_ok(self, batch: Sequence[Pod]) -> bool:
        """Whether the worker may touch this batch at all: prepare must
        be an IDEMPOTENT read of the pods + snapshot (pod transformers
        mutate state the real cycle will mutate again, so they stay
        out). Open-the-gates PR: warm-gang batches qualify — for them
        ``begin_and_order`` rebuilds the same pending registries the
        consuming cycle will rebuild from the same batch, creates no
        timeout mutation, and the lowered gang rows are validated
        against the live views at consume. Cold gangs (members missing,
        or a gang already past its schedule timeout) keep the prepare
        out entirely, like before."""
        sched = self.sched
        if sched.extender._pre_batch:
            return False
        return sched.pod_groups.batch_gangs_warm(batch)

    def gate_info(self) -> Dict[str, object]:
        """/debug/pipeline payload: the latest per-gate verdicts plus
        long-run gated/fast cycle counts and the live pipeline depth —
        the evidence base for "which gate keeps the slow configs
        (quota/NUMA/device/gang) serial"."""
        reg = self.sched.extender.registry
        depth = reg.get("solver_pipeline_depth")
        bo = self.sched.brownout
        return {
            "pipelined": True,
            "last": dict(self.last_gate_report),
            "cycles_gated": self._gated_cycles,
            "cycles_fast": self._fast_cycles,
            "depth": depth.value() if depth is not None else 0.0,
            "max_depth": self.depth,
            # adaptive-depth PR: the controller's live choice and its
            # discard-rate input, plus the effective cap after the
            # brownout ladder's L1 composition — depth decisions must
            # be explainable from this payload and the flight recorder
            "depth_controller": dict(
                self._controller.info(),
                adaptive=self.adaptive,
                effective_cap=self.last_depth_cap,
                brownout_cap=(
                    bo.pipeline_depth_cap() if bo is not None else None
                ),
            ),
        }

    def _gates_ok(self, batch: Sequence[Pod]) -> bool:
        """Whether this batch may take the speculative fast path. Every
        CLOSED gate names a subsystem whose host-side commit state the
        device chain cannot carry exactly (or whose bookkeeping the
        speculative ordering would double-run); gated cycles run serial
        — identical decisions, no overlap. Open-the-gates PR: quotas,
        NUMA, devices and warm gangs no longer close — their tables ride
        the device chain and ``_carry_consume_ok`` proves the inputs
        bit-exact at consume (any divergence discards, serial-identical
        either way). The still-gated subset is re-checked by the
        scheduler at consume time: a gated subsystem arriving
        mid-pipeline through an informer invalidates the in-flight
        speculation.

        Every evaluation records WHICH gates closed: per-gate counts in
        ``pipeline_gate_closed_total{gate}`` and the latest full report
        on :attr:`last_gate_report` (served at ``/debug/pipeline``)."""
        sched = self.sched
        gates = sched.speculation_gate_report()
        gates["ladder"] = (
            sched._fallback_level == 0 and sched._bucket_degrade == 0
        )
        # brownout L2+ (overload-control PR): the ladder says SERIAL —
        # no speculation while the fleet sheds load (decision-identical
        # by construction, like every closed gate)
        bo = sched.brownout
        gates["brownout"] = bo is None or not bo.serial_only()
        # warm gangs ride the chain; cold gangs (members missing or a
        # gang in timeout) keep the batch serial
        gates["batch_gangs"] = sched.pod_groups.batch_gangs_warm(batch)
        closed = sorted(g for g, open_ in gates.items() if not open_)
        self.last_gate_report = {
            "batch": len(batch),
            "gates": gates,
            "closed": closed,
        }
        if closed:
            self._gated_cycles += 1
            counter = sched.extender.registry.get(
                "pipeline_gate_closed_total"
            )
            for g in closed:
                counter.labels(gate=g).inc()
            return False
        self._fast_cycles += 1
        return True
