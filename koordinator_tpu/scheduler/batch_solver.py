"""BatchScheduler: snapshot → jitted solver → host-side Reserve commit.

The rebuild's analog of the reference's scheduling cycle
(``cmd/koord-scheduler/app/server.go:356-453`` setup + upstream
``scheduleOne``): instead of popping one pod at a time, pending pods are
drained in priority-bucketed batches, lowered to dense arrays, solved on TPU
(``ops.solver.assign``), and the nominations are committed host-side with
revalidation — the solver proposes, Reserve disposes (SURVEY §7 hard part
(a)). Rejected nominations simply stay pending for the next batch.
"""

from __future__ import annotations

import dataclasses
import functools
import queue as _queue
import threading as _threading
import time as _time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import extension as ext
from ..api.types import Pod
from ..chaos import NULL_INJECTOR, FaultInjector
from ..core.journal import JournalWriteError, StaleEpochError
from ..core.snapshot import ClusterSnapshot, SnapshotConfig, bucket_size
from ..obs import RejectReason, RejectStage, report_exception
from ..obs import devprof as _devprof
from ..obs.devprof import NULL_WATCH as _NULL_WATCH
from ..ops import estimator
from ..runtime.containment import (
    POISON_LABEL,
    PoisonBatchError,
    spec_fingerprint,
)
from ..ops.solver import (
    NodeState,
    PodBatch,
    QuotaState,
    SolverParams,
    SolveResult,
    assign,
    gather_rows,
    gather_rows_sharded,
    scatter_rows,
    scatter_rows_sharded,
)


@dataclasses.dataclass
class LoadAwareArgs:
    """LoadAwareScheduling plugin args (reference
    ``pkg/scheduler/apis/config/types.go`` ``LoadAwareSchedulingArgs``).

    Thresholds are percent of allocatable per resource name; 0/absent
    disables the check for that dim. ``estimator_scales`` mirrors
    DefaultEstimator's per-resource scaling factors.
    """

    usage_thresholds: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 65.0, ext.RES_MEMORY: 95.0}
    )
    prod_usage_thresholds: Mapping[str, float] = dataclasses.field(default_factory=dict)
    resource_weights: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 1.0, ext.RES_MEMORY: 1.0}
    )
    estimator_scales: Mapping[str, float] = dataclasses.field(default_factory=dict)
    node_metric_expiration_s: float = 180.0
    aggregated_usage_type: str = "p95"
    #: filter nodes whose NodeMetric has expired (load_aware.go:143-149;
    #: v1beta3's hand-written conversion FORCES this true,
    #: conversion_plugin.go:25-33, while v1 honors the configured value)
    filter_expired_node_metrics: bool = True
    #: whether expired-metric nodes may still schedule (usage checks
    #: skipped). The reference defaults this FALSE (strict) for configs
    #: decoded through the componentconfig (defaults.go:94-95); the
    #: in-process default stays True so metric-less simulations and
    #: embedders keep scheduling (a never-reported node is always
    #: admitted either way, like the Filter's nil-NodeMetric path)
    enable_schedule_when_node_metrics_expired: bool = True

    def solver_params(self, config: SnapshotConfig) -> SolverParams:
        res = config.resources

        def vec(table: Mapping[str, float], default: float = 0.0) -> jnp.ndarray:
            return jnp.asarray(
                [float(table.get(r, default)) for r in res], jnp.float32
            )

        return SolverParams(
            usage_thresholds=vec(self.usage_thresholds),
            prod_thresholds=vec(self.prod_usage_thresholds),
            score_weights=vec(self.resource_weights),
        )

    def scale_vector(self, config: SnapshotConfig) -> np.ndarray:
        return estimator.scale_vector(config.resources, self.estimator_scales)


#: upstream kube-scheduler's floor: clusters at or below this size are
#: always fully scored (minFeasibleNodesToFind)
MIN_FEASIBLE_NODES_TO_FIND = 100


#: refcounted process-wide GC pause (advisor r4): two schedulers with
#: overlapping cycles must keep the collector paused until the LAST cycle
#: exits — a bare disable()/enable() pair re-enables GC in the middle of
#: the other scheduler's cycle, silently losing its commit-p99 protection
_gc_lock = _threading.Lock()
_gc_depth = 0
_gc_was_enabled = False

#: cache-miss sentinel for the reservation fast path's pre-pass match
#: cache (None is a legitimate cached value: "no reservation matches")
_PREMATCH_MISS = object()


def _gc_pause() -> None:
    import gc

    global _gc_depth, _gc_was_enabled
    with _gc_lock:
        if _gc_depth == 0:
            _gc_was_enabled = gc.isenabled()
            if _gc_was_enabled:
                gc.disable()
        _gc_depth += 1


def _gc_resume() -> None:
    import gc

    global _gc_depth
    with _gc_lock:
        _gc_depth -= 1
        if _gc_depth == 0 and _gc_was_enabled:
            gc.enable()


def num_nodes_to_score(n_nodes: int, percentage: int = 0) -> int:
    """Upstream kube-scheduler ``numFeasibleNodesToFind``, which the
    reference passes through verbatim
    (``cmd/koord-scheduler/app/server.go:411``
    WithPercentageOfNodesToScore): clusters ≤100 nodes are fully scored;
    ``percentage`` 0 selects the adaptive ``50 − n/125`` (floored at 5%);
    the sampled count never drops below 100 nodes."""
    if n_nodes <= MIN_FEASIBLE_NODES_TO_FIND:
        return n_nodes
    pct = percentage
    if pct <= 0:
        pct = 50 - n_nodes // 125
        if pct < 5:
            pct = 5
    if pct >= 100:
        return n_nodes
    return max(n_nodes * pct // 100, MIN_FEASIBLE_NODES_TO_FIND)


@jax.jit
def _chain_commit_deltas(cur, nodes_t, result):
    """Carry only the solver's commit deltas onto the untransformed base
    state (one fused dispatch): a node transformer's rewrite applies
    exactly once per chunk, never compounded across the pipeline."""
    _devprof.tracing("_chain_commit_deltas")
    return cur.replace(
        requested=cur.requested + (result.node_requested - nodes_t.requested),
        estimated_used=cur.estimated_used
        + (result.node_estimated_used - nodes_t.estimated_used),
        prod_used=cur.prod_used
        + (result.node_prod_used - nodes_t.prod_used),
    )


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _apply_commit_deltas_donated(
    cur_req, cur_est, cur_prod, t_req, t_est, t_prod, r_req, r_est, r_prod
):
    """Donating form of the chained-state delta apply, used from the
    SECOND chunk onward (donation follow-on, ROADMAP item a): by then the
    carried requested/estimated/prod arrays are the previous chunk's
    chain outputs — referenced only by the chain, never re-read — so XLA
    writes the new chain state into the same [N, D] buffers instead of
    allocating three fresh ones per chunk. Chunk 0's carry aliases the
    device-RESIDENT arrays (re-read every cycle) and must go through the
    non-donating :func:`_chain_commit_deltas`."""
    _devprof.tracing("_apply_commit_deltas_donated")
    return (
        cur_req + (r_req - t_req),
        cur_est + (r_est - t_est),
        cur_prod + (r_prod - t_prod),
    )


@dataclasses.dataclass
class LoweredRows:
    """Host-side per-chunk lowering stash shared by solve() and _commit():
    Reserve revalidation and assume charges reuse these instead of
    recomputing res_vector / estimator / QoS predicates per winner (the
    recompute was a measurable slice of the per-batch host time). ``uids``
    guards the temporal coupling between pod_batch and _commit."""

    uids: Tuple[str, ...]
    req: np.ndarray       # [P, D] request rows (res_vector lowering)
    est: np.ndarray       # [P, D] estimator rows
    bind: np.ndarray      # [P] bool wants_cpu_bind
    prio: np.ndarray      # [P] int32 raw priority
    is_prod: np.ndarray   # [P] bool PROD band
    #: device request columns (parsed once per chunk; the per-winner
    #: parse_gpu_request/parse_rdma_request calls were a visible slice of
    #: the constrained commit loop). None when stashed by a path that
    #: didn't lower them — the batched Reserve then treats every pod as
    #: device-free, matching the manager-less fast path.
    gpu_whole: Optional[np.ndarray] = None   # [P] int32
    gpu_share: Optional[np.ndarray] = None   # [P] float32
    rdma: Optional[np.ndarray] = None        # [P] int32
    fpga: Optional[np.ndarray] = None        # [P] int32
    #: whether any pod in the chunk belongs to a gang (permit bypass)
    has_gangs: bool = True
    #: [P, L] lowered leaf-to-root quota index paths (−1 padding); the
    #: commit's quota accounting reuses them instead of re-walking names
    quota_chain: Optional[np.ndarray] = None
    #: [P] bool — pod requires single-NUMA placement (numa-topology-spec)
    numa_required: Optional[np.ndarray] = None


@dataclasses.dataclass
class _HostSolve:
    """Already-fetched solve outputs from the scanned dispatch — the
    commit loop consumes these without further device round trips."""

    assignment: np.ndarray
    pod_zone: Optional[np.ndarray]
    rounds_used: int
    #: [2] int64 — shortlist escape-hatch rounds (bound, infeasible)
    shortlist_fallbacks: Optional[np.ndarray] = None


class _FetchStalled(RuntimeError):
    """The solver-result feeder queue produced nothing within the fetch
    deadline — the prefetch worker wedged or died. The commit loop
    surfaces the remaining chunks as a counted RejectReason and their
    pods re-enter the next cycle (robustness PR satellite: a full
    ``fq.put``/``fq.get`` pair must never silently stall the drain)."""


class _ReserveJournal:
    """Transactional journal for one chunk's host-side Reserve.

    ``_reserve_batch`` records every mutation it makes — fresh assumes,
    idempotent re-assumes (with the pod's PRIOR charge captured), and
    NUMA/device holds — so a failure anywhere between assume and Permit
    (the reference's crash-mid-commit window, injected via the
    ``commit.crash`` chaos point) rolls the chunk back to its pre-commit
    state. Rollback goes through ``forget_pod``/``restore_assumed``/
    ``release``, all of which touch the snapshot's dirty-row ledger, so
    the device-resident NodeState reconverges bit-exactly on the next
    refresh (verified against a full re-lower by the chaos tests)."""

    __slots__ = ("fresh", "reassumed", "numa_holds", "dev_holds")

    def __init__(self):
        self.fresh: List[str] = []                    # fresh assume uids
        self.reassumed: List[tuple] = []              # (uid, prior entry)
        self.numa_holds: Dict[str, str] = {}          # uid -> node
        self.dev_holds: Dict[str, str] = {}           # uid -> node

    def rollback(self, sched: "BatchScheduler") -> None:
        snap = sched.snapshot
        for uid, node in self.dev_holds.items():
            if sched.devices is not None:
                sched.devices.release(uid, node)
        for uid, node in self.numa_holds.items():
            if sched.numa is not None:
                sched.numa.release(uid, node)
        for uid in self.fresh:
            snap.forget_pod(uid)
        for uid, prior in self.reassumed:
            snap.restore_assumed(uid, prior)


@dataclasses.dataclass
class ChainCarry:
    """Device-chained commit state spanning a cycle boundary (open the
    speculation gates PR): the post-solve tables of one speculative
    dispatch, handed to the NEXT cycle's dispatch as its chunk-0 inputs.
    ``nodes`` is the PR-4 node-capacity chain; the constrained
    subsystems ride beside it the same way ``solve_stream_full``'s scan
    state already chains them WITHIN a cycle — the solver outputs ARE
    the chained tables, so extending the carry across the boundary costs
    zero extra dispatches."""

    #: NodeState with post-solve requested/estimated_used/prod_used
    #: (static leaves aliased)
    nodes: object
    #: [2Q, D] post-commit extended quota-used table (None = no tree)
    quota_used: object = None
    #: (slot_free [N, G], rdma_free [N], fpga_free [N]) or None
    dev: object = None
    #: [N, Z, DN] post-commit NUMA zone-free table or None
    numa_zone: object = None
    #: predicted post-fast-path reservation overlay
    #: (:class:`~.plugins.reservation.ResvView`) — the HOST-side leg of
    #: the chain (open the last gates PR): a downstream chained dispatch
    #: previews ITS fast path against this cycle's predicted reservation
    #: ledger, exactly like ``quota_used`` chains the device ledger.
    #: None = reservations absent (or a fresh dispatch's empty overlay)
    resv_view: object = None


@dataclasses.dataclass
class _QuotaCarryMeta:
    """Validation inputs for a quota-bearing speculative solve: the
    exact (runtime, used) tables chunk 0 consumed, plus the tree shape
    the quota chains were lowered against."""

    used_in: object          # device [2Q, D] (chained) or host copy (fresh)
    runtime_host: np.ndarray  # host [2Q, D] preview the solve uploaded
    tree_version: int


@dataclasses.dataclass
class _NumaCarryMeta:
    """Validation inputs for a NUMA-bearing speculative solve. The
    structural tables are HOST COPIES taken at dispatch — the resident
    device copies are donation targets of the next dirty-row scatter and
    must never be re-read at consume time."""

    zone_in: object          # device carry (chained) or host copy (fresh)
    zone_cap: np.ndarray
    policy: np.ndarray
    zone_most: np.ndarray


@dataclasses.dataclass
class _DevCarryMeta:
    """Validation inputs for a device-bearing speculative solve (same
    host-copy discipline as :class:`_NumaCarryMeta`)."""

    slots_in: object         # device carry (chained) or host copy (fresh)
    rdma_in: object          # None when RDMA untracked
    fpga_in: object          # None when FPGA untracked
    cap: np.ndarray
    has_rdma: bool
    has_fpga: bool


@dataclasses.dataclass
class _ResvCarryMeta:
    """Validation inputs for a reservation-bearing speculative solve
    (open the last gates PR). The fast path runs at the START of the
    consuming cycle — before the chunks the speculation solved — so the
    dispatch PREDICTS its outcome (pure overlay preview) and the consume
    guard proves the prediction by value: the table the preview started
    from must equal the live table at cycle start, the actual fast-path
    binds/affinity verdicts must equal the predicted ones, and the live
    post-fast-path table must equal the predicted post table. Any bind
    that flipped a rival's spill feasibility differently than predicted
    shows up in one of the three and discards the speculation."""

    #: predicted ordered fast-path binds: ((uid, reservation, node), ...)
    binds: tuple = ()
    #: predicted required-affinity unschedulable uids (excluded from the
    #: solver chunks, like the real fast path excludes them)
    affinity_unsched: tuple = ()
    #: reservation table the preview started from (upstream predicted
    #: post state for a chained dispatch; live state for a fresh one)
    pre_table: tuple = ()
    #: predicted post-fast-path table
    post_table: tuple = ()


@dataclasses.dataclass
class CarryMeta:
    """Everything consume-time validation needs to prove the speculative
    solve's inputs equal what a fresh serial dispatch would lower NOW —
    bit-exact value comparison per carried table, not trust. One field
    per opened gate; None means the subsystem was absent at dispatch
    (and must still be absent at consume)."""

    quota: Optional[_QuotaCarryMeta] = None
    numa: Optional[_NumaCarryMeta] = None
    dev: Optional[_DevCarryMeta] = None
    #: frozen (key, outstanding_min, nonstrict) per gang in the batch,
    #: as the lowering's live views read them (empty = gang-free batch)
    gangs: tuple = ()
    #: reservation fast-path prediction (None = reservations absent)
    resv: Optional[_ResvCarryMeta] = None
    #: mode flags the dispatch baked in (reservation attachment,
    #: defer/priority/quota preemption) — a mid-pipeline flip changes
    #: PostFilter behavior without bumping any version, so it is
    #: compared by value like the tables
    modes: tuple = ()


@dataclasses.dataclass
class SpeculativeSolve:
    """An in-flight cross-cycle solve dispatched by the CyclePipeline:
    chunked solves chained off the previous cycle's on-device commit
    state, plus everything ``_schedule_locked`` needs to verify the
    speculation still matches reality at consume time."""

    #: per-chunk pod uid tuples — the consuming cycle's chunking must
    #: reproduce them exactly
    chunk_uids: Tuple[Tuple[str, ...], ...]
    #: sampled node window the solves ran over (None = full axis; the
    #: pipeline gates require None today)
    sub: Optional[np.ndarray]
    #: [(chunk, LoweredRows, SolveResult)] — the commit loop's shape
    solves: list
    #: post-solve chained state (nodes + quota/device/NUMA tables) —
    #: becomes the NEXT cycle's chain when the commit is clean
    chain_out: ChainCarry
    #: snapshot version at dispatch (under the lock); any write since
    #: invalidates
    version: int
    node_epoch: int
    #: consume-time validation inputs for the carried subsystems
    carry: CarryMeta = dataclasses.field(default_factory=CarryMeta)
    #: NaN-guard verdicts collected during the speculative lowering,
    #: merged into the consuming cycle's quarantine
    quarantine: Dict[str, tuple] = dataclasses.field(default_factory=dict)
    #: wall instant of dispatch (for the pipeline's overlap span)
    dispatched_at: float = 0.0


@dataclasses.dataclass
class _ResvPlan:
    """One dispatch-side fast-path preview run (see
    :meth:`BatchScheduler._reservation_fastpath_preview`)."""

    binds: tuple
    affinity_unsched: tuple
    #: uids leaving the solver path via a predicted fast-path bind
    taken: frozenset
    pre_table: tuple
    post_table: tuple
    #: post-prediction overlay (rides the ChainCarry for downstream
    #: chained previews)
    view: object
    #: [(node idx, d_requested, d_estimated, d_prod)] — predicted
    #: snapshot effects (owner assume + ghost forget + remainder assume)
    node_deltas: list
    #: the quota preview the plan charged into (reused by the dispatch
    #: when it TRUSTS a prepare-time plan — see _dispatch_chained)
    quota_prev: Optional[_QuotaFastpathPreview] = None


class _QuotaFastpathPreview:
    """Pure mirror of ``GroupQuotaManager.has_headroom`` for the
    dispatch-side reservation preview: headroom answered against the
    PREDICTED used/non-preemptible ledgers (the device carry's
    post-commit rows for a chained dispatch, the live rows for a fresh
    one) and the runtime the consuming cycle's fast path will actually
    read (the PREVIOUS cycle's refreshed runtime — a fast-path headroom
    check runs before the consuming cycle's own demand propagation).
    Predicted fast-path charges accumulate in the copies so later pods
    in the same preview — and the speculative solve's used table — see
    them, exactly like the real path's ``assign_pod`` charges."""

    __slots__ = ("quotas", "config", "used", "nonpre", "runtime", "charged")

    def __init__(self, quotas, config, used, nonpre, runtime):
        self.quotas = quotas
        self.config = config
        self.used = used          # [Q, D] mutable copy
        self.nonpre = nonpre      # [Q, D] mutable copy
        self.runtime = runtime    # [Q, D] read-only
        self.charged = False

    def headroom(self, leaf: str, requests, non_preemptible: bool) -> bool:
        # delegates to the manager's shared chain-walk arithmetic —
        # ONE copy of the admission math for the live check and the
        # preview, so they cannot drift
        return self.quotas.headroom_in(
            leaf,
            self.config.res_vector(requests),
            non_preemptible,
            self.used,
            self.nonpre,
            self.runtime,
        )

    def charge(self, leaf: str, requests, non_preemptible: bool) -> None:
        if self.quotas.charge_in(
            leaf,
            self.config.res_vector(requests),
            non_preemptible,
            self.used,
            self.nonpre,
        ):
            self.charged = True


@dataclasses.dataclass
class ScheduleOutcome:
    bound: List[Tuple[Pod, str]]
    unschedulable: List[Pod]
    rounds_used: int = 0
    #: victims evicted by quota preemption this cycle (the caller performs
    #: the actual eviction, like the reference's evictor plugins)
    preempted: List[Pod] = dataclasses.field(default_factory=list)


class BatchScheduler:
    """Drains pending pods through the TPU solver in fixed-shape batches."""

    def __init__(
        self,
        snapshot: Optional[ClusterSnapshot] = None,
        args: Optional[LoadAwareArgs] = None,
        batch_bucket: int = 4096,
        max_rounds: int = 16,
        shortlist_k: Optional[int] = 64,
        pod_groups: Optional["PodGroupManager"] = None,
        quotas: Optional["GroupQuotaManager"] = None,
        numa: Optional["NUMAManager"] = None,
        devices: Optional["DeviceManager"] = None,
        extender: Optional["FrameworkExtender"] = None,
        defer_preemption: bool = False,
        enable_priority_preemption: bool = False,
        defer_gc: bool = True,
        percentage_of_nodes_to_score: int = 100,
        mesh=None,
        chaos: Optional[FaultInjector] = None,
        cycle_deadline_s: Optional[float] = None,
        fallback_repromote_after: int = 3,
        fetch_timeout_s: float = 30.0,
        intern_pods: bool = True,
        journal=None,
        fence=None,
        journal_compact_records: Optional[int] = None,
        journal_compact_bytes: Optional[int] = None,
        scrub_rows: Optional[int] = None,
    ):
        from .frameworkext import FrameworkExtender
        from .plugins.coscheduling import PodGroupManager
        from .plugins.elasticquota import GroupQuotaManager

        self.snapshot = snapshot or ClusterSnapshot()
        self.args = args or LoadAwareArgs()
        # wire plugin args into metric ingest (agg percentile + expiry)
        self.snapshot.agg_type = self.args.aggregated_usage_type
        self.snapshot.metric_expiry_s = self.args.node_metric_expiration_s
        self.batch_bucket = batch_bucket
        self.max_rounds = max_rounds
        #: candidate-shortlist solve (node-axis pruning PR): per-pod
        #: top-K build-time candidates bound the round loop's [P, N]
        #: tensors to [P, K]; decisions stay identical via the exactness
        #: bound + full-axis escape hatch (ops.solver). None/0 disables.
        #: The effective static arg is power-of-two bucketed
        #: (:meth:`_shortlist_bucket`) so a tuned knob can't mint a new
        #: trace key per value.
        self.shortlist_k = shortlist_k
        self.pod_groups = pod_groups or PodGroupManager()
        self.quotas = quotas or GroupQuotaManager(self.snapshot.config)
        self.numa = numa
        self.devices = devices
        #: set by plugins.reservation.ReservationManager when attached
        self.reservations = None
        #: frameworkext spine: transformers, monitor, errors, debug, services
        self.extender = extender or FrameworkExtender()
        # the watchdog must sweep concurrently — a hung solve can't sweep
        # itself (scheduler_monitor.go runs it on its own goroutine)
        self.extender.monitor.start_background()
        self._params = self.args.solver_params(self.snapshot.config)
        self._scales = self.args.scale_vector(self.snapshot.config)
        # per-chunk lowered host rows, filled by pod_batch for _commit
        d = len(self.snapshot.config.resources)
        self._lowered = LoweredRows(
            uids=(),
            req=np.zeros((0, d)),
            est=np.zeros((0, d)),
            bind=np.zeros((0,), bool),
            prio=np.zeros((0,), np.int32),
            is_prod=np.zeros((0,), bool),
        )
        #: pod uid → node for bound pods (preemption victim lookup)
        self._bound_nodes: Dict[str, str] = {}
        #: uid → (stage, plugin, reason) for the CURRENT chunk's Reserve/
        #: Permit rejections, reset per _commit; joined with the host-side
        #: mask classification into rejection records
        self._reserve_reject: Dict[str, tuple] = {}
        #: commit-loop rejections buffered within one external cycle and
        #: flushed at its end — a pod the postfilter retry later binds
        #: must leave no record (the log means "this cycle failed to
        #: place the pod", not "some attempt inside it did")
        self._cycle_rejects: List[tuple] = []
        #: pod uid → Pod for bound pods (the reference cache's NodeInfo
        #: pod inventory — priority preemption picks victims from it)
        self._bound_pods: Dict[str, Pod] = {}
        #: priority-based preemption at PostFilter (the reservation
        #: plugin's preemption manager; ReservationArgs.EnablePreemption,
        #: default false per v1beta3/defaults.go:52)
        self.enable_priority_preemption = enable_priority_preemption
        #: True = quota preemption NOMINATES victims in
        #: ScheduleOutcome.preempted without evicting or retrying — the
        #: caller routes them through the descheduler's migration
        #: machinery (PodMigrationJob → evictor) and the preemptor
        #: retries next cycle once the evictions have landed. False
        #: (default) keeps the synchronous PostFilter behavior: evict
        #: internally and retry within the same call.
        self.defer_preemption = defer_preemption
        #: pause the cyclic garbage collector for the duration of one
        #: scheduling cycle (re-enabled on exit, so collection runs
        #: BETWEEN cycles): a gen-2 collection over the scheduler's
        #: object graph pauses 50-150 ms mid-commit and was the dominant
        #: source of per-chunk commit p99 spikes — the pause-free
        #: equivalent of what the reference gets from Go's concurrent GC.
        self.defer_gc = defer_gc
        #: kube-scheduler PercentageOfNodesToScore, passed through by the
        #: reference (``cmd/koord-scheduler/app/server.go:411``): 100 =
        #: score every node (default — full batched solve); 1-99 = score
        #: a rotating window of that share per cycle; 0 = upstream's
        #: adaptive 50 − n/125 (floor 5%). Sampling bounds the solve's
        #: node axis, which is what a latency-oriented deployment wants
        #: at 10k+ nodes (the upstream default at that scale is 5%).
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        #: rotating sample start (upstream nextStartNodeIndex analog)
        self._score_start = 0
        #: node names the next _select_nodes call must include beyond the
        #: rotating window — set by the preemption pass so the retry sees
        #: the nodes its victims were evicted from (consumed once)
        self._window_extra_nodes: set = set()
        #: pod uid → consecutive preemption-skip count under a sampled
        #: window (anti-starvation bookkeeping for the headroom gate)
        self._preempt_skips: Dict[str, int] = {}
        #: device-resident cluster state (perf tentpole): the full-axis
        #: NodeState lives on device across cycles and is refreshed by a
        #: jitted scatter of only the snapshot rows touched since the last
        #: refresh (full re-lower only on bucket growth / reset / flag
        #: change); the quota and NUMA/device tables carry the same
        #: versioned-upload cache, and sampled windows are gathered on
        #: device from the resident arrays instead of re-padded host-side
        self._resident_nodes: Optional[NodeState] = None
        self._resident_key: Optional[tuple] = None
        self._resident_version: int = -1
        #: (key, NodeState) for the last sampled-window gather
        self._window_cache: Optional[tuple] = None
        #: (key, QuotaState) / (key, NumaState) / (key, DeviceState)
        self._quota_dev_cache: Optional[tuple] = None
        self._numa_dev_cache: Optional[tuple] = None
        self._device_dev_cache: Optional[tuple] = None
        #: (key, (NumaState, DeviceState)) for the sampled-window gather
        self._constraint_window_cache: Optional[tuple] = None
        #: multi-chip production mode: a jax.sharding.Mesh over ("dp",
        #: "tp") — pod rows shard on dp, node-axis tables on tp, and
        #: GSPMD inserts the ICI collectives inside the SAME jitted
        #: solver (parallel.sharded; reference analog: parallelism wired
        #: into the scheduler at cmd/koord-scheduler/app/server.go:417).
        #: None = single-device dispatch.
        self.mesh = mesh
        #: fault injector (chaos points ``solver.dispatch``,
        #: ``solver.nan_rows``, ``solver.fetch.stall``, ``commit.crash``);
        #: the shared NULL injector costs one attribute read when unused
        self.chaos = chaos or NULL_INJECTOR
        if chaos is not None and chaos.counter is None:
            chaos.counter = self.extender.registry.get(
                "fault_injected_total"
            )
        #: per-cycle wall deadline (None = unbounded): a cycle that blows
        #: it stops committing further chunks (their pods retry next
        #: cycle) and degrades to a smaller batch bucket instead of
        #: wedging the drain behind one oversized cycle
        self.cycle_deadline_s = cycle_deadline_s
        #: fallback ladder (0 = scanned multi-chunk, 1 = per-chunk,
        #: 2 = host numpy reference). A dispatch failure demotes the
        #: ladder for subsequent cycles; ``fallback_repromote_after``
        #: consecutive clean cycles re-promote one level.
        self.fallback_repromote_after = max(1, fallback_repromote_after)
        self._fallback_level = 0
        self._fallback_clean = 0
        #: batch-bucket degradation exponent after deadline overruns
        #: (effective bucket = batch_bucket >> degrade, floor 16)
        self._bucket_degrade = 0
        self._degrade_clean = 0
        #: deadline the solver-result fetch may block before the chunk is
        #: surfaced as SOLVE_RESULT_STALLED (feeder-queue satellite)
        self.fetch_timeout_s = fetch_timeout_s
        #: uid -> (stage, plugin, reason) for rows the NaN/Inf guard
        #: quarantined this cycle (cleared per external cycle)
        self._numeric_quarantine: Dict[str, tuple] = {}
        #: gray-failure containment PR: optional
        #: runtime.containment.QuarantineLedger — when wired, the cycle
        #: gate rejects blamed pods (POISON_QUARANTINED) before they can
        #: re-crash a dispatch, and the bisection containment records
        #: blame when the fallback ladder's floor raises. None = the
        #: pre-PR behavior (a poison batch fails the whole cycle).
        self.quarantine = None
        #: gray-failure containment PR: optional zero-arg callable (the
        #: StalenessWatchdog's ``stale`` bound method) snapshotted ONCE
        #: per cycle into ``_cycle_stale`` — evidence-hungry actions
        #: (preemption) refuse on stale informer evidence while plain
        #: placement continues. None = always fresh.
        self.staleness = None
        self._cycle_stale = False
        #: pods isolated by the poison bisection THIS dispatch (consumed
        #: into unschedulable right after _dispatch_with_fallback)
        self._cycle_poisoned: List[Pod] = []
        #: resident PodBatch interning (ROADMAP item c): lowered per-pod
        #: rows cached across cycles keyed on (uid, spec fingerprint) so a
        #: retry-heavy stream doesn't re-parse the same still-pending pod
        #: every cycle; evicted on bind/drop, trimmed oldest-half on
        #: overflow. None disables (intern_pods=False).
        self._pod_intern: Optional[Dict[str, object]] = (
            {} if intern_pods else None
        )
        #: cross-cycle pipelining (perf PR 4): a CyclePipeline parks its
        #: speculatively dispatched solves here; _schedule_locked consumes
        #: them when the guards (uids / snapshot version / node epoch /
        #: bucket) still hold, else falls back to a fresh dispatch
        self._speculative = None
        self._cycle_used_spec = False
        self._cycle_reserve_rejected = False
        self._cycle_preempted = False
        #: snapshot versions at cycle entry/exit (under the cycle lock) —
        #: the pipeline uses them to detect external writes racing the
        #: prepare/solve stages
        self._pre_cycle_version = -1
        self._post_cycle_version = -1
        #: per-cycle flags consumed by the tail bookkeeping
        self._cycle_solver_failed = False
        self._cycle_deadline_hit = False
        self._cycle_commit_rolled_back = False
        self._cycle_fetch_deferred = False
        self._cycle_t0 = 0.0
        self._cycle_journal_failed = False
        #: HA layer (failover PR): write-ahead bind journal + leadership
        #: fence. ``journal`` is a core.journal.BindJournal — every chunk
        #: commit appends an intent record BEFORE mutating the snapshot
        #: and a bind record before acknowledging; ``fence`` is the
        #: EpochFence checked at the commit boundary so a deposed
        #: leader's in-flight commit is rejected (STALE_LEADER_EPOCH)
        #: instead of double-placing. ``_fence_epoch`` is the epoch of
        #: the current grant (-1 = locally revoked).
        self.bind_journal = journal
        self.fence = fence
        self._fence_epoch = 0
        #: distributed observability (fleet-tracing PR): optional per-pod
        #: lifecycle tracker (obs.lifecycle.PodLifecycle) — when wired,
        #: bind-journal entries carry the pod's compact trace context so
        #: a takeover's replay can bridge the timeline across the crash;
        #: optional crash-surviving flight recorder (attach via
        #: attach_flight_recorder) receiving one per-cycle summary; the
        #: stream pump hints its backlog depth here for that record
        self.lifecycle = None
        self.flight_recorder = None
        #: decision observatory (obs.decisions.DecisionLedger): when
        #: wired, every controller decision (pipeline depth, brownout,
        #: admission, breaker, topology) records its full input snapshot
        #: here. None = disabled; every record site is one
        #: attribute-is-None check. Attach via attach_decision_ledger.
        self.decision_ledger = None
        #: solver observatory (obs.devprof.DevProf): compile/retrace
        #: ledger + on-demand device-timeline capture + per-cycle
        #: device-memory census. None = disabled; every hot-path site is
        #: one attribute-is-None check (PR 1/PR 7 standing rule). Attach
        #: via attach_devprof.
        self.devprof = None
        #: brownout ladder (overload-control PR): when wired, L2+ adds a
        #: batch-bucket degrade step (effective_batch_bucket) and closes
        #: the pipeline's ``brownout`` speculation gate. None = normal
        #: operation; every consumer is one attribute-is-None check.
        self.brownout = None
        self._queue_depth_hint = 0
        #: most recent pipeline gate evaluation (set by CyclePipeline)
        self.last_gate_report: Dict[str, object] = {}
        self._cycle_fenced = False
        self._cycle_spec_outcome = ""
        #: reservation-carry consume evidence (open the last gates PR):
        #: the pre-fast-path snapshot version + reservation table and
        #: the fast path's ACTUAL (uid, reservation, node) binds and
        #: required-affinity refusals, captured per cycle and compared
        #: by value against the speculation's predictions
        self._cycle_prefast_version = -1
        self._cycle_resv_binds: List[tuple] = []
        self._cycle_resv_affinity: tuple = ()
        self._cycle_resv_pre_table = None
        #: adaptive-depth decision for this cycle — (chosen depth, max
        #: depth, discard-rate input), stamped by the CyclePipeline
        #: before the trailing commit so the flight recorder can explain
        #: the choice post-hoc
        self._depth_decision: Optional[tuple] = None
        #: periodic journal compaction from the run loop (PR 6
        #: satellite, ROADMAP queued follow-on): after a clean cycle,
        #: compact once at least this many records (or bytes, for file
        #: stores) accumulated since the last checkpoint. None = never.
        self.journal_compact_records = journal_compact_records
        self.journal_compact_bytes = journal_compact_bytes
        #: invoked (no args) after a successful run-loop journal
        #: compaction — the sharded runtime hangs ClaimTable tombstone
        #: GC off it so claim compaction rides the same maintenance beat
        self.on_journal_compacted = None
        if journal is not None:
            reg = self.extender.registry
            if journal.writes_counter is None:
                journal.writes_counter = reg.get("journal_writes_total")
            if journal.failures_counter is None:
                journal.failures_counter = reg.get(
                    "journal_write_failures_total"
                )
            if journal.chaos is NULL_INJECTOR:
                # journal.write_fail fires from the scheduler's injector
                # unless the journal brought its own
                journal.chaos = self.chaos
            # state-integrity PR: the journal's store counts quarantined
            # records per store, and corruption flips the
            # journal_integrity health row to degraded
            store = journal.store
            if hasattr(store, "integrity_total"):
                # rewired UNCONDITIONALLY: a store surviving a crash
                # restart still points at the dead incarnation's
                # registry child, and this scheduler's /metrics must
                # count. The fresh child is backfilled with the store's
                # cumulative findings (detections that predate the
                # wiring — the journal's own init load screens before
                # the scheduler exists — and prior incarnations')
                store.corrupt_counter = reg.get(
                    "journal_corrupt_records_total"
                ).labels(store=getattr(store, "name", "journal"))
                backlog = (
                    store.integrity_total.corrupt
                    + store.integrity_total.seq_gaps
                )
                if backlog:
                    store.corrupt_counter.inc(float(backlog))
            if journal.health is None:
                journal.health = self.extender.health
                journal._note_integrity()
        #: anti-entropy scrubber (state-integrity PR): rows audited per
        #: scrub_step call (None = scrubbing disabled; the run loop's
        #: tail bookkeeping then never audits). Each step re-lowers a
        #: rotating window of host truth and compares it bit-exact
        #: against the device-resident tables, self-healing divergence
        #: through the dirty-row scatter.
        self.scrub_rows = scrub_rows
        self._scrub_cursor = 0
        self._scrub_report: Dict[str, object] = {
            "enabled": scrub_rows is not None,
            "window": int(scrub_rows or 0),
            "cursor": 0,
            "steps": 0,
            "rows_audited": 0,
            "divergence": {},
            "last": {},
        }
        self.extender.services.scrub = lambda: dict(self._scrub_report)
        self.extender.health.set("solver", True)
        self.extender.health.set("commit", True)

    def attach_flight_recorder(self, recorder) -> None:
        """Wire a crash-surviving flight recorder: every completed cycle
        appends one summary record, and the services engine serves the
        ring at ``/debug/flightrecorder``."""
        self.flight_recorder = recorder
        self.extender.services.flightrecorder = recorder

    def attach_decision_ledger(self, ledger) -> None:
        """Wire the controller-decision ledger: the pipeline's depth
        controller and any attached overload/topology controllers
        record their decisions here, counters bind to this scheduler's
        registry, and the services engine serves the ring at
        ``/debug/decisions``."""
        self.decision_ledger = ledger
        ledger.bind_registry(self.extender.registry)
        if self.flight_recorder is not None:
            ledger.attach_flight(self.flight_recorder)
        self.extender.services.decisions = ledger

    def attach_devprof(self, devprof) -> None:
        """Wire the solver observatory (obs.devprof.DevProf): installs
        the trace-time retrace hook, serves the ledger at
        ``/debug/compiles`` and the capture window at ``/debug/profile``,
        and samples the device-memory census every cycle."""
        self.devprof = devprof.install()
        self.extender.services.devprof = devprof

    # ---- HA: leadership grant/revoke (driven by the LeaderCoordinator) ----

    def grant_leadership(self, epoch: int) -> None:
        """Adopt a fencing epoch: subsequent commits carry it and pass
        the fence while it stays the current grant."""
        self._fence_epoch = int(epoch)
        reg = self.extender.registry
        reg.get("leader_transitions_total").inc()
        reg.get("leader_epoch").set(float(epoch))
        self.extender.health.set("leader", True, f"leader epoch={epoch}")

    def revoke_leadership(self, detail: str = "") -> None:
        """Leadership lost: stamp the local revoked sentinel so every
        in-flight commit fails the fence regardless of who (if anyone)
        holds the new grant, and surface the standby state on /healthz."""
        self._fence_epoch = -1
        self.extender.registry.get("leader_epoch").set(-1.0)
        self.extender.health.set(
            "leader", True, detail or "standby (leadership revoked)"
        )

    # ---- device lowering ----

    def _select_nodes(
        self, pending: Sequence[Pod] = ()
    ) -> Optional[np.ndarray]:
        """Real node indices to lower this cycle, or None for all (the
        kube-scheduler node-sampling pass: a rotating window of
        ``num_nodes_to_score`` nodes, advanced per cycle like upstream's
        nextStartNodeIndex so every node is visited fairly).

        Hard-constrained pods must always reach their nodes (upstream's
        sampling keeps scanning until enough FEASIBLE nodes are found, so
        a pinned pod can never rotate out — advisor r4): node names
        referenced by spec.nodeName / required node affinity are unioned
        into the window; a label nodeSelector can match any node, so any
        selector-carrying pod disables sampling for the cycle."""
        n_real = self.snapshot.node_count
        want = num_nodes_to_score(n_real, self.percentage_of_nodes_to_score)
        if want >= n_real:
            self._window_extra_nodes = set()
            return None
        # nodes nominated by the preemption pass (victims just evicted
        # there) must be visible to the retry's window
        named: set = self._window_extra_nodes
        self._window_extra_nodes = set()
        for p in pending:
            spec = p.spec
            if spec.node_selector:
                return None
            if spec.node_name:
                named.add(spec.node_name)
            elif spec.affinity_required_nodes:
                named.update(spec.affinity_required_nodes)
        start = self._score_start
        self._score_start = (start + want) % n_real
        window = (np.arange(want) + start) % n_real
        if named:
            in_window = set(window.tolist())
            extra = sorted(
                idx
                for idx in (self.snapshot.node_id(nm) for nm in named)
                if idx is not None and idx not in in_window
            )
            if extra:
                window = np.concatenate(
                    [window, np.asarray(extra, window.dtype)]
                )
        return window

    def node_state(self, sub: Optional[np.ndarray] = None) -> NodeState:
        """Device-side NodeState over the full node axis (``sub`` None) or
        a sampled window. The full-axis state is RESIDENT: it persists on
        device across cycles and only the snapshot rows touched since the
        last refresh are re-lowered and scattered in (a full re-lower
        happens only on bucket growth, reset, or an args-flag change);
        window states are gathered on device from the resident arrays."""
        full = self._resident_node_state()
        if sub is None:
            return full
        return self._window_node_state(full, sub)

    def _node_state_rows(self, rows: Optional[np.ndarray]) -> NodeState:
        """Host lowering of the derived NodeState blocks for ``rows``
        (None = the whole node axis). The amplified-CPU surcharge for
        exclusively-held cores (plugin.go:430-438) is charged by
        snapshot.assume_pod itself, so na.requested is already
        amplified-space for bound pods."""
        na = self.snapshot.nodes
        sl = slice(None) if rows is None else rows
        est_used = (
            np.maximum(na.usage_agg[sl], na.usage_avg[sl])
            + na.assigned_pending[sl]
        )
        schedulable = na.schedulable[sl]
        if (
            self.args.filter_expired_node_metrics
            and not self.args.enable_schedule_when_node_metrics_expired
        ):
            # strict expired-metric filtering (load_aware.go:143-149):
            # a node that HAS reported but went stale is unschedulable;
            # a never-reported node stays admitted (nil-NodeMetric path)
            schedulable = schedulable & (
                na.metric_fresh[sl] | ~na.has_metric[sl]
            )
        return NodeState(
            allocatable=jnp.asarray(na.allocatable[sl]),
            requested=jnp.asarray(na.requested[sl]),
            estimated_used=jnp.asarray(est_used),
            prod_used=jnp.asarray(
                na.prod_usage[sl] + na.assigned_pending_prod[sl]
            ),
            metric_fresh=jnp.asarray(na.metric_fresh[sl]),
            schedulable=jnp.asarray(schedulable),
            cpu_amp=jnp.asarray(na.cpu_amp[sl]),
            custom_thresholds=jnp.asarray(na.custom_thresholds[sl]),
            custom_prod_thresholds=jnp.asarray(na.custom_prod_thresholds[sl]),
        )

    def _scatter_refresh(
        self, cached_state, rows: np.ndarray, make_blocks, span_name: str,
        table: str,
    ):
        """Shared dirty-row scatter ladder for every device-resident
        table (nodes / NUMA zones / GPU slots): pad the index vector to a
        power of two (min 8) so the scatter jit-cache stays tiny
        (duplicate indices carry identical row data, so the ``.set`` is
        well-defined), scatter ``make_blocks(idx)`` into the DONATED
        resident pytree, and account the upload + partial cache hit.
        Mesh mode routes through ``scatter_rows_sharded``: the resident
        shards are refreshed in place across the (dp, tp) mesh with
        donation pinned through the resharding boundary (same census,
        same discipline)."""
        reg = self.extender.registry
        b = max(8, 1 << (len(rows) - 1).bit_length())
        idx = np.empty((b,), np.int32)
        idx[: len(rows)] = rows
        idx[len(rows) :] = rows[-1]
        dp = self.devprof
        with self.extender.tracer.span(
            span_name, cat="scheduler", dirty=len(rows), uploaded=b
        ):
            if self.mesh is not None:
                # the sharded wrapper owns its watch window (PR 8 rule)
                state = scatter_rows_sharded(
                    self.mesh,
                    cached_state,
                    jnp.asarray(idx),
                    make_blocks(idx),
                    devprof=dp,
                    table=table,
                    nrows=b,
                )
            else:
                with (
                    dp.watch(
                        "scatter_rows", stage="snapshot", kind="transfer",
                        table=table, rows=b,
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    state = scatter_rows(
                        cached_state, jnp.asarray(idx), make_blocks(idx)
                    )
                    w.result(state)
        if dp is not None:
            # donation-effectiveness: the donated resident pytree must be
            # DEAD after the scatter (a live leaf means XLA copied) — the
            # census reads only leaf deadness, never buffer contents
            dp.census.check_donation(cached_state)  # koordlint: disable=donation-safety
        reg.get("solver_h2d_rows_total").inc(float(b))
        reg.get("solver_state_cache_hits_total").labels(table=table).inc()
        return state

    def _resident_node_state(self) -> NodeState:
        snap = self.snapshot
        reg = self.extender.registry
        tr = self.extender.tracer
        with snap.lock:
            n_bucket = snap.nodes.allocatable.shape[0]
            # the mesh rides the key: attaching/detaching a mesh mid-run
            # (no snapshot-version bump) must full-relower so the
            # resident shards match the dispatch placement
            key = (
                n_bucket,
                self.args.filter_expired_node_metrics,
                self.args.enable_schedule_when_node_metrics_expired,
                self.mesh,
            )
            cur = self._resident_nodes
            if cur is not None and key == self._resident_key:
                if snap.version == self._resident_version:
                    reg.get("solver_state_cache_hits_total").labels(
                        table="nodes"
                    ).inc()
                    return cur
                rows = snap.drain_dirty(owner=id(self))
                if rows is not None and 0 < len(rows) <= n_bucket // 2:
                    new = self._scatter_refresh(
                        cur,
                        rows,
                        self._node_state_rows,
                        "snapshot:node_scatter",
                        "nodes",
                    )
                    self._resident_nodes = new
                    self._resident_version = snap.version
                    return new
                # too many dirty rows / structural change: fall through
            else:
                # bucket or flag change: stale marks are meaningless for
                # the rebuilt mirror
                snap.drain_dirty(owner=id(self))
            dp = self.devprof
            with tr.span(
                "snapshot:node_full_lower", cat="scheduler", uploaded=n_bucket
            ):
                with (
                    dp.watch(
                        "node_full_lower", stage="snapshot",
                        kind="transfer", n=n_bucket,
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    new = self._node_state_rows(None)
                    if self.mesh is not None:
                        from ..parallel.sharded import put_resident

                        new = put_resident(self.mesh, new)
                    w.result(new)
            reg.get("solver_h2d_rows_total").inc(float(n_bucket))
            self._resident_nodes = new
            self._resident_key = key
            self._resident_version = snap.version
            return new

    def _window_node_state(self, full: NodeState, sub: np.ndarray) -> NodeState:
        """Sampled-window NodeState, gathered ON DEVICE from the resident
        full-axis arrays and memoized on (window, snapshot version) — the
        scanned and pipelined dispatches both ask for it within a cycle,
        and an unmoved window across cycles re-uses the gather outright."""
        reg = self.extender.registry
        b = bucket_size(len(sub), self.snapshot.config.min_bucket)
        # _resident_key rides along: an args-flag change full-relowers the
        # resident state WITHOUT bumping snap.version, and the window must
        # not outlive it
        key = (self._resident_version, self._resident_key, b, sub.tobytes())
        cached = self._window_cache
        if cached is not None and cached[0] == key:
            reg.get("solver_state_cache_hits_total").labels(
                table="nodes_window"
            ).inc()
            return cached[1]
        idx = np.zeros((b,), np.int32)
        idx[: len(sub)] = sub
        valid = np.zeros((b,), bool)
        valid[: len(sub)] = True
        dp = self.devprof
        with self.extender.tracer.span(
            "snapshot:window_gather", cat="scheduler", window=len(sub)
        ):
            if self.mesh is not None:
                out = gather_rows_sharded(
                    self.mesh,
                    full,
                    jnp.asarray(idx),
                    jnp.asarray(valid),
                    devprof=dp,
                    window=b,
                )
            else:
                with (
                    dp.watch(
                        "gather_rows", stage="snapshot", kind="transfer",
                        window=b,
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    out = gather_rows(
                        full, jnp.asarray(idx), jnp.asarray(valid)
                    )
                    w.result(out)
        self._window_cache = (key, out)
        return out

    def _map_assignment(
        self, assignment: np.ndarray, sub: Optional[np.ndarray]
    ) -> np.ndarray:
        """Solver output indices → real snapshot node indices when the
        cycle solved over a sampled window."""
        if sub is None:
            return assignment
        lut = np.full(
            bucket_size(len(sub), self.snapshot.config.min_bucket),
            -1,
            np.int32,
        )
        lut[: len(sub)] = sub
        return np.where(assignment >= 0, lut[np.clip(assignment, 0, None)], -1)

    def pod_batch(self, pods: Sequence[Pod], bucket: Optional[int] = None) -> PodBatch:
        with self.extender.tracer.span(
            "lower", cat="scheduler", pods=len(pods)
        ):
            return self._pod_batch(pods, bucket)

    def _pod_batch(
        self, pods: Sequence[Pod], bucket: Optional[int] = None
    ) -> PodBatch:
        batch, _rows = self._lower_chunk(pods, bucket)
        return batch

    def _lower_chunk(
        self,
        pods: Sequence[Pod],
        bucket: Optional[int] = None,
        stash: bool = True,
        quarantine: Optional[Dict[str, tuple]] = None,
        inject: bool = True,
    ) -> Tuple[PodBatch, LoweredRows]:
        """Lower one chunk to a device :class:`PodBatch` plus its host
        :class:`LoweredRows`. ``stash=False`` keeps the instance stash
        untouched — the pipeline's prepare worker lowers the NEXT cycle
        on its own thread while the current cycle's commit still relies
        on ``self._lowered`` (``quarantine`` then collects NaN-guard
        verdicts for a later merge instead of writing the shared dict)."""
        arrays, est, rows = self._lower_rows(
            pods, bucket, stash=stash, quarantine=quarantine, inject=inject
        )
        batch = PodBatch.create(
            requests=arrays.requests,
            estimate=est,
            priority=arrays.priority,
            is_prod=rows.is_prod,
            valid=arrays.valid,
            gang_id=arrays.gang_id,
            gang_min=arrays.gang_min,
            quota_chain=rows.quota_chain,
            qos=arrays.qos,
            gpu_whole=arrays.gpu_whole,
            gpu_share=arrays.gpu_share,
            rdma=arrays.rdma,
            fpga=arrays.fpga,
            gang_nonstrict=arrays.gang_nonstrict,
            numa_required=arrays.numa_required,
        )
        return batch, rows

    def _lower_rows(
        self,
        pods: Sequence[Pod],
        bucket: Optional[int] = None,
        stash: bool = True,
        quarantine: Optional[Dict[str, tuple]] = None,
        inject: bool = True,
    ):
        """Host-side lowering shared by the device dispatches and the
        host reference path: builds the dense pod arrays + estimates,
        stashes :class:`LoweredRows` for ``_commit`` (unless
        ``stash=False``), and runs the NaN/Inf guard (non-finite
        request/estimate rows are quarantined as a counted RejectReason
        before they can poison a cost tensor). Returns
        ``(arrays, est, rows)``."""
        if quarantine is None:
            quarantine = self._numeric_quarantine
        arrays = self.snapshot.build_pods(
            list(pods),
            min_member_by_gang=self.pod_groups.min_member_map(),
            nonstrict_by_gang=self.pod_groups.nonstrict_map(),
            bucket=bucket,
            row_cache=self._pod_intern,
        )
        if arrays.intern_hits and self._pod_intern is not None:
            self.extender.registry.get("pod_intern_hits_total").inc(
                arrays.intern_hits
            )
        b = bucket or bucket_size(len(pods), self.snapshot.config.min_bucket)
        if arrays.requests.shape[0] != b:
            raise ValueError("pod bucket mismatch")
        # One estimate per pod, shared with Reserve/reservation commits.
        # The common case (requests only, no limits, no explicit estimate)
        # vectorizes: round(requests × scale) with the zero-request tier
        # floors; pods with overrides fall back to the per-pod estimator.
        from ..ops.estimator import (
            DEFAULT_MEMORY_REQUEST_MIB,
            DEFAULT_MILLI_CPU_REQUEST,
        )

        cfg = self.snapshot.config
        est = np.floor(arrays.requests * self._scales[None, :] + 0.5).astype(
            np.float32
        )
        floors_prod = cfg.res_vector(
            {
                ext.RES_CPU: DEFAULT_MILLI_CPU_REQUEST,
                ext.RES_MEMORY: DEFAULT_MEMORY_REQUEST_MIB,
            }
        )
        floors_batch = cfg.res_vector(
            {
                ext.RES_BATCH_CPU: DEFAULT_MILLI_CPU_REQUEST,
                ext.RES_BATCH_MEMORY: DEFAULT_MEMORY_REQUEST_MIB,
            }
        )
        is_batch_pod = arrays.prio_class == int(ext.PriorityClass.BATCH)
        floors = np.where(
            is_batch_pod[:, None], floors_batch[None, :], floors_prod[None, :]
        ) * arrays.valid[:, None]
        est = np.where(arrays.requests > 0, est, floors).astype(np.float32)
        # overrides detected in build_pods' single pass — only those rows
        # pay the per-pod estimator
        if arrays.est_override is not None and arrays.est_override.any():
            for i in np.nonzero(arrays.est_override)[0].tolist():
                est[i] = self._estimate_of(pods[i])
        is_prod = arrays.prio_class == int(ext.PriorityClass.PROD)
        # chaos: corrupt one estimate row (emulates a poisoned upstream
        # estimator / device readback); the guard below quarantines it
        # exactly like a genuinely corrupt spec would be. The pipeline's
        # warm-only prepare passes inject=False — a throwaway lowering
        # must not consume a scheduled fault hit
        if inject and self.chaos.enabled and len(pods) and self.chaos.fire(
            "solver.nan_rows"
        ):
            est[0, 0] = float("nan")
        # chaos: a poison batch — lowering RAISES whenever a marked pod
        # is present (emulates a spec that deterministically crashes the
        # solver path, e.g. an adversarial topology constraint). Unlike
        # nan_rows this is not a value corruption the numeric guard can
        # absorb: the error escapes every ladder level and only the
        # bisection containment (_contain_poison) can isolate WHICH pod
        # is to blame — the error deliberately carries no uid.
        if (
            inject
            and self.chaos.enabled
            and any(POISON_LABEL in (p.meta.labels or {}) for p in pods)
            and self.chaos.fire("solver.poison_batch")
        ):
            raise PoisonBatchError(
                "lowering crashed: batch of %d contains a poison spec"
                % len(pods)
            )
        # NaN/Inf guard: a single non-finite row would propagate through
        # the cost sums and corrupt EVERY pod's ranking in the chunk —
        # quarantine the offending rows (valid=False, zeroed) and
        # attribute them as NUMERIC_INVALID so they surface in
        # rejections_total instead of as garbage placements
        n_pods = len(pods)
        if n_pods:
            finite = np.isfinite(arrays.requests[:n_pods]).all(
                axis=1
            ) & np.isfinite(est[:n_pods]).all(axis=1)
            if not finite.all():
                bad = np.nonzero(~finite)[0]
                for i in bad.tolist():
                    quarantine[arrays.uids[i]] = (
                        RejectStage.FILTER,
                        "numeric_guard",
                        RejectReason.NUMERIC_INVALID,
                    )
                arrays.requests[bad] = 0.0
                est[bad] = 0.0
                arrays.valid[bad] = False
        chains = self.quotas.chains_for_names(arrays.quota_names, b)
        # non-preemptible pods: append the leaf's SHADOW quota index
        # (leaf + Q; runtime=min, used=nonPreemptibleUsed in the extended
        # solver table) so ordinary chain admission enforces the MIN
        # bound in-batch (plugin.go:252-262). chains_for_names reserves a
        # spare column beyond MAX_LEVELS, so a free slot always exists.
        nonpre = arrays.non_preemptible
        if (
            nonpre is not None
            and self.quotas.quota_count > 0
            and nonpre.any()
        ):
            q_count = self.quotas.quota_count
            for i in np.nonzero(nonpre)[0].tolist():
                row = chains[i]
                if row[0] < 0:
                    continue
                row[np.nonzero(row < 0)[0][0]] = row[0] + q_count
        # stash the host-side rows for _commit: Reserve revalidation and
        # assume charges reuse these instead of recomputing res_vector /
        # estimate_pod per winner (the recompute was a measurable slice of
        # the per-batch host time); the uid tuple guards the temporal
        # coupling — _commit refuses rows lowered for a different chunk
        rows = LoweredRows(
            uids=tuple(arrays.uids),
            req=arrays.requests,
            est=est,
            # vectorized wants_cpu_bind over the chunk (per-winner
            # ext.wants_cpu_bind was a visible slice of the commit loop)
            bind=ext.wants_cpu_bind_rows(
                arrays.qos, arrays.requests[:, self.snapshot._cpu_dim]
            ),
            prio=arrays.priority,
            is_prod=is_prod,
            gpu_whole=arrays.gpu_whole,
            gpu_share=arrays.gpu_share,
            rdma=arrays.rdma,
            fpga=arrays.fpga,
            has_gangs=bool((arrays.gang_id >= 0).any()),
            quota_chain=chains,
            numa_required=arrays.numa_required,
        )
        if stash:
            self._lowered = rows
        return arrays, est, rows

    # ---- scheduling cycle ----

    def schedule(
        self, pending: Sequence[Pod], _retry: bool = False
    ) -> ScheduleOutcome:
        # one scheduling cycle is atomic w.r.t. informer writers (the
        # reference cache lock at batch granularity); re-entrant for the
        # preemption retry
        pause_gc = self.defer_gc and not _retry
        if pause_gc:
            _gc_pause()
        try:
            with self.snapshot.lock:
                out = self._traced_cycle(pending, _retry)
                if not _retry:
                    # run-loop journal maintenance (PR 6 satellite):
                    # threshold-gated compaction under the same lock the
                    # commits hold, so a checkpoint never races a chunk
                    self._maybe_compact_journal()
                return out
        finally:
            if pause_gc:
                _gc_resume()

    def _traced_cycle(
        self, pending: Sequence[Pod], _retry: bool
    ) -> ScheduleOutcome:
        """Cycle-level observability shell around the real cycle: a
        ``cycle`` span + latency histogram, and a :class:`StageSequence`
        whose snapshot/solve/commit/postfilter stages tile the cycle's
        wall time (the preemption retry nests inside its parent's
        postfilter stage and reuses the parent cycle id)."""
        from ..obs.trace import StageSequence

        fwext = self.extender
        cid = fwext.current_cycle_id if _retry else fwext.begin_cycle()
        seq = StageSequence(
            fwext.tracer,
            fwext.registry.get("stage_latency_seconds"),
            cat="scheduler",
            cycle=cid,
        )
        if _retry:
            try:
                return self._schedule_locked(pending, seq, _retry)
            finally:
                seq.close()
        cycle_timer = fwext.tracer.stage(
            "cycle",
            fwext.registry.get("cycle_latency_seconds"),
            cat="scheduler",
            cycle=cid,
            pods=len(pending),
        )
        dp = self.devprof
        if dp is not None:
            dp.cycle_begin(cid)
        with cycle_timer:
            try:
                out = self._schedule_locked(pending, seq, _retry)
            finally:
                seq.close()
                if dp is not None:
                    dp.cycle_end(self)
        if self.flight_recorder is not None:
            self._record_cycle(cid, seq.totals, cycle_timer.last_dur, out)
        return out

    def _record_cycle(
        self, cid: int, stage_totals: Dict[str, float],
        cycle_s: float, out: "ScheduleOutcome",
    ) -> None:
        """One flight-recorder record per completed cycle: the black-box
        summary (per-cycle stage_ms, latest pipeline gate verdicts,
        speculation outcome, fencing, queue depth) a post-mortem needs
        when the process does not survive to be asked."""
        gates = self.last_gate_report
        extra: Dict[str, object] = {}
        dd = self._depth_decision
        # consume-once: the pipeline stamps a decision per trailing
        # commit; a later SERIAL cycle (ghost scheduling, direct
        # schedule() calls) must not record a stale pipelined choice
        self._depth_decision = None
        if dd is not None:
            # adaptive-depth PR: the chosen depth + its discard-rate
            # input per cycle — depth decisions must be explainable
            # post-hoc, and a takeover adopting this recorder's tail
            # inherits the dead writer's churn evidence with it
            extra["depth"] = dd[0]
            extra["depth_max"] = dd[1]
            extra["discard_rate"] = dd[2]
        self.flight_recorder.record(
            cid,
            stage_ms={
                k: v * 1e3
                for k, v in dict(
                    stage_totals, cycle=cycle_s
                ).items()
            },
            gates=dict(gates.get("gates", {})),
            speculation=self._cycle_spec_outcome or "serial",
            fenced=self._cycle_fenced,
            queue_depth=self._queue_depth_hint,
            bound=len(out.bound),
            unschedulable=len(out.unschedulable),
            epoch=self._fence_epoch,
            rolled_back=self._cycle_commit_rolled_back,
            deadline_hit=self._cycle_deadline_hit,
            **extra,
        )

    def _schedule_locked(
        self, pending: Sequence[Pod], seq, _retry: bool = False
    ) -> ScheduleOutcome:
        import time as _time

        fwext = self.extender
        tr = fwext.tracer
        rej = fwext.rejections
        cid = fwext.current_cycle_id
        seq.enter("snapshot")
        if not _retry:
            # stale buffer from a cycle that raised mid-flight must not
            # leak records into this cycle
            self._cycle_rejects = []
            self._numeric_quarantine = {}
            self._cycle_solver_failed = False
            self._cycle_deadline_hit = False
            self._cycle_commit_rolled_back = False
            self._cycle_journal_failed = False
            self._cycle_fetch_deferred = False
            self._cycle_used_spec = False
            self._cycle_reserve_rejected = False
            self._cycle_preempted = False
            self._cycle_fenced = False
            self._cycle_spec_outcome = ""
            self._cycle_resv_binds = []
            self._cycle_resv_affinity = ()
            self._cycle_resv_pre_table = None
            self._cycle_poisoned = []
            # staleness snapshotted ONCE per cycle (snapshot-once →
            # decide): every gate below reads the same verdict, and the
            # decision replay sees one input, not a race
            self._cycle_stale = (
                bool(self.staleness())
                if self.staleness is not None
                else False
            )
            self._pre_cycle_version = self.snapshot.version
            self._cycle_t0 = _time.perf_counter()
            fwext.monitor.start_batch(pending)
            # amortized purge: pods forgotten through any path (delete
            # sync, resync, eviction) must not accumulate here forever
            if len(self._bound_nodes) > 64 + 2 * len(self.snapshot._assumed):
                self._bound_nodes = {
                    uid: node
                    for uid, node in self._bound_nodes.items()
                    if uid in self.snapshot._assumed
                }
                self._bound_pods = {
                    uid: p
                    for uid, p in self._bound_pods.items()
                    if uid in self._bound_nodes
                }
        # BeforePreFilter analog: pod transformers may rewrite or drop.
        # (Dropped pods are error-handled inside the transformer run.)
        pending, dropped = fwext.run_pre_batch_transformers(pending)
        dropped_uids = {p.meta.uid for p in dropped}
        for pod in dropped:
            rej.record(
                cid,
                pod,
                RejectStage.TRANSFORM,
                "frameworkext",
                RejectReason.POD_TRANSFORMER_DROPPED,
            )
        # gray-failure containment: pods blamed on the quarantine ledger
        # are rejected AT THE GATE — a poison spec must not reach a solve
        # and re-crash the cycle it already crashed once. The check runs
        # post-transform so the fingerprint covers the spec that would
        # actually be lowered; a CHANGED fingerprint redeems the blame
        # inside ``blamed()`` and the pod proceeds normally.
        quarantined_gated: List[Pod] = []
        if self.quarantine is not None and self.quarantine.active():
            kept: List[Pod] = []
            for pod in pending:
                if self.quarantine.blamed(
                    pod.meta.uid, spec_fingerprint(pod)
                ):
                    quarantined_gated.append(pod)
                    rej.record(
                        cid,
                        pod,
                        RejectStage.GATE,
                        "poison_quarantine",
                        RejectReason.POISON_QUARANTINED,
                    )
                else:
                    kept.append(pod)
            if quarantined_gated:
                fwext.registry.get("poison_quarantined_total").inc(
                    len(quarantined_gated)
                )
                pending = kept
        # PreEnqueue gate + gang-adjacent ordering (coscheduling NextPod):
        # whole gangs land in one solver batch.
        # Reservation pre-match: pods owned by an Available reservation
        # commit directly against its hold (the reference transformer
        # restores reserved resources before Filter; the ghost hold makes
        # the direct commit capacity-safe). Pods needing the full pipeline
        # fall through to the solver: gang members (Permit), and matched
        # pods whose NUMA/device/quota Reserve fails.
        reserved_bound: List[Tuple[Pod, str]] = []
        # open the last gates PR: a pending speculation PREDICTED this
        # cycle's fast-path outcome at dispatch. Capture what the
        # consume guard compares by value — the snapshot version before
        # the fast path's own sanctioned writes, and the reservation
        # table before begin_cycle can touch it. The whole cycle runs
        # under snapshot.lock, so every write between here and the
        # consume guard IS the fast path's.
        if not _retry:
            self._cycle_prefast_version = self.snapshot.version
            if (
                self._speculative is not None
                and self.reservations is not None
            ):
                self._cycle_resv_pre_table = self.reservations.table_view()
        # HA fencing: the reservation fast path is a commit too (it
        # assumes pods directly, bypassing _commit) — a deposed leader
        # must not take it. The check here is fence-only (no chaos
        # evaluation: ``leader.stale_commit`` belongs to the _commit
        # boundary); fenced pods fall through to the solver path, whose
        # _commit rejects them with STALE_LEADER_EPOCH.
        fast_path_fenced = False
        if self.reservations is not None and self.fence is not None:
            try:
                self.fence.check(self._fence_epoch)
            except StaleEpochError:
                fast_path_fenced = True
        if self.reservations is not None and not fast_path_fenced:
            from .plugins.coscheduling import gang_key_of
            from .plugins.elasticquota import (
                is_pod_non_preemptible as is_nonpre,
                quota_name_of,
            )

            # refresh the Available candidate cache once per cycle (the
            # per-pod match scan must not re-validate every reservation)
            self.reservations.begin_cycle()
            remaining_pending = []
            affinity_unsched: List[Pod] = []
            # HA (PR 6 satellite — the fast path's journal exception is
            # CLOSED): ONE batched write-ahead intent for the whole fast
            # path, from a read-only match pre-pass BEFORE any mutation
            # (per-pod intent+bind pairs cost 2K fsyncs per cycle where
            # _commit pays two per chunk). The planned list may overshoot:
            # an earlier pod's allocation can steal a later pod's match,
            # and the eventual bind node may differ from the nominated
            # one — safe, because replay builds the live set from bind
            # records alone; intents only mark crash-mid-commit windows.
            fast_path_refused = False
            # the pre-pass result doubles as a match CACHE for the bind
            # loop (the per-pod match scan is the cost begin_cycle exists
            # to amortize — running it twice per pod would give that
            # back). Reuse is decision-identical ONLY until the first
            # successful bind of the cycle: a bind swaps the ghost's hold
            # for the owner's (possibly smaller) charge, so node free
            # capacity can INCREASE, flipping a rival reservation's
            # spill feasibility — after that, matches must be fresh.
            # Failed attempts restore state exactly and invalidate
            # nothing. Steady-state cycles with no fast-path bind keep
            # the single scan they had before the batched intent.
            prematch: Dict[str, object] = {}
            prematch_valid = True
            if self.bind_journal is not None:
                planned_fast = []
                for pod in pending:
                    if gang_key_of(pod) is not None:
                        continue
                    r0 = self.reservations.match(pod)
                    prematch[pod.meta.uid] = r0
                    if r0 is not None and r0.node_name is not None:
                        planned_fast.append((pod.meta.uid, r0.node_name))
                if planned_fast:
                    try:
                        self.bind_journal.append_intent(
                            self._fence_epoch,
                            self.extender.current_cycle_id,
                            planned_fast,
                        )
                    except (JournalWriteError, StaleEpochError) as exc:
                        report_exception(
                            "scheduler.journal.reservation",
                            exc,
                            registry=self.extender.registry,
                        )
                        self._cycle_journal_failed = True
                        self.extender.health.set(
                            "commit",
                            False,
                            f"reservation intent journal refused: {exc!r}",
                        )
                        fast_path_refused = True
            if fast_path_refused:
                # same outcome as every matched pod's own append having
                # been refused: nothing mutates, required-affinity pods
                # stay unschedulable, the rest take the solver path
                # (whose journal boundary holds while the store is down)
                for pod in pending:
                    required = (
                        ext.parse_reservation_affinity(pod.meta.annotations)
                        is not None
                    )
                    (
                        affinity_unsched if required else remaining_pending
                    ).append(pod)
                pending = []
            for pod in pending:
                if gang_key_of(pod) is not None:
                    r = None
                else:
                    r = (
                        prematch.get(pod.meta.uid, _PREMATCH_MISS)
                        if prematch_valid
                        else _PREMATCH_MISS
                    )
                    if r is _PREMATCH_MISS:
                        r = self.reservations.match(pod)
                # required reservation affinity: the pod may ONLY run
                # from a matching reservation — no fallthrough to normal
                # node scheduling, even when the match's Reserve fails
                # (reference ReservationAffinity RequiredDuringScheduling
                # semantics); it stays unschedulable and retries next cycle
                required = (
                    ext.parse_reservation_affinity(pod.meta.annotations)
                    is not None
                )
                retry_queue = affinity_unsched if required else remaining_pending
                if r is None:
                    retry_queue.append(pod)
                    continue
                node = r.node_name
                leaf = quota_name_of(pod)
                if leaf is not None and not self.quotas.has_headroom(
                    leaf,
                    pod.spec.requests,
                    non_preemptible=is_nonpre(pod),
                ):
                    retry_queue.append(pod)
                    continue
                # Aligned-policy spill and undeclared dims allocate from
                # NODE free capacity (reservation_types.go:86-97) — the
                # spill re-checks headroom at commit (node state may have
                # moved since the per-cycle match), via the same helper
                # the match filter and the allocation charge use
                _consumed, spill = self.reservations.consumed_and_spill(
                    r, pod
                )
                if not self.reservations.spill_fits_node(r, spill):
                    retry_queue.append(pod)
                    continue
                patch: Dict[str, str] = {}
                # free the ghost's reserved cpuset/minors first so the
                # owner can take exactly what was held for it
                self.reservations.release_ghost_holds(r)
                if self.numa is not None:
                    numa_patch = self.numa.allocate(pod, node)
                    if numa_patch is None:
                        # failed owner Reserve: the still-Available
                        # reservation must get its cpuset/minor holds back
                        self.reservations.reacquire_ghost_holds(r)
                        retry_queue.append(pod)
                        continue
                    patch.update(numa_patch)
                if self.devices is not None:
                    dev_patch = self.devices.allocate(pod, node)
                    if dev_patch is None:
                        if self.numa is not None:
                            self.numa.release(pod.meta.uid, node)
                        self.reservations.reacquire_ghost_holds(r)
                        retry_queue.append(pod)
                        continue
                    patch.update(dev_patch)
                if not self.snapshot.assume_pod(
                    pod, node, self._estimate_of(pod), confirmed=False
                ):
                    # reservation's node deleted this cycle: release the
                    # per-winner allocations and retry via the full pipeline
                    if self.devices is not None:
                        self.devices.release(pod.meta.uid, node)
                    if self.numa is not None:
                        self.numa.release(pod.meta.uid, node)
                    self.reservations.reacquire_ghost_holds(r)
                    retry_queue.append(pod)
                    continue
                # the bind record IS the acknowledgement (same contract
                # as _commit): it lands BEFORE the reservation ledger /
                # quota charge, while the unwind is still trivial — a
                # refused write releases the assume + holds, re-arms the
                # ghost, and the pod falls through to the solver path.
                # A crash after this record replays the bind; the ghost
                # swap + owner ledger rebuild from the reservation
                # resync (ingest_operating_pod / informers).
                if self.bind_journal is not None:
                    try:
                        self.bind_journal.append_bind(
                            self._fence_epoch,
                            self.extender.current_cycle_id,
                            self._journal_bind_entries([(pod, node)]),
                        )
                    except (JournalWriteError, StaleEpochError) as exc:
                        report_exception(
                            "scheduler.journal.reservation",
                            exc,
                            registry=self.extender.registry,
                        )
                        self._cycle_journal_failed = True
                        self.extender.health.set(
                            "commit",
                            False,
                            f"reservation bind journal refused: {exc!r}",
                        )
                        self.snapshot.forget_pod(pod.meta.uid)
                        if self.devices is not None:
                            self.devices.release(pod.meta.uid, node)
                        if self.numa is not None:
                            self.numa.release(pod.meta.uid, node)
                        self.reservations.reacquire_ghost_holds(r)
                        retry_queue.append(pod)
                        continue
                self.reservations.allocate(r, pod)
                if leaf is not None:
                    self.quotas.assign_pod(leaf, pod)
                self._bound_nodes[pod.meta.uid] = node
                self._bound_pods[pod.meta.uid] = pod
                pod.meta.annotations.update(patch)
                reserved_bound.append((pod, node))
                self._cycle_resv_binds.append(
                    (pod.meta.uid, r.meta.name, node)
                )
                prematch_valid = False
            pending = remaining_pending
        else:
            affinity_unsched = []
        if not _retry:
            self._cycle_resv_affinity = tuple(
                p.meta.uid for p in affinity_unsched
            )

        eligible = self.pod_groups.begin_and_order(pending)
        eligible_uids = {p.meta.uid for p in eligible}
        gated = [p for p in pending if p.meta.uid not in eligible_uids]
        for pod in gated:
            rej.record(
                cid,
                pod,
                RejectStage.GATE,
                "coscheduling",
                RejectReason.GANG_NOT_READY,
            )
        for pod in affinity_unsched:
            rej.record(
                cid,
                pod,
                RejectStage.PREFILTER,
                "reservation",
                RejectReason.RESERVATION_UNAVAILABLE,
            )

        bound: List[Tuple[Pod, str]] = list(reserved_bound)
        unsched: List[Pod] = (
            list(gated)
            + list(dropped)
            + list(affinity_unsched)
            + list(quarantined_gated)
        )
        rounds = 0
        chunks = self._chunks(eligible)
        # cross-cycle pipelining (perf PR 4): a CyclePipeline may have
        # dispatched this cycle's solves already, chained off the previous
        # cycle's on-device commit state while that cycle's host Reserve
        # trailed behind. Consume them only when the guards prove the
        # speculative inputs equal what a fresh dispatch would see —
        # identical chunking, no snapshot writes since dispatch, no node
        # churn, ladder healthy — else fall back to a fresh dispatch
        # (decision-identical either way; the discard is only lost work).
        solves = None
        sub = None
        spec = self._speculative
        self._speculative = None
        if spec is not None and not _retry:
            # chaos (pipeline.carry_mismatch): evaluated the moment a
            # speculation reaches the consume guard. Deliberate
            # trade-off: firing here guarantees the soak's fixed-cycle
            # arm lands on the NEXT spec-present consume (placing it
            # inside _carry_consume_ok starved it — most soak consumes
            # discard on the version guard and the arm never fired);
            # the cost is that a cheap-guard discard can subsume the
            # corruption (same observable effect — a discard — without
            # walking the comparison). The comparison path itself is
            # pinned deterministically by the dedicated tier-1 arm
            # (test_carry_mismatch_chaos_forces_redispatch).
            carry_corrupt = self.chaos.enabled and self.chaos.fire(
                "pipeline.carry_mismatch"
            )
            if (
                chunks
                and spec.chunk_uids
                == tuple(tuple(p.meta.uid for p in c) for c in chunks)
                # compared against the PRE-fast-path version: the fast
                # path's own writes are sanctioned (predicted, validated
                # by value below); any OTHER write since dispatch is not
                and spec.version == self._cycle_prefast_version
                and spec.node_epoch == self.snapshot.node_epoch
                and self._fallback_level == 0
                and self._speculation_consume_ok()
                # LAST: the carry validation is the expensive check (it
                # runs the real quota demand propagation and fetches the
                # carried tables) — cheap guards short-circuit it
                and self._carry_consume_ok(
                    spec, chunks, corrupt=carry_corrupt
                )
            ):
                solves = spec.solves
                sub = spec.sub
                self._cycle_used_spec = True
                self._cycle_spec_outcome = "kept"
                self._numeric_quarantine.update(spec.quarantine)
                fwext.registry.get("pipeline_speculation_total").labels(
                    outcome="kept"
                ).inc()
            else:
                self._cycle_spec_outcome = "discarded"
                fwext.registry.get("pipeline_speculation_total").labels(
                    outcome="discarded"
                ).inc()
        if solves is None:
            # kube-scheduler node sampling (PercentageOfNodesToScore): one
            # rotating window per cycle, shared by every chunk so the
            # on-device capacity chaining stays on a consistent node axis
            sub = self._select_nodes(eligible) if chunks else None
        seq.enter("solve")
        seq.set(chunks=len(chunks))
        if solves is None:
            # fallback ladder: scanned multi-chunk → per-chunk → host numpy
            # reference; a dispatch failure demotes the ladder for
            # subsequent cycles instead of killing this one
            solves = self._dispatch_with_fallback(chunks, sub)
        # consume pods the poison bisection isolated during THIS dispatch:
        # they were excluded from the re-dispatched healthy chunks and are
        # unschedulable this cycle (the cycle gate rejects them from the
        # next one; their _cycle_rejects records flush at the tail)
        if self._cycle_poisoned:
            unsched.extend(self._cycle_poisoned)
            self._cycle_poisoned = []
        fence_failed = False
        if tr.enabled and solves and not isinstance(solves[0][2], _HostSolve):
            # fence the async dispatches so the solve span's duration is
            # real device time, not enqueue time (the commit stage then
            # measures pure transfer + host Reserve). The fence is where
            # an async device failure surfaces when tracing is on, so it
            # gets the same ladder treatment as a fetch-time failure —
            # escaping here would kill the cycle un-demoted.
            try:
                jax.block_until_ready(
                    [r.assignment for _c, _r, r in solves]
                )
            except Exception as exc:  # noqa: BLE001 — ladder absorbs
                self._note_solver_failure(
                    min(self._fallback_level, 1), exc
                )
                fence_failed = True
        use_zone_hints = self.numa is not None and self.numa.has_topology

        def _pack(result):
            # assignment + device zone picks ride ONE fetch (a second
            # per-chunk device→host read costs a full tunnel round trip)
            if use_zone_hints and result.pod_zone is not None:
                return jnp.stack([result.assignment, result.pod_zone])
            return result.assignment

        def _host_arrays():
            """Per-chunk host copies of the packed results. The scanned
            dispatch already fetched everything in one transfer; the
            per-chunk paths group chunks in PAIRS per transfer and
            prefetch the next group on a worker thread while this thread
            commits — on tunneled backends every device→host call costs
            a fixed round trip and async copies are inert, so an unpiped
            fetch→commit→fetch chain serializes the drain on the wire."""
            if solves and isinstance(solves[0][2], _HostSolve):
                for _c, _r, r in solves:
                    if use_zone_hints and r.pod_zone is not None:
                        yield np.stack([r.assignment, r.pod_zone])
                    else:
                        yield r.assignment
                return
            if len(solves) == 1:
                yield np.asarray(_pack(solves[0][2]))
                return
            # group CONSECUTIVE equal-shaped results in pairs (the last
            # chunk's bucket may be smaller — stacking across shapes
            # would crash); singles transfer alone
            packed = [_pack(r) for _c, _r, r in solves]
            groups: List[Tuple[int, int]] = []  # (start, count)
            i = 0
            while i < len(packed):
                if (
                    i + 1 < len(packed)
                    and packed[i].shape == packed[i + 1].shape
                ):
                    groups.append((i, 2))
                    i += 2
                else:
                    groups.append((i, 1))
                    i += 1
            packed_groups = [
                jnp.stack(packed[s : s + c]) if c > 1 else packed[s]
                for s, c in groups
            ]
            fq: "_queue.Queue" = _queue.Queue(maxsize=2)
            cancelled = _threading.Event()

            def worker():
                for pg in packed_groups:
                    if self.chaos.enabled and self.chaos.fire(
                        "solver.fetch.stall"
                    ):
                        # simulated wedged device→host transfer: nothing
                        # ever arrives; the consumer's fetch deadline
                        # surfaces the stall as SOLVE_RESULT_STALLED
                        return
                    try:
                        item = np.asarray(pg)
                    except Exception as exc:  # noqa: BLE001 — re-raised below
                        report_exception(
                            "scheduler.solve.prefetch",
                            exc,
                            registry=self.extender.registry,
                        )
                        item = exc
                    while not cancelled.is_set():
                        try:
                            fq.put(item, timeout=0.25)
                            break
                        except _queue.Full:
                            continue
                    if isinstance(item, Exception) or cancelled.is_set():
                        return

            _threading.Thread(
                target=worker, name="solve-prefetch", daemon=True
            ).start()
            try:
                for s, c in groups:
                    # bounded fetch: a dead/wedged worker must not block
                    # the drain forever (feeder-queue satellite) — the
                    # remaining chunks re-enter the next cycle instead
                    deadline = _time.monotonic() + self.fetch_timeout_s
                    while True:
                        try:
                            got = fq.get(timeout=0.25)
                            break
                        except _queue.Empty:
                            if _time.monotonic() >= deadline:
                                raise _FetchStalled(
                                    f"solver result fetch stalled > "
                                    f"{self.fetch_timeout_s}s"
                                ) from None
                    if isinstance(got, Exception):
                        raise got
                    if c == 1:
                        yield got
                    else:
                        for j in range(c):
                            yield got[j]
            finally:
                # a consumer abandoning the generator (commit raised)
                # must release the worker, not strand it on a full queue
                cancelled.set()

        seq.enter("commit")
        # hardened commit loop: a stalled result fetch, an async device
        # failure surfacing at transfer time, or a blown per-cycle
        # deadline defers the REMAINING chunks to the next cycle (each
        # pod gets a counted RejectReason) instead of wedging or killing
        # the cycle; already-committed chunks stand.
        deferred_from = len(solves)
        deferred_reason = None
        if fence_failed:
            deferred_from = 0
            deferred_reason = RejectReason.SOLVE_RESULT_STALLED
        host_iter = _host_arrays()
        try:
            for k, (chunk, rows, result) in enumerate(
                [] if fence_failed else solves
            ):
                if (
                    self.cycle_deadline_s is not None
                    and k > 0
                    and _time.perf_counter() - self._cycle_t0
                    > self.cycle_deadline_s
                ):
                    deferred_from = k
                    deferred_reason = RejectReason.CYCLE_DEADLINE_EXCEEDED
                    self._cycle_deadline_hit = True
                    fwext.registry.get("cycle_deadline_exceeded_total").inc()
                    break
                t0 = _time.perf_counter()
                try:
                    host_arr = next(host_iter)
                except _FetchStalled as exc:
                    report_exception(
                        "scheduler.fetch_stall",
                        exc,
                        registry=fwext.registry,
                    )
                    deferred_from = k
                    deferred_reason = RejectReason.SOLVE_RESULT_STALLED
                    break
                except StopIteration:
                    raise RuntimeError(
                        "solver host-transfer iterator exhausted early"
                    ) from None
                except Exception as exc:  # async device failure at fetch
                    self._note_solver_failure(
                        min(self._fallback_level, 1), exc
                    )
                    deferred_from = k
                    deferred_reason = RejectReason.SOLVE_RESULT_STALLED
                    break
                if use_zone_hints and result.pod_zone is not None:
                    assignment, pod_zone = host_arr[0], host_arr[1]
                else:
                    assignment, pod_zone = host_arr, None
                assignment = self._map_assignment(assignment, sub)
                if fwext.scores.top_n > 0:
                    with tr.span(
                        "plugin:loadaware:score", cat="scheduler", cycle=cid
                    ):
                        self._debug_capture(chunk, assignment)
                b, u = self._commit(chunk, assignment, rows, pod_zone=pod_zone)
                fwext.registry.get("solver_batch_latency_seconds").observe(
                    _time.perf_counter() - t0
                )
                self._record_chunk_rejections(chunk, rows, assignment, u)
                bound.extend(b)
                unsched.extend(u)
        finally:
            host_iter.close()   # releases the prefetch worker
        if deferred_reason is RejectReason.SOLVE_RESULT_STALLED:
            self._cycle_fetch_deferred = True
        for chunk, _rows, _result in solves[deferred_from:]:
            for pod in chunk:
                unsched.append(pod)
                self._cycle_rejects.append(
                    (pod, RejectStage.SOLVE, "scheduler", deferred_reason)
                )
        # rounds_used / shortlist_fallbacks are diagnostics only — fetched
        # AFTER the commit loop and in ONE stacked transfer (per-chunk
        # int() fetches each cost a tunnel round trip); the scanned path
        # already holds host ints. Skipped entirely when chunks were
        # deferred: a stalled/failed fetch means the device may be wedged,
        # and blocking here on another unbounded transfer would defeat the
        # fetch deadline.
        fb_total = np.zeros((2,), np.int64)  # (bound, infeasible) rounds
        if solves and isinstance(solves[0][2], _HostSolve):
            for _chunk, _rows, result in solves:
                rounds += result.rounds_used
                if result.shortlist_fallbacks is not None:
                    fb_total += np.asarray(
                        result.shortlist_fallbacks, dtype=np.int64
                    )
        elif deferred_reason is not None:
            pass
        elif len(solves) == 1:
            res = solves[0][2]
            if res.shortlist_fallbacks is not None:
                packed = np.asarray(
                    jnp.concatenate(
                        [
                            res.rounds_used.astype(jnp.int32)[None],
                            res.shortlist_fallbacks,
                        ]
                    )
                )
                rounds += int(packed[0])
                fb_total += packed[1:].astype(np.int64)
            else:
                rounds += int(res.rounds_used)
        elif solves:
            # pack (rounds_used, fb[0], fb[1]) per chunk so the stacked
            # diagnostics still ride a single transfer
            packed = np.asarray(
                jnp.stack(
                    [
                        jnp.concatenate(
                            [
                                r.rounds_used.astype(jnp.int32)[None],
                                (
                                    r.shortlist_fallbacks
                                    if r.shortlist_fallbacks is not None
                                    else jnp.zeros((2,), jnp.int32)
                                ),
                            ]
                        )
                        for _c, _r, r in solves
                    ]
                )
            ).sum(axis=0)
            rounds += int(packed[0])
            fb_total += packed[1:].astype(np.int64)
        if fb_total[0] or fb_total[1]:
            ctr = fwext.registry.get("solver_shortlist_fallback_total")
            if fb_total[0]:
                ctr.labels(cause="bound").inc(int(fb_total[0]))
            if fb_total[1]:
                ctr.labels(cause="infeasible").inc(int(fb_total[1]))
        # PostFilter analog (reference elasticquota/preempt.go): a failed
        # quota-labeled pod may evict lower-priority same-quota pods, then
        # the batch retries once for the preemptors.
        seq.enter("postfilter")
        preempted: List[Pod] = []
        retry_pods: List[Pod] = []
        #: pods that already nominated victims in defer mode this cycle:
        #: the priority-preemption pass must skip them, or one pod could
        #: nominate two disjoint victim sets (quota + priority) in a
        #: single cycle and over-evict through the migration controller
        nominated_uids: set = set()
        #: an infrastructure deferral (deadline, stalled fetch, commit
        #: rollback) means these pods were never proven infeasible —
        #: evicting victims on their behalf would be wrong, and the
        #: in-cycle retry would re-dispatch against a possibly-wedged
        #: device
        infra_deferral = (
            self._cycle_deadline_hit
            or self._cycle_fetch_deferred
            or self._cycle_commit_rolled_back
        )
        # gray-failure containment: preemption is evidence-hungry — it
        # evicts REAL victims based on what the informers claim the
        # cluster looks like. A stale snapshot (silent watch stall) means
        # the evidence may be minutes old; refuse eviction and let plain
        # placement continue until events resume.
        if self._cycle_stale and not _retry and unsched:
            if (
                self.quotas.enable_preemption and self.quotas.quota_count > 0
            ) or self.enable_priority_preemption:
                fwext.registry.get("stale_evidence_refusals_total").labels(
                    action="preemption"
                ).inc()
        if (
            not _retry
            and unsched
            and not infra_deferral
            and not self._cycle_stale
            and self.quotas.enable_preemption
            and self.quotas.quota_count > 0
        ):
            from .plugins.coscheduling import gang_key_of as _gang_of
            from .plugins.elasticquota import ElasticQuotaPreemptor

            preemptor = ElasticQuotaPreemptor(self, self.quotas)
            for pod in sorted(
                unsched, key=lambda p: -(p.spec.priority or 0)
            ):
                if pod.meta.uid in dropped_uids or _gang_of(pod) is not None:
                    continue
                # required reservation affinity: the pod may only run from
                # a matching reservation — evicting quota victims cannot
                # help it, so never preempt on its behalf
                if ext.parse_reservation_affinity(pod.meta.annotations):
                    continue
                # preemption-policy=Never (preemption.go:22-41)
                if ext.pod_never_preempts(pod):
                    continue
                # sampled node window + clear quota headroom: the failure
                # is (possibly transient) node fit, not quota — upstream
                # preemption only runs after a FULL feasibility scan, so
                # evicting before the rotating window has been retried
                # would be premature (and the scan was the latency
                # stream's dominant PostFilter cost). The skip must not
                # become starvation: hard-constrained pods (whose nodes
                # are unioned into EVERY window) get preemption at once,
                # and an unconstrained pod is only skipped until the
                # window has fully rotated past it.
                if sub is not None and self.quotas.headroom_clears(pod):
                    spec = pod.spec
                    if not (
                        spec.node_name
                        or spec.node_selector
                        or spec.affinity_required_nodes
                    ):
                        uid = pod.meta.uid
                        rotation = max(
                            1,
                            -(-self.snapshot.node_count // max(len(sub), 1)),
                        )
                        seen_skips = self._preempt_skips.get(uid, 0) + 1
                        if seen_skips < rotation:
                            if len(self._preempt_skips) > 100_000:
                                self._trim_preempt_skips()
                            self._preempt_skips[uid] = seen_skips
                            continue
                        self._preempt_skips.pop(uid, None)
                sel = preemptor.select_victims(pod)
                if sel is None:
                    continue
                _node, victims = sel
                if self.defer_preemption:
                    # nominate only: the external migration controller
                    # performs the (arbitrated, rate-limited) eviction and
                    # the preemptor retries next cycle. Selections are not
                    # applied between preemptors here, so overlapping
                    # victim sets are deduped and re-resolved next cycle.
                    seen = {v.meta.uid for v in preempted}
                    preempted.extend(
                        v for v in victims if v.meta.uid not in seen
                    )
                    nominated_uids.add(pod.meta.uid)
                    continue
                for victim in victims:
                    self.evict_for_preemption(victim)
                    preempted.append(victim)
                retry_pods.append(pod)
                self._window_extra_nodes.add(_node)
        # Priority preemption at PostFilter (the reservation plugin's
        # preemption manager, reference reservation/preemption.go:105-250)
        # for pods quota preemption could not help; gated by
        # ReservationArgs.EnablePreemption (default false).
        if (
            not _retry
            and unsched
            and not infra_deferral
            and not self._cycle_stale
            and self.enable_priority_preemption
        ):
            from .plugins.coscheduling import gang_key_of as _gang_of
            from .plugins.preemption import PriorityPreemptor

            helped = {p.meta.uid for p in retry_pods} | nominated_uids
            pp = PriorityPreemptor(self)
            for pod in sorted(
                unsched, key=lambda p: -(p.spec.priority or 0)
            ):
                if (
                    pod.meta.uid in dropped_uids
                    or pod.meta.uid in helped
                    or _gang_of(pod) is not None
                ):
                    continue
                if ext.parse_reservation_affinity(
                    pod.meta.annotations
                ) or ext.pod_never_preempts(pod):
                    continue
                sel = pp.select_victims(pod)
                if sel is None:
                    continue
                _node, victims = sel
                if self.defer_preemption:
                    seen = {v.meta.uid for v in preempted}
                    preempted.extend(
                        v for v in victims if v.meta.uid not in seen
                    )
                    continue
                for victim in victims:
                    self.evict_for_preemption(victim)
                    preempted.append(victim)
                retry_pods.append(pod)
                self._window_extra_nodes.add(_node)
        if retry_pods or (preempted and not self.defer_preemption):
            # EAGER preemption moved window bookkeeping / evicted holders
            # — the speculative chain (if any) no longer matches the
            # snapshot. Nominate-only (defer_preemption) passes are pure
            # reads and keep the chain (open the last gates PR): the
            # external migration controller's eventual evictions bump
            # snapshot.version and discard any in-flight speculation at
            # the ordinary version guard.
            self._cycle_preempted = True
        if retry_pods:
            # the retry's sampled window must contain the nodes the
            # victims were just evicted from (_window_extra_nodes — the
            # rotated window would usually exclude them, wasting the
            # evictions); _select_nodes consumes the set
            again = self.schedule(retry_pods, _retry=True)
            bound.extend(again.bound)
            retried = {p.meta.uid for p in retry_pods}
            unsched = [
                p for p in unsched if p.meta.uid not in retried
            ] + list(again.unschedulable)

        for pod, _node in bound:
            self.pod_groups.remove_pod(pod, bound=True)
        # Tail bookkeeping runs once per external cycle: the preemption
        # retry's inner call skips it (the outer call accounts the merged
        # results) so retried pods are never double-counted and never get
        # errors.handle/monitor.complete fired twice.
        if not _retry:
            for pod in unsched:
                if pod.meta.uid not in dropped_uids:
                    fwext.errors.handle(pod, "unschedulable in batch cycle")
            # The attempt is over for every pod in this cycle, whatever
            # the outcome — the reference monitor wraps scheduleOne the
            # same way.
            fwext.monitor.complete_batch([p for p, _n in bound])
            fwext.monitor.complete_batch(unsched)
            from .plugins.coscheduling import gang_key_of

            gated_groups = {gang_key_of(p) for p in gated} - {None}
            fwext.registry.get("scheduled_pods_total").inc(len(bound))
            fwext.registry.get("unschedulable_pods_total").inc(len(unsched))
            fwext.registry.get("waiting_gang_group_number").set(
                float(len(gated_groups))
            )
            # flush the cycle's buffered commit-loop rejections, keeping
            # only pods the cycle REALLY failed to place (a preemption
            # retry may have bound some) and the most recent attempt's
            # attribution when a pod failed both the outer pass and the
            # retry
            unsched_uids = {p.meta.uid for p in unsched}
            flushed: Dict[str, tuple] = {}
            for entry in self._cycle_rejects:
                if entry[0].meta.uid in unsched_uids:
                    flushed[entry[0].meta.uid] = entry
            self._cycle_rejects = []
            for pod, stage, plugin, reason in flushed.values():
                rej.record(cid, pod, stage, plugin, reason)
            if fwext.filters.enabled:
                # per-stage rejected-pod tally for /debug/filters, joined
                # to this cycle by id (includes the preemption retry's
                # records — it shares the parent cycle id)
                tally: Dict[str, int] = {}
                for r in rej.records(cycle_id=cid):
                    tally[f"{r.stage}:{r.plugin}"] = (
                        tally.get(f"{r.stage}:{r.plugin}", 0) + 1
                    )
                fwext.filters.capture(tally)
            self._cycle_tail_bookkeeping()
            # interned-row eviction (bind/drop): a bound pod never lowers
            # again and a transformer-dropped pod must not resurrect; the
            # overflow trim sheds the OLDEST half (insertion order — same
            # discipline as _trim_preempt_skips)
            cache = self._pod_intern
            if cache is not None:
                for pod, _node in bound:
                    cache.pop(pod.meta.uid, None)
                for uid in dropped_uids:
                    cache.pop(uid, None)
                if len(cache) > max(4096, 4 * self.batch_bucket):
                    from itertools import islice

                    for uid in list(islice(cache, len(cache) // 2)):
                        del cache[uid]
            self._post_cycle_version = self.snapshot.version
        return ScheduleOutcome(
            bound=bound,
            unschedulable=unsched,
            rounds_used=rounds,
            preempted=preempted,
        )

    def _trim_preempt_skips(self) -> None:
        """Evict the OLDEST half of the preemption-skip ledger when it
        overflows. A wholesale ``.clear()`` here reset the window-rotation
        fairness clock for EVERY pending pod at once — each one restarted
        its full-rotation wait and preemption stalled cluster-wide; dicts
        preserve insertion order and re-assignment keeps a key's slot, so
        the first half really is the longest-tracked half. Trade-off: the
        longest-tracked entries carry the most accumulated progress, but
        at >100k tracked pods they are also the likeliest to be stale
        uids of pods long since bound or deleted (nothing else prunes
        this dict), so age-first eviction sheds garbage before progress
        — and a live evicted pod merely re-earns its rotation instead of
        the whole cluster losing its clock."""
        from itertools import islice

        drop = max(len(self._preempt_skips) // 2, 1)
        for uid in list(islice(self._preempt_skips, drop)):
            del self._preempt_skips[uid]

    # ---- robustness: fallback ladder + deadline degrade bookkeeping ----

    def _note_solver_failure(self, level: int, exc: BaseException) -> None:
        """A dispatch at ladder ``level`` failed (compile/device error or
        injected fault): demote for subsequent cycles, count it, surface
        on /healthz. Commit-side Reserve means demoted cycles can only
        under-place, never corrupt state."""
        fallen_to = min(level + 1, 2)
        reg = self.extender.registry
        reg.get("solver_fallback_total").labels(level=str(fallen_to)).inc()
        report_exception(f"scheduler.solve.l{level}", exc, registry=reg)
        self._fallback_level = max(self._fallback_level, fallen_to)
        self._fallback_clean = 0
        self._cycle_solver_failed = True
        self.extender.health.set(
            "solver",
            False,
            f"fallback level {self._fallback_level} after: {exc!r}",
        )

    def _dispatch_with_fallback(self, chunks, sub):
        """Fallback ladder (robustness tentpole): level 0 = scanned
        multi-chunk, 1 = per-chunk dispatch, 2 = pure-numpy host
        reference. Each level's failure falls through to the next within
        the SAME cycle; the reached level persists for subsequent cycles
        and ``fallback_repromote_after`` consecutive clean cycles
        re-promote one level (see ``_cycle_tail_bookkeeping``).

        Mesh mode rides the SAME ladder (first-class multi-chip PR):
        level 0 is the pipelined sharded dispatch (the scanned program
        declines meshes), a mesh dispatch fault degrades to the
        per-chunk sharded path and then to the host reference — the
        same capacity-safe approximate trade the single-chip ladder
        already accepts (under-placement, never overcommit), instead of
        crashing the cycle. Decision identity is guaranteed by the
        sharded==single bit-exactness suite, not by refusing to
        degrade."""
        if not chunks:
            return []
        level = self._fallback_level
        if level == 0:
            try:
                self.chaos.fire("solver.dispatch")
                if len(chunks) > 1:
                    solves = self._dispatch_scanned(chunks, sub)
                    if solves is None:
                        solves = self._dispatch_pipelined(chunks, sub)
                else:
                    solves = [
                        (c, None, self.solve(c, sub)) for c in chunks
                    ]
                return solves
            except Exception as exc:  # noqa: BLE001 — ladder absorbs
                self._note_solver_failure(0, exc)
                level = 1
        if level == 1:
            try:
                self.chaos.fire("solver.dispatch_chunk")
                if len(chunks) > 1:
                    return self._dispatch_pipelined(chunks, sub)
                return [(c, None, self.solve(c, sub)) for c in chunks]
            except Exception as exc:  # noqa: BLE001 — ladder absorbs
                self._note_solver_failure(1, exc)
        with self.extender.tracer.span(
            "assign", cat="scheduler", mode="host_reference",
            chunks=len(chunks),
        ):
            try:
                return self._dispatch_host_reference(chunks, sub)
            except Exception as exc:  # noqa: BLE001 — containment floor
                # the ladder's floor ALSO raised: every level crashed on
                # the same batch, which is the poison-batch signature.
                # Bisect to isolate the minimal blame set instead of
                # failing the whole cycle forever.
                return self._contain_poison(chunks, sub, exc)

    def _contain_poison(self, chunks, sub, exc: BaseException):
        """Poison-batch bisection: every fallback level crashed on this
        batch, so some pod's lowering deterministically raises. Probe
        groups of pods through throwaway lowerings (binary search over
        each failing chunk) until the failing singletons are isolated,
        blame them on the quarantine ledger (sealed journal record — a
        takeover adopts the blame BEFORE replaying its queue), and
        re-dispatch the remaining healthy pods through the host
        reference so the rest of the batch still places this cycle.

        If no quarantine ledger is wired, or the probes cannot pin a
        poison pod (the failure is not pod-deterministic), the original
        error is re-raised — containment never masks a real outage."""
        reg = self.extender.registry
        probes = reg.get("poison_bisect_probes_total")

        def _probe(grp):
            """The exception this group's lowering raises, or None."""
            probes.inc()
            try:
                # stash=False + private quarantine dict: a probe must
                # not pollute commit state or the cycle's NaN records
                self._lower_rows(grp, stash=False, quarantine={})
                return None
            except Exception as probe_exc:  # noqa: BLE001 — probing for this
                if len(grp) == 1:
                    # singleton isolation: THIS exception is the pod's
                    # blame evidence — report it once per blamed pod
                    # (per-probe reporting would count a dozen split
                    # probes for one contained fault)
                    report_exception(
                        "scheduler.poison_probe", probe_exc, registry=reg
                    )
                return probe_exc

        poison: List[tuple] = []   # (pod, its own lowering exception)
        stack: List[List[Pod]] = [list(c) for c in chunks if len(c)]
        while stack:
            grp = stack.pop()
            probe_exc = _probe(grp)
            if probe_exc is None:
                continue
            if len(grp) == 1:
                poison.append((grp[0], probe_exc))
                continue
            mid = len(grp) // 2
            stack.append(grp[:mid])
            stack.append(grp[mid:])
        if not poison:
            raise exc
        cid = self.extender.current_cycle_id
        for pod, pod_exc in poison:
            if self.quarantine is not None:
                self.quarantine.blame(
                    pod.meta.uid,
                    spec_fingerprint(pod),
                    evidence=repr(pod_exc),
                    cycle=cid,
                )
            self._cycle_rejects.append(
                (
                    pod,
                    RejectStage.SOLVE,
                    "poison_quarantine",
                    RejectReason.POISON_QUARANTINED,
                )
            )
        reg.get("poison_quarantined_total").inc(len(poison))
        self._cycle_poisoned.extend(pod for pod, _e in poison)
        report_exception("scheduler.poison_quarantine", exc, registry=reg)
        poison_uids = {pod.meta.uid for pod, _e in poison}
        healthy = [
            kept
            for kept in (
                [p for p in c if p.meta.uid not in poison_uids]
                for c in chunks
            )
            if kept
        ]
        if not healthy:
            return []
        return self._dispatch_host_reference(healthy, sub)

    def _dispatch_host_reference(self, chunks, sub: Optional[np.ndarray] = None):
        """Level-2 degraded mode: a pure-numpy greedy assigner that keeps
        the cluster draining when the device path is down. Decision-
        APPROXIMATE, capacity-SAFE: pods commit in (-priority, arrival)
        order against locally-charged copies of node capacity, LoadAware
        thresholds and the quota chain table; NUMA/device exactness is
        left to the commit-side Reserve revalidation (an infeasible pick
        is rejected there and retries next cycle — under-placement,
        never overcommit). Batch/cost transformers do not run here."""
        snap = self.snapshot
        na = snap.nodes
        n_real = snap.node_count
        rows_idx = (
            np.arange(n_real, dtype=np.int64)
            if sub is None
            else np.asarray(sub, np.int64)
        )
        alloc = na.allocatable[rows_idx].copy()
        requested = na.requested[rows_idx].copy()
        est_used = (
            np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
        )[rows_idx].copy()
        prod_used = (na.prod_usage + na.assigned_pending_prod)[
            rows_idx
        ].copy()
        schedulable = na.schedulable[rows_idx].copy()
        if (
            self.args.filter_expired_node_metrics
            and not self.args.enable_schedule_when_node_metrics_expired
        ):
            schedulable &= (
                na.metric_fresh[rows_idx] | ~na.has_metric[rows_idx]
            )
        fresh = na.metric_fresh[rows_idx]
        thr = np.asarray(self._params.usage_thresholds)
        pthr = np.asarray(self._params.prod_thresholds)
        w = np.asarray(self._params.score_weights)
        cap = alloc * thr[None, :] / 100.0
        pcap = alloc * pthr[None, :] / 100.0
        runtime = used = None
        host_quota = self._quota_host_arrays(
            [p for c in chunks for p in c]
        )
        if host_quota is not None:
            runtime, used = host_quota
            runtime = np.asarray(runtime)
            used = np.asarray(used).copy()
        out = []
        for chunk in chunks:
            arrays, _est, rows = self._lower_rows(chunk)
            n = len(chunk)
            assignment = np.full(arrays.requests.shape[0], -1, np.int32)
            mask_host = self._node_constraint_mask_host(chunk, n)
            valid = arrays.valid
            order = np.lexsort((np.arange(n), -rows.prio[:n]))
            for i in order.tolist():
                if not valid[i]:
                    continue
                req = rows.req[i]
                est = rows.est[i]
                chain: List[int] = []
                if used is not None and rows.quota_chain is not None:
                    chain = [
                        int(q)
                        for q in rows.quota_chain[i]
                        if 0 <= q < used.shape[0]
                    ]
                    if any(
                        np.any(used[q] + req > runtime[q] + 1e-3)
                        for q in chain
                    ):
                        continue
                feas = schedulable & np.all(
                    req[None, :] <= alloc - requested + 1e-3, axis=1
                )
                if mask_host is not None:
                    feas &= mask_host[i][rows_idx]
                if feas.any():
                    ok_thr = np.where(
                        (thr[None, :] > 0) & fresh[:, None],
                        est_used + est[None, :] <= cap + 1e-3,
                        True,
                    ).all(axis=1)
                    if rows.is_prod[i] and pthr.any():
                        ok_thr &= np.where(
                            (pthr[None, :] > 0) & fresh[:, None],
                            prod_used + est[None, :] <= pcap + 1e-3,
                            True,
                        ).all(axis=1)
                    feas &= ok_thr
                if not feas.any():
                    continue
                after = est_used + est[None, :]
                free_pct = (
                    np.maximum(alloc - after, 0.0) * 100.0 / (alloc + 1e-9)
                )
                cost = -np.sum(free_pct * w[None, :], axis=1) / (
                    w.sum() + 1e-9
                )
                j = int(np.argmin(np.where(feas, cost, np.inf)))
                assignment[i] = j
                requested[j] += req
                est_used[j] += est
                if rows.is_prod[i]:
                    prod_used[j] += est
                for q in chain:
                    used[q] += req
            out.append(
                (
                    chunk,
                    rows,
                    _HostSolve(
                        assignment=assignment, pod_zone=None, rounds_used=1
                    ),
                )
            )
        return out

    def _cycle_tail_bookkeeping(self) -> None:
        """Once per external cycle: re-promotion clocks for the fallback
        ladder and the deadline-degraded batch bucket, plus /healthz
        state transitions."""
        health = self.extender.health
        if self._fallback_level > 0 and not self._cycle_solver_failed:
            self._fallback_clean += 1
            if self._fallback_clean >= self.fallback_repromote_after:
                self._fallback_level -= 1
                self._fallback_clean = 0
                if self._fallback_level == 0:
                    health.set("solver", True)
                else:
                    health.set(
                        "solver",
                        False,
                        f"fallback level {self._fallback_level} "
                        "(re-promoting)",
                    )
        if self.cycle_deadline_s is not None:
            if self._cycle_deadline_hit:
                if self.effective_batch_bucket() > 16:
                    self._bucket_degrade += 1
                self._degrade_clean = 0
                health.set(
                    "cycle_deadline",
                    False,
                    f"deadline exceeded; batch degraded to "
                    f"{self.effective_batch_bucket()}",
                )
            else:
                self._degrade_clean += 1
                if self._degrade_clean >= self.fallback_repromote_after:
                    if self._bucket_degrade > 0:
                        self._bucket_degrade -= 1
                        self._degrade_clean = 0
                    if self._bucket_degrade == 0:
                        health.set("cycle_deadline", True)
        if not (self._cycle_commit_rolled_back or self._cycle_journal_failed):
            health.set("commit", True)
        if self.scrub_rows:
            # anti-entropy audit rides the cycle tail: one rotating
            # window per cycle, never raising into the scheduling path
            try:
                self.scrub_step()
            except Exception as exc:  # noqa: BLE001 — audit must not
                # take down scheduling; a broken scrub is an error
                # report, not an outage
                report_exception(
                    "scheduler.scrub", exc, registry=self.extender.registry
                )

    # ---- anti-entropy scrubber (state-integrity PR) ----

    def _scrub_clean_rows(self, rows: np.ndarray) -> np.ndarray:
        """The subset of ``rows`` whose resident node mirror must equal
        CURRENT host truth: rows without a pending dirty mark. A marked
        row legitimately lags (un-scattered truth, not rot); an
        unmarked row was untouched since the mirror's version, so any
        difference there is corruption. Empty when the whole mirror is
        pending a rebuild."""
        snap = self.snapshot
        cur = self._resident_nodes
        if (
            cur is None
            or snap._dirty_all
            or cur.allocatable.shape[0] != snap.nodes.allocatable.shape[0]
        ):
            return np.zeros((0,), np.int32)
        if snap._dirty_rows:
            rows = rows[
                ~np.isin(rows, np.fromiter(snap._dirty_rows, np.int64))
            ]
        return rows

    def _scrub_nodes_window(self, rows: np.ndarray) -> np.ndarray:
        """Host-truth vs resident comparison for one node-table window
        (pre-filtered to clean rows by :meth:`_scrub_clean_rows`).
        Returns the GLOBAL row indices that diverged."""
        snap = self.snapshot
        cur = self._resident_nodes
        if len(rows) == 0:
            return rows.astype(np.int32)
        na = snap.nodes
        est = (
            np.maximum(na.usage_agg[rows], na.usage_avg[rows])
            + na.assigned_pending[rows]
        )
        sched_rows = na.schedulable[rows]
        if (
            self.args.filter_expired_node_metrics
            and not self.args.enable_schedule_when_node_metrics_expired
        ):
            sched_rows = sched_rows & (
                na.metric_fresh[rows] | ~na.has_metric[rows]
            )
        idx = jnp.asarray(rows.astype(np.int32))
        pairs = (
            (na.allocatable[rows], cur.allocatable),
            (na.requested[rows], cur.requested),
            (est, cur.estimated_used),
            (
                na.prod_usage[rows] + na.assigned_pending_prod[rows],
                cur.prod_used,
            ),
            (na.metric_fresh[rows], cur.metric_fresh),
            (sched_rows, cur.schedulable),
            (na.cpu_amp[rows], cur.cpu_amp),
            (na.custom_thresholds[rows], cur.custom_thresholds),
            (na.custom_prod_thresholds[rows], cur.custom_prod_thresholds),
        )
        bad = np.zeros((len(rows),), bool)
        for host, res in pairs:
            got = np.asarray(jnp.take(res, idx, axis=0))
            diff = got != np.asarray(host)
            bad |= (
                diff
                if diff.ndim == 1
                else diff.reshape(len(rows), -1).any(axis=1)
            )
        return rows[bad]

    def _scrub_constraint_window(
        self, mgr, cache, arrays_of, rows: np.ndarray
    ) -> np.ndarray:
        """Window audit for a manager-backed resident table (NUMA zones
        / device slots). ``cache`` is the (key, state) device cache,
        ``arrays_of`` maps the manager to its ordered host arrays and
        the cached state to the matching device arrays. Rows with a
        pending scatter mark are excluded (they legitimately lag until
        the next refresh); unmarked rows must match host truth
        bit-exactly."""
        if cache is None or mgr._scatter_full:
            return np.zeros((0,), np.int32)
        _key, state = cache
        # arrays_of flushes the manager's pending dirty names into the
        # scatter marks, and CAN raise the full-rebuild flag mid-flush
        host_arrays, dev_arrays = arrays_of(mgr, state)
        if mgr._scatter_full:
            return np.zeros((0,), np.int32)
        if mgr._scatter_rows:
            rows = rows[
                ~np.isin(
                    rows, np.fromiter(mgr._scatter_rows, np.int64)
                )
            ]
        if len(rows) == 0:
            return rows.astype(np.int32)
        idx = jnp.asarray(rows.astype(np.int32))
        bad = np.zeros((len(rows),), bool)
        for host, dev in zip(host_arrays, dev_arrays):
            if dev is None:
                continue
            host = np.asarray(host)
            dev_shape = tuple(dev.shape)
            if dev_shape != host.shape or rows.max() >= host.shape[0]:
                return np.zeros((0,), np.int32)
            got = np.asarray(jnp.take(dev, idx, axis=0))
            diff = got != host[rows]
            bad |= (
                diff
                if diff.ndim == 1
                else diff.reshape(len(rows), -1).any(axis=1)
            )
        return rows[bad]

    def scrub_step(self, rows: Optional[int] = None) -> Dict[str, object]:
        """One anti-entropy audit step (state-integrity PR): re-lower a
        rotating window of HOST truth and compare it bit-exact against
        the device-resident NodeState / NUMA / device / quota tables.
        Divergence (cosmic bit rot, a missed scatter, or the
        ``resident.bit_flip`` chaos point) is counted per table
        (``resident_scrub_divergence_total{table}``), self-healed
        through ``touch_rows`` + the dirty-row scatter, and surfaced at
        ``/debug/scrub``. The audit is PASSIVE for tables mid-refresh:
        a resident mirror legitimately behind its host version is
        skipped, never "healed" against in-flight truth."""
        reg = self.extender.registry
        snap = self.snapshot
        window = int(
            rows if rows is not None else (self.scrub_rows or 64)
        )
        report = self._scrub_report
        with snap.lock:
            n_bucket = snap.nodes.allocatable.shape[0]
            start = self._scrub_cursor % n_bucket
            span = np.arange(start, start + min(window, n_bucket))
            win = (span % n_bucket).astype(np.int32)
            win = np.unique(win)
            self._scrub_cursor = (start + min(window, n_bucket)) % n_bucket
            # The audit is STRICTLY PASSIVE on the device side: it
            # never re-lowers or scatters here, because an in-flight
            # speculative solve (cross-cycle pipeline) may still read
            # the current resident buffers and a scatter DONATES them.
            # Rows with a pending dirty mark are excluded — a marked
            # row legitimately lags host truth until the next refresh
            # scatters it; an UNMARKED row must match bit-exactly.
            clean = self._scrub_clean_rows(win)
            if len(clean) and self.chaos.fire("resident.bit_flip"):
                # corruption fault domain: one resident cell rots on
                # device. Injected into a CLEAN row of the current
                # window, so the audit that owns this step detects it
                # immediately and the heal mark makes the next refresh
                # scatter truth back (the soak separately asserts
                # end-state bit-exactness). Evaluated only when this
                # step can audit — an armed flip waits for a step with
                # clean rows instead of rotting undetectably.
                row = int(clean[0])
                cur = self._resident_nodes
                self._resident_nodes = cur.replace(
                    requested=cur.requested.at[row, 0].add(1.0)
                )
            diverged: Dict[str, int] = {}
            healed_rows: Dict[str, list] = {}
            bad = self._scrub_nodes_window(clean)
            if len(bad):
                diverged["nodes"] = int(len(bad))
                healed_rows["nodes"] = [int(r) for r in bad]
                # heal by MARKING: the next cycle's normal refresh
                # scatters host truth into exactly these rows (writing
                # here would donate buffers an in-flight speculative
                # solve may still read)
                snap.touch_rows(bad)
            if (
                self.numa is not None
                and getattr(self.numa, "has_topology", False)
            ):
                bad = self._scrub_constraint_window(
                    self.numa,
                    self._numa_dev_cache,
                    lambda m, s: (
                        (*m.arrays(), m.most_allocated_rows()),
                        (s.zone_free, s.zone_cap, s.policy, s.zone_most),
                    ),
                    win,
                )
                if len(bad):
                    diverged["numa"] = int(len(bad))
                    healed_rows["numa"] = [int(r) for r in bad]
                    self.numa.touch_lowered_rows(bad)
            if (
                self.devices is not None
                and getattr(self.devices, "has_devices", False)
            ):
                bad = self._scrub_constraint_window(
                    self.devices,
                    self._device_dev_cache,
                    lambda m, s: (
                        (
                            m.slot_array(),
                            m.rdma_array() if m.has_rdma else None,
                            m.fpga_array() if m.has_fpga else None,
                            m.cap_array(),
                        ),
                        (s.slot_free, s.rdma_free, s.fpga_free, s.cap_total),
                    ),
                    win,
                )
                if len(bad):
                    diverged["device"] = int(len(bad))
                    healed_rows["device"] = [int(r) for r in bad]
                    self.devices.touch_lowered_rows(bad)
            n_quota = self._scrub_quota_table()
            if n_quota:
                diverged["quota"] = n_quota
        reg.get("resident_scrub_rows_total").inc(float(len(win)))
        for table, n in diverged.items():
            reg.get("resident_scrub_divergence_total").labels(
                table=table
            ).inc(float(n))
        report["steps"] = int(report["steps"]) + 1
        report["cursor"] = int(self._scrub_cursor)
        report["window"] = window
        report["rows_audited"] = int(report["rows_audited"]) + len(win)
        totals = dict(report["divergence"])
        for table, n in diverged.items():
            totals[table] = totals.get(table, 0) + n
        report["divergence"] = totals
        report["last"] = {
            "rows": [int(win[0]), int(win[-1])] if len(win) else [],
            "diverged": diverged,
            "healed_rows": healed_rows,
        }
        return report["last"]

    def _scrub_quota_table(self) -> int:
        """Whole-table audit of the resident quota lowering (small:
        [Q, D] twice). Diverged → drop the device cache (the next
        quota_state re-lowers from host truth — the quota table's
        normal full-upload path). Returns diverged row count."""
        cache = self._quota_dev_cache
        if cache is None or self.quotas is None:
            return 0
        key, state = cache
        if key[0] != self.quotas.state_version:
            return 0
        runtime, used = self.quotas.quota_arrays_extended()
        if runtime.shape[0] == 1:
            pad = np.zeros((1, runtime.shape[1]), np.float32)
            runtime = np.concatenate([runtime, pad])
            used = np.concatenate([used, pad])
        if runtime.shape != tuple(state.runtime.shape):
            return 0
        bad = (np.asarray(state.runtime) != runtime).any(axis=1) | (
            np.asarray(state.used) != used
        ).any(axis=1)
        n = int(bad.sum())
        if n:
            self._quota_dev_cache = None
        return n

    def node_allowed(self, pod: Pod, node_name: str) -> bool:
        """Single-node form of the node-constraint mask (nodeSelector /
        required nodeAffinity names / spec.nodeName)."""
        spec = pod.spec
        if not (
            spec.node_selector or spec.affinity_required_nodes or spec.node_name
        ):
            return True
        if spec.node_name and spec.node_name != node_name:
            return False
        if (
            spec.affinity_required_nodes is not None
            and node_name not in set(spec.affinity_required_nodes)
        ):
            return False
        labels = self.snapshot.node_labels(node_name)
        return all(
            labels.get(k) == v for k, v in spec.node_selector.items()
        )

    def bound_node_of(self, pod_uid: str) -> Optional[str]:
        """Node a previously-bound pod is charged to, or None once the pod
        is no longer assumed (deleted/forgotten externally)."""
        node = self._bound_nodes.get(pod_uid)
        if node is None or pod_uid not in self.snapshot._assumed:
            return None
        return node

    def evict_for_preemption(self, victim: Pod) -> None:
        """Release a preemption victim's holds everywhere: snapshot charge,
        quota chain, NUMA cpuset, device minors (the caller is responsible
        for the actual eviction API call, like the reference's evictor)."""
        from .plugins.elasticquota import quota_name_of

        uid = victim.meta.uid
        node = self._bound_nodes.pop(uid, None)
        self._bound_pods.pop(uid, None)
        was_assumed = self.snapshot.is_assumed(uid)
        self.snapshot.forget_pod(uid)
        if self.bind_journal is not None and (was_assumed or node is not None):
            # journal the release so a replay does not resurrect the
            # pod's charge. Fence-EXEMPT (epoch=None): deletions are
            # apiserver-authoritative and a standby's informers keep
            # observing them during a leaderless gap. Best-effort: a
            # refused write cannot block the delete, but is visible.
            try:
                self.bind_journal.append_forget(
                    None,
                    self.extender.current_cycle_id,
                    [uid],
                )
            except (JournalWriteError, StaleEpochError) as exc:
                report_exception(
                    "scheduler.journal.forget",
                    exc,
                    registry=self.extender.registry,
                )
        leaf = quota_name_of(victim)
        if leaf is not None:
            self.quotas.unassign_pod(leaf, victim)
        if node is not None:
            if self.numa is not None:
                self.numa.release(uid, node)
            if self.devices is not None:
                self.devices.release(uid, node)

    def _debug_capture(self, chunk: Sequence[Pod], assignment: np.ndarray) -> None:
        """Host-side recompute of the LoadAware cost for the debug score
        table (reference /debug/flags/s) — only when dumping is enabled."""
        na = self.snapshot.nodes
        est_used = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
        n_real = self.snapshot.node_count
        na_alloc = na.allocatable[:n_real]
        est_used = est_used[:n_real]
        names = [self.snapshot.node_name(i) for i in range(n_real)]
        w = np.asarray(self._params.score_weights)
        costs = np.zeros((len(chunk), n_real), np.float32)
        for i, pod in enumerate(chunk):
            est = self.snapshot.config.res_vector(pod.spec.requests) * self._scales
            after = est_used + est[None, :]
            free = np.maximum(na_alloc - after, 0.0)
            per = np.where(na_alloc > 0, free * 100.0 / (na_alloc + 1e-9), 0)
            costs[i] = -np.sum(per * w, -1) / (np.sum(w) + 1e-9)
        # Mirror what the solver actually ranked: apply the BeforeScore
        # chain to the table too.
        transform = self.extender.cost_transform
        if transform is not None:
            costs = np.asarray(transform(costs), np.float32)
        self.extender.scores.capture(chunk, names, costs, assignment[: len(chunk)])

    # ---- rejection attribution ----

    def _record_chunk_rejections(
        self,
        chunk: Sequence[Pod],
        rows: Optional[LoweredRows],
        assignment: np.ndarray,
        unsched: Sequence[Pod],
    ) -> None:
        """One rejection record per pod this chunk failed to place: the
        Reserve/Permit stages report their exact failure via
        ``_reserve_reject``; solver-rejected pods (assignment < 0) are
        attributed host-side by replaying the boolean-mask stages in
        filter order against the live snapshot. Records are BUFFERED on
        the scheduler and flushed at the end of the external cycle, so a
        pod the postfilter retry binds leaves no record."""
        if not unsched:
            return
        fwext = self.extender
        cid = fwext.current_cycle_id
        rows = rows if rows is not None else self._lowered
        idx = {u: i for i, u in enumerate(rows.uids)}
        with fwext.tracer.span(
            "attribute", cat="scheduler", cycle=cid, pods=len(unsched)
        ):
            for pod in unsched:
                uid = pod.meta.uid
                # quarantined rows carry their NUMERIC_INVALID verdict
                # from lowering time (the first stage that saw them)
                hit = self._numeric_quarantine.get(
                    uid
                ) or self._reserve_reject.get(uid)
                if hit is None:
                    i = idx.get(uid)
                    if i is not None and assignment[i] < 0:
                        hit = self._classify_solver_reject(
                            pod, rows.req[i], rows.est[i]
                        )
                    else:
                        hit = (
                            RejectStage.SOLVE,
                            "solver",
                            RejectReason.NO_FEASIBLE_NODE,
                        )
                self._cycle_rejects.append((pod, hit[0], hit[1], hit[2]))

    def _classify_solver_reject(
        self, pod: Pod, req_row: np.ndarray, est_row: np.ndarray
    ) -> tuple:
        """Replay the mask stages host-side for one rejected pod, in the
        same order the solver composes them, and return the first stage
        that zeroes the pod's node row (stage, plugin, reason). A pod no
        stage rejects lost the capacity rounds to higher-priority
        competitors (or awaits its gang)."""
        from .plugins.coscheduling import gang_key_of
        from .plugins.elasticquota import (
            is_pod_non_preemptible,
            quota_name_of,
        )

        snap = self.snapshot
        na = snap.nodes
        n_real = snap.node_count
        if n_real == 0:
            return (
                RejectStage.FILTER,
                "noderesources",
                RejectReason.NO_MATCHING_NODE,
            )
        leaf = quota_name_of(pod)
        if (
            leaf is not None
            and self.quotas.quota_count > 0
            and not self.quotas.has_headroom(
                leaf,
                pod.spec.requests,
                non_preemptible=is_pod_non_preemptible(pod),
            )
        ):
            return (
                RejectStage.QUOTA,
                "elasticquota",
                RejectReason.QUOTA_EXHAUSTED,
            )
        spec = pod.spec
        if spec.node_selector or spec.affinity_required_nodes or spec.node_name:
            allowed = np.fromiter(
                (
                    self.node_allowed(pod, snap.node_name(j))
                    for j in range(n_real)
                ),
                bool,
                count=n_real,
            )
            if not allowed.any():
                return (
                    RejectStage.FILTER,
                    "nodeaffinity",
                    RejectReason.NO_MATCHING_NODE,
                )
        else:
            allowed = np.ones(n_real, bool)
        free = na.allocatable[:n_real] - na.requested[:n_real]
        fits = (
            na.schedulable[:n_real]
            & allowed
            & np.all(req_row[None, :] <= free + 1e-3, axis=1)
        )
        if not fits.any():
            return (
                RejectStage.FILTER,
                "noderesources",
                RejectReason.INSUFFICIENT_RESOURCES,
            )
        est_used = (
            np.maximum(na.usage_agg[:n_real], na.usage_avg[:n_real])
            + na.assigned_pending[:n_real]
        )
        fresh = na.metric_fresh[:n_real][:, None]
        thr = np.asarray(self._params.usage_thresholds)
        cap = na.allocatable[:n_real] * thr[None, :] / 100.0
        thr_ok = np.where(
            (thr[None, :] > 0) & fresh,
            est_used + est_row[None, :] <= cap + 1e-3,
            True,
        ).all(axis=1)
        pthr = np.asarray(self._params.prod_thresholds)
        is_prod = (
            ext.PriorityClass.from_priority(pod.spec.priority)
            == ext.PriorityClass.PROD
        )
        if pthr.any() and is_prod:
            prod_used = (
                na.prod_usage[:n_real] + na.assigned_pending_prod[:n_real]
            )
            pcap = na.allocatable[:n_real] * pthr[None, :] / 100.0
            thr_ok &= np.where(
                (pthr[None, :] > 0) & fresh,
                prod_used + est_row[None, :] <= pcap + 1e-3,
                True,
            ).all(axis=1)
        if not (fits & thr_ok).any():
            return (
                RejectStage.FILTER,
                "loadaware",
                RejectReason.USAGE_EXCEEDS_THRESHOLD,
            )
        if gang_key_of(pod) is not None:
            return (
                RejectStage.GANG,
                "coscheduling",
                RejectReason.GANG_INCOMPLETE,
            )
        return (RejectStage.SOLVE, "solver", RejectReason.NO_FEASIBLE_NODE)

    def effective_batch_bucket(self) -> int:
        """Chunk size this cycle: ``batch_bucket`` halved once per
        deadline-degrade step (floor 16). A cycle that blows its
        deadline degrades to smaller batches instead of wedging; clean
        cycles re-promote (see the tail bookkeeping). The brownout
        ladder (L2+) contributes one more degrade step for as long as
        it holds — pressure-bounded cycles, re-promoted by the ladder's
        own de-escalation rather than the clean-cycle counter."""
        degrade = self._bucket_degrade
        bo = self.brownout
        if bo is not None:
            degrade += bo.bucket_degrade_steps()
        if degrade <= 0:
            return self.batch_bucket
        return max(16, self.batch_bucket >> degrade)

    def _chunks(self, eligible: Sequence[Pod]) -> List[List[Pod]]:
        """Split into solver batches of ~batch_bucket without splitting a
        gang across chunks (a split gang would be rolled back on both
        sides). A gang larger than the bucket becomes its own chunk —
        bucketed padding handles the odd size."""
        from .plugins.coscheduling import gang_key_of

        blocks: List[List[Pod]] = []
        i = 0
        n = len(eligible)
        while i < n:
            key = gang_key_of(eligible[i])
            j = i + 1
            if key is not None:
                while j < n and gang_key_of(eligible[j]) == key:
                    j += 1
            blocks.append(list(eligible[i:j]))
            i = j
        chunks: List[List[Pod]] = []
        cur: List[Pod] = []
        bucket = self.effective_batch_bucket()
        for block in blocks:
            if cur and len(cur) + len(block) > bucket:
                chunks.append(cur)
                cur = []
            cur.extend(block)
        if cur:
            chunks.append(cur)
        return chunks

    def _shortlist_bucket(self) -> Optional[int]:
        """Effective static ``shortlist_k`` for this dispatch: the
        configured width rounded UP to the next power of two, or None
        when pruning is disabled or the mesh owns the node axis.

        Mesh exemption (written note, per the node-axis pruning PR): the
        tp-sharded path keeps the full axis for now — ``plan_cand`` is a
        per-pod gather across the WHOLE node axis, so on a tp-sharded
        mesh every round's candidate gather would be a cross-shard
        all-gather of the resident node tables, resharding the very
        state the mesh keeps resident. The solver's static gate also
        turns pruning off whenever K would cover the axis anyway."""
        k = self.shortlist_k
        if not k or k <= 0 or self.mesh is not None:
            return None
        return 1 << (int(k) - 1).bit_length()

    def _shortlist_plan_probe(
        self, stacked, nodes0, numa_state, device_state, mask_stacked=None
    ) -> None:
        """Observability-only re-run of the shortlist BUILD as its own
        jitted entry (``ops.solver.shortlist_plan``). On the hot path
        the plan cost is fused into the solve program, so a profile
        window can't attribute it there; with the solver observatory
        attached, time one representative chunk's plan under its own
        ``shortlist`` stage so it shows up in
        ``solve_breakdown_ms.stage_ms``. Never feeds decisions."""
        dp = self.devprof
        k = self._shortlist_bucket()
        n = nodes0.allocatable.shape[0]
        if (
            dp is None
            or k is None
            or k >= n
            or self._device_scoring() == "MostAllocated"
        ):
            return
        from ..ops.solver import shortlist_plan

        chunk0 = jax.tree.map(lambda a: a[0], stacked)
        mask0 = mask_stacked[0] if mask_stacked is not None else None
        with dp.watch(
            "shortlist_plan",
            stage="shortlist",
            bucket=chunk0.requests.shape[0],
            n=n,
            kbucket=k,
            numa=numa_state is not None,
            devices=device_state is not None,
            mask=mask0 is not None,
            numa_scoring=self._numa_scoring(),
            device_scoring=self._device_scoring(),
        ) as w:
            cand, _bound = shortlist_plan(
                chunk0,
                nodes0,
                self._params,
                numa=numa_state,
                devices=device_state,
                node_mask=mask0,
                shortlist_k=k,
                numa_scoring=self._numa_scoring(),
                device_scoring=self._device_scoring(),
            )
            w.result(cand)

    def _dispatch_scanned(
        self, chunks: List[List[Pod]], sub: Optional[np.ndarray] = None
    ):
        """One jitted ``lax.scan`` over every chunk (solve_stream_full):
        a single program launch and 1-2 device→host transfers per drain.
        On tunneled backends each launch/fetch costs a fixed round trip,
        which made the per-chunk pipeline's wall scale with chunk count
        regardless of compute. Chunks carrying hard node constraints
        (nodeSelector / affinity / nodeName) thread their lowered
        [C, P, N] masks through the scan rather than forcing the
        per-chunk path. Returns the same (chunk, rows, result) shape with
        host-side results, or None when the cycle needs the per-chunk
        path (mesh mode or batch/cost transformers)."""
        if self.mesh is not None:
            return None
        ex = self.extender
        if ex._batch_transformers or ex.cost_transform is not None:
            return None
        bucket = max(
            bucket_size(len(c), self.snapshot.config.min_bucket)
            for c in chunks
        )
        if any(
            p.spec.node_selector
            or p.spec.affinity_required_nodes
            or p.spec.node_name
            for c in chunks
            for p in c
        ):
            # constrained chunks thread a dense [C, P, N] bool mask
            # through the scan (all-ones rows for unconstrained pods).
            # Bound its footprint: past ~256 MiB the stacked mask would
            # dominate H2D (or blow device memory), and the per-chunk
            # path — one [P, N] mask in flight at a time — is the better
            # trade there.
            if sub is not None:
                n_mask = bucket_size(len(sub), self.snapshot.config.min_bucket)
            else:
                n_mask = self.snapshot.nodes.allocatable.shape[0]
            c_bucket_est = 1 << (len(chunks) - 1).bit_length()
            if c_bucket_est * bucket * n_mask > (256 << 20):
                return None
        from ..ops.solver import solve_stream_full

        quotas0 = self.quota_state([p for c in chunks for p in c])
        numa_state, device_state = self._constraint_states(sub)
        nodes0 = self.node_state(sub)
        n_axis = nodes0.allocatable.shape[0]
        pods_list: List[PodBatch] = []
        rows_list: List[LoweredRows] = []
        masks_list: List[Optional[jnp.ndarray]] = []
        for chunk in chunks:
            pods_list.append(self.pod_batch(chunk, bucket=bucket))
            rows_list.append(self._lowered)
            masks_list.append(
                self._node_constraint_mask(chunk, bucket, sub)
            )
        if any(m is not None for m in masks_list):
            ones = None
            for k, m in enumerate(masks_list):
                if m is None:
                    if ones is None:
                        ones = jnp.ones((bucket, n_axis), bool)
                    masks_list[k] = ones
        else:
            masks_list = None
        # bucket the CHUNK axis too (next power of two): a drifting
        # backlog would otherwise retrace the scanned program for every
        # distinct chunk count. Padding chunks are all-invalid, so their
        # scan steps exit on round one.
        c_real = len(pods_list)
        c_bucket = 1 << (c_real - 1).bit_length()
        if c_bucket > c_real:
            empty = jax.tree.map(jnp.zeros_like, pods_list[0])
            pods_list.extend([empty] * (c_bucket - c_real))
            if masks_list is not None:
                if ones is None:
                    ones = jnp.ones((bucket, n_axis), bool)
                masks_list.extend([ones] * (c_bucket - c_real))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *pods_list)
        mask_stacked = (
            jnp.stack(masks_list) if masks_list is not None else None
        )
        dp = self.devprof
        with self.extender.tracer.span(
            "assign", cat="scheduler", mode="scanned", chunks=c_real
        ):
            with (
                dp.watch(
                    "solve_stream_full",
                    chunks=c_bucket,
                    bucket=bucket,
                    n=n_axis,
                    quotas=quotas0 is not None,
                    numa=numa_state is not None,
                    devices=device_state is not None,
                    mask=mask_stacked is not None,
                    numa_scoring=self._numa_scoring(),
                    device_scoring=self._device_scoring(),
                    max_rounds=self.max_rounds,
                    shortlist=self._shortlist_bucket(),
                )
                if dp is not None
                else _NULL_WATCH
            ) as w:
                assignments, zones, rounds, fallbacks = solve_stream_full(
                    stacked,
                    nodes0,
                    self._params,
                    quotas=quotas0,
                    numa=numa_state,
                    devices=device_state,
                    max_rounds=self.max_rounds,
                    approx_topk=True,
                    numa_scoring=self._numa_scoring(),
                    device_scoring=self._device_scoring(),
                    node_mask=mask_stacked,
                    shortlist_k=self._shortlist_bucket(),
                )
                w.result(assignments)
            self._shortlist_plan_probe(
                stacked, nodes0, numa_state, device_state, mask_stacked
            )
            host_a = np.asarray(assignments)
            host_z = (
                np.asarray(zones)
                if numa_state is not None
                else None
            )
            host_r = np.asarray(rounds)
            host_fb = np.asarray(fallbacks)
        out = []
        for i, (chunk, rows) in enumerate(zip(chunks, rows_list)):
            out.append(
                (
                    chunk,
                    rows,
                    _HostSolve(
                        assignment=host_a[i],
                        pod_zone=host_z[i] if host_z is not None else None,
                        rounds_used=int(host_r[i]),
                        shortlist_fallbacks=host_fb[i],
                    ),
                )
            )
        return out

    def _dispatch_pipelined(
        self, chunks: List[List[Pod]], sub: Optional[np.ndarray] = None
    ) -> List[Tuple[List[Pod], LoweredRows, SolveResult]]:
        """Dispatch every chunk's solve back-to-back, chaining consumed
        node/quota/device capacity on device (solve_stream's discipline
        applied to the host pipeline): chunk k+1's masks see chunk k's
        solver commits without waiting for the host Reserve of chunk k.
        On tunneled TPU backends the per-dispatch round-trip dominated
        the constrained scenarios — this overlaps all of them. NUMA zone
        state is lowered once and refined only by conservative on-device
        aggregates; the per-slot GPU table is carried EXACTLY on device
        across chunks (ops.device.slot_commit mirrors the host
        allocator's best-fit rule). The host managers still revalidate
        every winner at commit, so any residual staleness can only
        under-place within one call, never overcommit."""
        quotas0 = self.quota_state([p for c in chunks for p in c])
        qused = quotas0.used if quotas0 is not None else None
        numa_state, device_state = self._constraint_states(sub)

        nodes0 = self.node_state(sub)
        if self.mesh is not None:
            from ..parallel.sharded import shard_solver_inputs

            # nodes/NUMA/devices are mesh-resident already — only the
            # replicated quota tables are placed per cycle (tiny [2Q, D])
            (_, _, quotas0, _, _, _, _, _) = shard_solver_inputs(
                self.mesh, quotas=quotas0
            )
            if quotas0 is not None:
                qused = quotas0.used
        cur = nodes0
        dev_carry = None
        numa_carry = None
        out: List[Tuple[List[Pod], LoweredRows, SolveResult]] = []
        for chunk in chunks:
            pods = self.pod_batch(chunk)
            rows = self._lowered
            # transformers see the chained base state fresh each chunk;
            # chaining carries only the solver's own commit DELTAS, so a
            # transformer that rewrites node state (the BeforeFilter
            # analog) is applied exactly once per chunk, never compounded
            pods_t, nodes_t = self.extender.run_batch_transformers(pods, cur)
            node_mask = self._node_constraint_mask(
                chunk, pods_t.requests.shape[0], sub
            )
            if self.mesh is not None:
                from ..parallel.sharded import shard_solver_inputs

                (pods_t, _, _, _, _, node_mask, _, _) = shard_solver_inputs(
                    self.mesh, pods=pods_t, node_mask=node_mask
                )
            dp = self.devprof
            with self.extender.tracer.span(
                "assign", cat="scheduler", mode="pipelined", pods=len(chunk)
            ):
                with (
                    dp.watch(
                        "assign",
                        bucket=pods_t.requests.shape[0],
                        n=nodes_t.allocatable.shape[0],
                        quotas=quotas0 is not None,
                        numa=numa_state is not None,
                        devices=device_state is not None,
                        mask=node_mask is not None,
                        carry=dev_carry is not None or numa_carry is not None,
                        numa_scoring=self._numa_scoring(),
                        device_scoring=self._device_scoring(),
                        max_rounds=self.max_rounds,
                        shortlist=self._shortlist_bucket(),
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    result = assign(
                        pods_t,
                        nodes_t,
                        self._params,
                        quotas=(
                            QuotaState(runtime=quotas0.runtime, used=qused)
                            if quotas0 is not None
                            else None
                        ),
                        numa=numa_state,
                        devices=device_state,
                        max_rounds=self.max_rounds,
                        cost_transform=self.extender.cost_transform,
                        approx_topk=True,
                        node_mask=node_mask,
                        dev_carry=dev_carry,
                        numa_carry=numa_carry,
                        numa_scoring=self._numa_scoring(),
                        device_scoring=self._device_scoring(),
                        shortlist_k=self._shortlist_bucket(),
                    )
                    w.result(result.assignment)
            if nodes_t is cur:
                # no node transformer ran: the solver outputs ARE the
                # chained state (avoids extra dispatches on the tunnel —
                # and allocates nothing: the replace is pure aliasing)
                cur = cur.replace(
                    requested=result.node_requested,
                    estimated_used=result.node_estimated_used,
                    prod_used=result.node_prod_used,
                )
            elif cur is nodes0 or (
                nodes_t.requested is cur.requested
                or nodes_t.estimated_used is cur.estimated_used
                or nodes_t.prod_used is cur.prod_used
            ):
                # chunk 0 carries the RESIDENT arrays (re-read next
                # cycle), and a transformer may pass some carry leaves
                # through unchanged (aliased) — donation would invalidate
                # a buffer somebody still reads, so take the copying form
                with (
                    dp.watch(
                        "_chain_commit_deltas", stage="overlap",
                        n=cur.requested.shape[0],
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    cur = _chain_commit_deltas(cur, nodes_t, result)
                    w.result(cur)
            else:
                # steady chain: the carry arrays belong exclusively to the
                # chain — update them in place (donated)
                with (
                    dp.watch(
                        "_apply_commit_deltas_donated", stage="overlap",
                        n=cur.requested.shape[0],
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    req, est, prod = _apply_commit_deltas_donated(
                        cur.requested,
                        cur.estimated_used,
                        cur.prod_used,
                        nodes_t.requested,
                        nodes_t.estimated_used,
                        nodes_t.prod_used,
                        result.node_requested,
                        result.node_estimated_used,
                        result.node_prod_used,
                    )
                    w.result(req)
                cur = cur.replace(
                    requested=req, estimated_used=est, prod_used=prod
                )
            if quotas0 is not None:
                qused = result.quota_used
            if device_state is not None:
                dev_carry = (
                    result.node_dev_slots,
                    result.node_rdma_free,
                    result.node_fpga_free,
                )
            if numa_state is not None:
                numa_carry = result.node_zone_free
            out.append((chunk, rows, result))
        return out

    def speculation_gate_report(self) -> Dict[str, bool]:
        """Named per-gate verdicts (True = OPEN) for the pipeline's
        speculation gates. One vocabulary serves three consumers: the
        boolean conjunction below (:meth:`_speculation_consume_ok`), the
        CyclePipeline's ``pipeline_gate_closed_total{gate}`` attribution
        and the ``/debug/pipeline`` introspection payload.

        Open-the-gates PRs: ``quotas`` / ``numa`` / ``devices`` report
        OPEN unconditionally — their host commit state rides the device
        chain (:class:`ChainCarry`) with bit-exact retroactive
        validation at consume (:meth:`_carry_consume_ok`), so presence
        no longer forces the serial path. ``gangs`` likewise opens at
        the manager level; the per-BATCH warm-gang check lives in the
        CyclePipeline's ``batch_gangs`` gate. ``reservations`` (open
        the last gates PR) carries the fast path as a validated
        PREDICTION — it closes only for the combination a pure preview
        cannot reproduce: NUMA/device managers live AND an Available
        reservation whose ghost-hold swap would pick cpusets/minors.
        ``preemption`` is open: nominate-only (defer) passes are pure
        reads and chain through; an EAGER eviction+retry sets
        ``_cycle_preempted``, which discards the downstream chain at
        that commit (decision-identical — the next dispatch re-reads
        the post-eviction world). ``mesh`` (first-class multi-chip PR)
        is open: the resident tables are mesh-sharded and the chained
        dispatch runs the SAME jitted program SPMD — the carry rides
        sharded arrays, every carried table is still validated by
        value at consume, and a mesh attach/detach between dispatch
        and consume flips :meth:`_carry_modes` and discards. The
        remaining closed-on-presence gates are transformers (host
        rewrites) and node sampling (rotating sub-axis)."""
        fwext = self.extender
        return {
            "reservations": self.reservations is None
            or not (
                (
                    (self.numa is not None and self.numa.has_topology)
                    or (
                        self.devices is not None
                        and self.devices.has_devices
                    )
                )
                and self.reservations.has_available()
            ),
            "mesh": True,
            "numa": True,
            "devices": True,
            "quotas": True,
            "transformers": not fwext._pre_batch
            and not fwext._batch_transformers
            and fwext.cost_transform is None,
            "preemption": True,
            "gangs": True,
            "sampling": num_nodes_to_score(
                self.snapshot.node_count, self.percentage_of_nodes_to_score
            )
            >= self.snapshot.node_count,
        }

    def _speculation_consume_ok(self) -> bool:
        """Still-gated pipeline subsystems, re-checked at CONSUME time: a
        gated subsystem can arrive through an informer WITHOUT bumping
        ``snapshot.version`` (a reservation manager attach, a
        transformer registration), and a speculation dispatched before
        that arrival must not be consumed. The CARRIED subsystems
        (quota/NUMA/device/gang) are validated by value instead —
        :meth:`_carry_consume_ok` — and a mesh attach/detach is caught
        by the mode-flag comparison (:meth:`_carry_modes`)."""
        return all(self.speculation_gate_report().values())

    def _carry_consume_ok(
        self, spec: "SpeculativeSolve", chunks, corrupt: bool = False
    ) -> bool:
        """Retroactive carry validation (open-the-gates PR): prove, by
        BIT-EXACT value comparison, that every constrained table the
        speculative solve consumed equals what a fresh serial dispatch
        would lower right now. Divergence of any kind — an elastic-quota
        runtime refresh landing differently, a host allocator picking a
        different zone/slot than the device chain, a conservative
        fractional-GPU gang refund, amplification or preemption moving
        capacity — fails the comparison and the speculation is discarded
        (counted per table in ``pipeline_carry_mismatch_total``), the
        cycle re-dispatching from refreshed host state. A kept
        speculation therefore used inputs EQUAL to the serial path's, so
        placements match either way.

        ``corrupt`` is the ``pipeline.carry_mismatch`` chaos point's
        effect (evaluated by the caller at the consume guard's entry, so
        the scheduled fault cannot be starved by an earlier guard
        discarding first): the first carried table is corrupted before
        the comparison, forcing the discard-and-redispatch path through
        the REAL validation code (fixed-cycle soak arm; fires with
        probability 1, so no rng-stream draw)."""
        carry = spec.carry
        reg = self.extender.registry

        def _fail(table: str) -> bool:
            reg.get("pipeline_carry_mismatch_total").labels(
                table=table
            ).inc()
            return False
        # PostFilter/fast-path mode flags must not have flipped since
        # dispatch (none of them bump a version)
        if carry.modes != self._carry_modes():
            return _fail("modes")
        # presence must match what the solve lowered with: a subsystem
        # arriving (or emptying) mid-pipeline invalidates rows that
        # carry no quota chains / no device columns for it
        if (self.quotas.quota_count > 0) != (carry.quota is not None):
            return _fail("quota")
        if (self.reservations is not None) != (carry.resv is not None):
            return _fail("reservation")
        rm = carry.resv
        if rm is not None:
            # reservation carry (open the last gates PR): the dispatch
            # PREDICTED this cycle's fast-path outcome — prove it. The
            # table the preview started from must equal the live table
            # at cycle start (no sync/expiry/informer drift since
            # dispatch), the actual binds and required-affinity refusals
            # must equal the predicted ones, and the live post-fast-path
            # ledger must equal the predicted post table. A bind that
            # flipped a rival's spill feasibility differently than
            # predicted diverges in one of the three.
            if (
                self._cycle_resv_pre_table != rm.pre_table
                or tuple(self._cycle_resv_binds) != rm.binds
                or self._cycle_resv_affinity != rm.affinity_unsched
                or self.reservations.table_view() != rm.post_table
            ):
                return _fail("reservation")
        numa_live = self.numa is not None and self.numa.has_topology
        if numa_live != (carry.numa is not None):
            return _fail("numa")
        dev_live = self.devices is not None and self.devices.has_devices
        if dev_live != (carry.dev is not None):
            return _fail("device")
        all_pods = [p for c in chunks for p in c]
        if self.pod_groups.gang_view(all_pods) != carry.gangs:
            return _fail("gangs")
        q = carry.quota
        if q is not None:
            if q.tree_version != self.quotas.tree_version:
                # the tree was re-indexed — the rows' lowered chains no
                # longer name the right quotas, whatever the tables say
                return _fail("quota")
            # run the REAL mutating demand propagation + runtime refresh
            # exactly where the serial dispatch would (the speculative
            # dispatch only previewed it), then compare
            host = self._quota_host_arrays(all_pods)
            if host is None:
                return _fail("quota")
            runtime_h, used_h = host
            used_spec = np.asarray(q.used_in)
            if corrupt:
                used_spec = used_spec + 1.0
                corrupt = False
            if not (
                runtime_h.shape == q.runtime_host.shape
                and np.array_equal(runtime_h, q.runtime_host)
                and used_h.shape == used_spec.shape
                and np.array_equal(used_h, used_spec)
            ):
                return _fail("quota")
        nm = carry.numa
        if nm is not None:
            zone_free_h, zone_cap_h, policy_h = self.numa.arrays()
            most_h = self.numa.most_allocated_rows()
            zin = np.asarray(nm.zone_in)
            if corrupt:
                zin = zin + 1.0
                corrupt = False
            if not (
                zin.shape == zone_free_h.shape
                and np.array_equal(zin, zone_free_h)
                and np.array_equal(nm.zone_cap, zone_cap_h)
                and np.array_equal(nm.policy, policy_h)
                and np.array_equal(nm.zone_most, most_h)
            ):
                return _fail("numa")
        dv = carry.dev
        if dv is not None:
            slots_h = self.devices.slot_array()
            sin = np.asarray(dv.slots_in)
            if corrupt:
                sin = sin + 1.0
                corrupt = False
            ok = (
                sin.shape == slots_h.shape
                and np.array_equal(sin, slots_h)
                and dv.has_rdma == self.devices.has_rdma
                and dv.has_fpga == self.devices.has_fpga
                and np.array_equal(dv.cap, self.devices.cap_array())
            )
            if ok and dv.has_rdma:
                ok = np.array_equal(
                    np.asarray(dv.rdma_in), self.devices.rdma_array()
                )
            if ok and dv.has_fpga:
                ok = np.array_equal(
                    np.asarray(dv.fpga_in), self.devices.fpga_array()
                )
            if not ok:
                return _fail("device")
        if corrupt:
            # the chaos point fired against a carry-free cycle: force the
            # discard anyway so a scheduled fault is never silently spent
            return _fail("none")
        return True

    def last_cycle_spec_safe(self) -> bool:
        """Whether the just-finished cycle left the speculative chain
        valid: the host Reserve accepted every solver winner, nothing was
        deferred, rolled back or ladder-demoted, and no preemption pass
        ran — the on-device chained capacity state then equals what a
        fresh host lowering would produce (bit-exact for the integral
        milli-CPU / MiB values k8s specs carry)."""
        return not (
            self._cycle_solver_failed
            or self._cycle_deadline_hit
            or self._cycle_commit_rolled_back
            or self._cycle_fetch_deferred
            or self._cycle_reserve_rejected
            or self._cycle_preempted
        )

    def _carry_modes(self) -> tuple:
        """PostFilter/fast-path mode flags a speculative dispatch bakes
        in (compared by value at consume — a flip between dispatch and
        consume changes scheduling behavior without bumping any
        version). The mesh rides along (open-the-mesh-gate PR):
        ``jax.sharding.Mesh`` compares by value (devices + axis names),
        so attaching, detaching or swapping the mesh between dispatch
        and consume discards the speculation — the carried tables were
        lowered under a different placement."""
        return (
            self.reservations is not None,
            self.defer_preemption,
            self.enable_priority_preemption,
            self.quotas.enable_preemption,
            self.mesh,
        )

    def _quota_fastpath_preview_live(self) -> Optional[_QuotaFastpathPreview]:
        """Live-state quota preview for the PREPARE-time reservation
        plan (the prepare worker does not know which chain — if any —
        the dispatch will pick; the dispatch re-previews chain-aware
        and falls back to inline lowering when the plans disagree)."""
        q = self.quotas.quota_count
        if q == 0:
            return None
        self.quotas._ensure_capacity()
        return _QuotaFastpathPreview(
            self.quotas,
            self.snapshot.config,
            self.quotas.used[:q].copy(),
            self.quotas.nonpre_used[:q].copy(),
            self.quotas.runtime[:q],
        )

    def _quota_fastpath_preview_chain(
        self, quota_used_dev, chain_meta: Optional[CarryMeta]
    ) -> Optional[_QuotaFastpathPreview]:
        """Chain-aware quota preview: headroom answered against the
        upstream speculation's predicted post-commit used/non-preemptible
        rows (the device carry) and ITS runtime preview — exactly the
        ledgers the consuming cycle's fast path will read if the chain
        validates. None when the carried shapes no longer line up (tree
        reshaped mid-chain; the dispatch refuses speculation then)."""
        q = self.quotas.quota_count
        if q == 0:
            return None
        carried = np.asarray(quota_used_dev)
        cm = chain_meta.quota if chain_meta is not None else None
        if (
            carried.shape[0] < 2 * q
            or cm is None
            or cm.runtime_host.shape[0] < q
        ):
            return None
        off = carried.shape[0] // 2
        return _QuotaFastpathPreview(
            self.quotas,
            self.snapshot.config,
            carried[:q].copy(),
            carried[off : off + q].copy(),
            cm.runtime_host[:q],
        )

    def _reservation_fastpath_preview(
        self,
        batch: Sequence[Pod],
        base_view=None,
        quota_prev: Optional[_QuotaFastpathPreview] = None,
        chain_nodes=None,
    ) -> Optional[_ResvPlan]:
        """PURE preview of the reservation fast path for ``batch`` (open
        the last gates PR): the same match → quota headroom → spill →
        allocate sequence ``_schedule_locked`` runs, executed against an
        overlay view so neither the manager, the snapshot nor the quota
        ledgers move. ``base_view`` chains the upstream speculation's
        predicted post state (None = live); ``chain_nodes`` supplies the
        chained device node table whose requested rows stand in for the
        not-yet-committed upstream solver charges in spill checks.

        Returns the plan, or None to REFUSE speculation: NUMA/device
        managers with a live match (the ghost-hold cpuset/minor swap is
        a host-allocator decision a pure preview cannot reproduce) and
        operating-pod-backed reservations (charge reshaping) keep such
        cycles serial. A wrong prediction is never a correctness hazard
        — the consume guard compares every predicted outcome by value
        and discards on divergence — it only costs the speculation."""
        from .plugins.coscheduling import gang_key_of
        from .plugins.elasticquota import (
            is_pod_non_preemptible as is_nonpre,
            quota_name_of,
        )
        from .plugins.reservation import ResvView

        resv = self.reservations
        snap = self.snapshot
        view = base_view.clone() if base_view is not None else ResvView(resv)
        if chain_nodes is not None:
            # candidate nodes' requested rows come from the CHAIN (the
            # upstream solver's post-commit charges are not in the host
            # snapshot yet); REPLACING the per-node overlay keeps the
            # upstream view's own predicted deltas from double-counting
            # (they are already inside the chained rows)
            idxs = sorted(
                {
                    snap.node_id(r.node_name)
                    for r in view.candidates()
                }
                - {None}
            )
            if idxs:
                rows = np.asarray(
                    chain_nodes.requested[np.asarray(idxs, np.int32)]
                )
                for i, idx in enumerate(idxs):
                    view.node_req[idx] = rows[i] - snap.nodes.requested[idx]
        pre_table = resv.table_view(view)
        numa_live = self.numa is not None and self.numa.has_topology
        dev_live = self.devices is not None and self.devices.has_devices
        binds: List[tuple] = []
        affinity: List[str] = []
        node_deltas: List[tuple] = []
        cpu_dim = snap._cpu_dim
        for pod in batch:
            required = (
                ext.parse_reservation_affinity(pod.meta.annotations)
                is not None
            )
            if gang_key_of(pod) is not None:
                # the real path never matches gang pods (r = None), but
                # a gang pod with REQUIRED reservation affinity still
                # routes to affinity_unsched there — mirror it, or the
                # predicted chunks/affinity diverge structurally and
                # every speculation over such a batch discards forever
                if required:
                    affinity.append(pod.meta.uid)
                continue
            r = resv.match(pod, view=view)
            if r is None:
                if required:
                    affinity.append(pod.meta.uid)
                continue
            if (
                numa_live
                or dev_live
                or resv.is_operating_backed(r.meta.name)
            ):
                return None
            leaf = quota_name_of(pod)
            nonpre = is_nonpre(pod)
            if (
                leaf is not None
                and quota_prev is not None
                and not quota_prev.headroom(leaf, pod.spec.requests, nonpre)
            ):
                if required:
                    affinity.append(pod.meta.uid)
                continue
            _consumed, spill = resv.consumed_and_spill(r, pod, view)
            if not resv.spill_fits_node(r, spill, view):
                if required:
                    affinity.append(pod.meta.uid)
                continue
            node = r.node_name
            idx = snap.node_id(node)
            if idx is None:
                return None  # racing delete; epoch guard settles it
            # the owner's own assume (assume_pod in the real path):
            # request with the amplified-CPU surcharge for bound pods,
            # estimate from the shared _estimate_of
            req = snap.config.res_vector(pod.spec.requests)
            est = np.asarray(self._estimate_of(pod), np.float32)
            amp = float(snap.nodes.cpu_amp[idx])
            if amp > 1.0 and req[cpu_dim] > 0 and ext.wants_cpu_bind(pod):
                req = req.copy()
                req[cpu_dim] *= amp
            is_prod = pod.priority_class == ext.PriorityClass.PROD
            node_deltas.append(
                (idx, req, est, est if is_prod else np.zeros_like(est))
            )
            view.add_node_delta(idx, req)
            view.assumed[pod.meta.uid] = (req, est, is_prod)
            node_deltas.extend(resv.preview_allocate(r, pod, view))
            if leaf is not None and quota_prev is not None:
                quota_prev.charge(leaf, pod.spec.requests, nonpre)
            binds.append((pod.meta.uid, r.meta.name, node))
        return _ResvPlan(
            binds=tuple(binds),
            affinity_unsched=tuple(affinity),
            taken=frozenset(u for u, _r, _n in binds),
            pre_table=pre_table,
            post_table=resv.table_view(view),
            view=view,
            node_deltas=node_deltas,
            quota_prev=quota_prev,
        )

    def _fold_resv_node_deltas(self, nodes, deltas: List[tuple]):
        """Fold the preview's predicted fast-path node deltas into the
        chained NodeState. Functional ``.at[].add`` updates — the input
        arrays stay live (the fresh-dispatch path hands in the RESIDENT
        state, which must never be consumed). The index vector is padded
        to a power of two (min 8, trailing duplicates carrying zero
        rows, which ``.add`` tolerates) so the update op's jit cache
        stays bounded — the ``_scatter_refresh`` discipline."""
        agg: Dict[int, List[np.ndarray]] = {}
        for idx, dreq, dest, dprod in deltas:
            a = agg.get(idx)
            if a is None:
                agg[idx] = [
                    np.asarray(dreq, np.float32).copy(),
                    np.asarray(dest, np.float32).copy(),
                    np.asarray(dprod, np.float32).copy(),
                ]
            else:
                a[0] += dreq
                a[1] += dest
                a[2] += dprod
        idxs = sorted(agg)
        d = len(self.snapshot.config.resources)
        b = max(8, 1 << (len(idxs) - 1).bit_length())
        ii = np.empty((b,), np.int32)
        ii[: len(idxs)] = idxs
        ii[len(idxs):] = idxs[-1]
        rows = np.zeros((3, b, d), np.float32)
        for i, idx in enumerate(idxs):
            rows[0, i], rows[1, i], rows[2, i] = agg[idx]
        idx_dev = jnp.asarray(ii)
        return nodes.replace(
            requested=nodes.requested.at[idx_dev].add(
                jnp.asarray(rows[0])
            ),
            estimated_used=nodes.estimated_used.at[idx_dev].add(
                jnp.asarray(rows[1])
            ),
            prod_used=nodes.prod_used.at[idx_dev].add(
                jnp.asarray(rows[2])
            ),
        )

    def _dispatch_chained(
        self,
        chunks: List[List[Pod]],
        carry: ChainCarry,
        quarantine: Optional[Dict[str, tuple]] = None,
        prepared: Optional[list] = None,
        gang_view: tuple = (),
        batch: Optional[Sequence[Pod]] = None,
        prep_plan: Optional[_ResvPlan] = None,
        chain_meta: Optional[CarryMeta] = None,
        chained: bool = False,
        prep_chain: object = None,
    ) -> Optional[Tuple[list, ChainCarry, CarryMeta]]:
        """Cross-cycle chained dispatch (the pipeline's speculative fast
        path): solve every chunk against the device-chained capacity
        state carried from the PREVIOUS cycle's solve — dispatched while
        that cycle's host Reserve still trails behind. Open-the-gates
        PR: the constrained subsystems ride the chain too — the quota
        used-table, the exact GPU slot table and the exact NUMA zone
        table are chained across the cycle boundary exactly the way
        ``solve_stream_full``'s scan state chains them across chunks,
        and the quota RUNTIME is a pure host preview of the demand
        propagation the consuming cycle will re-run for real. Decision
        identity rests on :meth:`_carry_consume_ok`'s bit-exact
        retroactive validation, not on gate-guaranteed absence.

        ``prepared`` carries the prepare worker's (PodBatch,
        LoweredRows, node_mask) triples when it finished in time;
        otherwise lowering happens inline (cold, still correct).
        ``batch``/``prep_plan``/``chain_meta``/``chained`` serve the
        reservation carry (open the last gates PR): the FULL batch is
        re-previewed against the chained reservation/quota state and the
        prepared chunks are reused only when the plan still matches the
        prepare-time one. Returns ``(solves, chain_out, carry_meta)``,
        or None when a carried table no longer matches the live shapes
        (tree/topology reshaped mid-chain) or the reservation preview
        refuses (NUMA/device ghost-hold swaps, operating-pod holds) —
        no speculation this cycle."""
        q_real = self.quotas.quota_count
        carried_ext = None
        if q_real > 0 and carry.quota_used is not None:
            # tiny [2Q, D] fetch of an already-completed solve's output;
            # the producing solve finished during the inter-feed window,
            # so this rarely blocks
            carried_ext = np.asarray(carry.quota_used)
            if carried_ext.shape[0] < 2 * q_real:
                return None  # tree reshaped mid-chain
        # ---- reservation fast-path preview (open the last gates PR):
        # predict which pods the consuming cycle's fast path will bind
        # (they leave the solver chunks; their node/quota charges fold
        # into the chain inputs) — every prediction is validated by
        # value at consume (_carry_consume_ok) ----
        resv_plan: Optional[_ResvPlan] = None
        quota_prev: Optional[_QuotaFastpathPreview] = None
        if self.reservations is not None:
            if batch is None:
                batch = [p for c in chunks for p in c]
            # TRUST the prepare-time plan when it was previewed against
            # exactly this chain (object identity — the worker's
            # resv_ctx was the same newest spec this dispatch chains
            # off, or both are fresh): re-running the match scan here
            # would triple the fast path's per-cycle cost, two of the
            # three on the pump thread. Safe: any state drift a stale
            # plan could hide is caught by the consume-time by-value
            # comparison — a wrong reuse costs a discard, never a
            # divergent decision.
            if prep_plan is not None and (
                (chained and prep_chain is carry)
                or (not chained and prep_chain is None)
            ):
                resv_plan = prep_plan
                quota_prev = prep_plan.quota_prev
            else:
                if q_real > 0:
                    if carried_ext is not None:
                        quota_prev = self._quota_fastpath_preview_chain(
                            carried_ext, chain_meta
                        )
                        if quota_prev is None:
                            return None
                    else:
                        # live rows + raw live runtime (NO refresh —
                        # purity): the values the consuming fast path
                        # reads unless its previous cycle left the
                        # manager dirty, in which case the prediction
                        # misses and the consume guard discards
                        quota_prev = self._quota_fastpath_preview_live()
                resv_plan = self._reservation_fastpath_preview(
                    batch,
                    base_view=carry.resv_view,
                    quota_prev=quota_prev,
                    chain_nodes=carry.nodes if chained else None,
                )
                if resv_plan is None:
                    return None
                plan_matches = (
                    prep_plan is not None
                    and prep_plan.binds == resv_plan.binds
                    and prep_plan.affinity_unsched
                    == resv_plan.affinity_unsched
                )
                if not plan_matches:
                    # the chain-aware preview disagrees with the
                    # prepare-time one (a different chain than the
                    # worker previewed against): re-chunk the remaining
                    # pods and lower inline — cold but correct
                    excluded = resv_plan.taken | set(
                        resv_plan.affinity_unsched
                    )
                    remaining = [
                        p for p in batch if p.meta.uid not in excluded
                    ]
                    eligible = self.pod_groups.begin_and_order(remaining)
                    chunks = self._chunks(eligible)
                    prepared = None
                    gang_view = self.pod_groups.gang_view(eligible)
            if not chunks:
                # every pod rides the fast path — nothing to solve, so
                # nothing worth speculating on
                return None
        all_pods = [p for c in chunks for p in c]
        # quota tables: pure preview (no manager mutation — the trailing
        # cycle's PostFilter still reads the live requests/runtime); the
        # used table is the device chain when one is carried, plus the
        # reservation preview's predicted fast-path charges
        quotas0 = None
        qmeta = None
        if q_real > 0:
            charged = quota_prev is not None and quota_prev.charged
            # the demand propagation's used term must be the POST-commit
            # (and post-fast-path) ledger the consuming cycle will see —
            # at a chained dispatch the host ledger is still pre-commit,
            # so the device carry's predicted rows stand in. Without
            # this the runtime preview diverges whenever consecutive
            # batches admit into the same leaf and every chained quota
            # speculation discards at validation.
            used_rows = None
            if charged:
                used_rows = quota_prev.used
            elif carried_ext is not None:
                used_rows = carried_ext[:q_real]
            by_leaf, _nonpre = self._quota_pending_demand(
                all_pods, used_rows=used_rows
            )
            runtime_ext, used_ext = self.quotas.preview_arrays_extended(
                by_leaf,
                self.quotas.effective_cluster_total(self.snapshot),
            )
            if charged:
                ext_host = (
                    carried_ext.copy()
                    if carried_ext is not None
                    else np.asarray(used_ext, np.float32).copy()
                )
                if ext_host.shape[0] < 2 * q_real:
                    return None
                off = ext_host.shape[0] // 2
                ext_host[:q_real] = quota_prev.used
                ext_host[off : off + q_real] = quota_prev.nonpre
                used0 = jnp.asarray(ext_host)
            elif carried_ext is not None:
                used0 = carry.quota_used
            else:
                used0 = jnp.asarray(used_ext)
            if tuple(used0.shape) != runtime_ext.shape:
                return None
            quotas0 = QuotaState(
                runtime=jnp.asarray(runtime_ext), used=used0
            )
            qmeta = _QuotaCarryMeta(
                used_in=used0,
                runtime_host=runtime_ext,
                tree_version=self.quotas.tree_version,
            )
        numa_state, device_state = self._constraint_states(None)
        nmeta = None
        numa_zone = None
        if numa_state is not None:
            numa_zone = carry.numa_zone
            if numa_zone is not None and tuple(numa_zone.shape) != tuple(
                numa_state.zone_free.shape
            ):
                return None
            # structural tables as HOST copies: the resident device
            # arrays are donation targets of the next dirty-row scatter
            # and must never be re-read at consume time
            zone_free_h, zone_cap_h, policy_h = self.numa.arrays()
            nmeta = _NumaCarryMeta(
                zone_in=(
                    numa_zone
                    if numa_zone is not None
                    else zone_free_h.copy()
                ),
                zone_cap=zone_cap_h.copy(),
                policy=policy_h.copy(),
                zone_most=self.numa.most_allocated_rows().copy(),
            )
        dmeta = None
        dev_carry = None
        if device_state is not None:
            has_rdma = device_state.rdma_free is not None
            has_fpga = device_state.fpga_free is not None
            if carry.dev is not None:
                slots_in, rdma_in, fpga_in = carry.dev
                if tuple(slots_in.shape) != tuple(
                    device_state.slot_free.shape
                ):
                    return None
                dev_carry = (slots_in, rdma_in, fpga_in)
            else:
                slots_in = self.devices.slot_array().copy()
                rdma_in = (
                    self.devices.rdma_array().copy() if has_rdma else None
                )
                fpga_in = (
                    self.devices.fpga_array().copy() if has_fpga else None
                )
            dmeta = _DevCarryMeta(
                slots_in=slots_in,
                rdma_in=rdma_in,
                fpga_in=fpga_in,
                cap=self.devices.cap_array().copy(),
                has_rdma=has_rdma,
                has_fpga=has_fpga,
            )
        cur = carry.nodes
        if resv_plan is not None and resv_plan.node_deltas:
            # predicted fast-path node charges (owner assumes, ghost
            # forget, remainder re-assume): the consuming cycle's serial
            # dispatch would lower node state AFTER the fast path ran
            cur = self._fold_resv_node_deltas(cur, resv_plan.node_deltas)
        qused = quotas0.used if quotas0 is not None else None
        out = []
        for k, chunk in enumerate(chunks):
            if prepared is not None:
                pods, rows, node_mask = prepared[k]
            else:
                pods, rows = self._lower_chunk(
                    chunk, stash=False, quarantine=quarantine
                )
                node_mask = self._node_constraint_mask(
                    chunk, pods.requests.shape[0], None
                )
            if self.mesh is not None:
                from ..parallel.sharded import shard_solver_inputs

                # chained mesh dispatch: pod rows onto dp, the mask onto
                # (dp, tp) — the chained node/constraint tables are
                # already sharded (they are solver outputs of the
                # previous sharded solve or the mesh-resident tables)
                (pods, _, _, _, _, node_mask, _, _) = shard_solver_inputs(
                    self.mesh, pods=pods, node_mask=node_mask
                )
            dp = self.devprof
            with self.extender.tracer.span(
                "assign", cat="scheduler", mode="chained", pods=len(chunk)
            ):
                with (
                    dp.watch(
                        "assign",
                        stage="overlap",
                        bucket=pods.requests.shape[0],
                        n=cur.allocatable.shape[0],
                        quotas=quotas0 is not None,
                        numa=numa_state is not None,
                        devices=device_state is not None,
                        mask=node_mask is not None,
                        carry=True,
                        numa_scoring=self._numa_scoring(),
                        device_scoring=self._device_scoring(),
                        max_rounds=self.max_rounds,
                        shortlist=self._shortlist_bucket(),
                    )
                    if dp is not None
                    else _NULL_WATCH
                ) as w:
                    result = assign(
                        pods,
                        cur,
                        self._params,
                        quotas=(
                            QuotaState(
                                runtime=quotas0.runtime, used=qused
                            )
                            if quotas0 is not None
                            else None
                        ),
                        numa=numa_state,
                        devices=device_state,
                        max_rounds=self.max_rounds,
                        approx_topk=True,
                        node_mask=node_mask,
                        dev_carry=dev_carry,
                        numa_carry=(
                            numa_zone if numa_state is not None else None
                        ),
                        numa_scoring=self._numa_scoring(),
                        device_scoring=self._device_scoring(),
                        shortlist_k=self._shortlist_bucket(),
                    )
                    w.result(result.assignment)
            # zero-copy chain replace (the solver outputs ARE the chained
            # state; allocatable/flags leaves stay aliased)
            cur = cur.replace(
                requested=result.node_requested,
                estimated_used=result.node_estimated_used,
                prod_used=result.node_prod_used,
            )
            if quotas0 is not None:
                qused = result.quota_used
            if device_state is not None:
                dev_carry = (
                    result.node_dev_slots,
                    result.node_rdma_free,
                    result.node_fpga_free,
                )
            if numa_state is not None:
                numa_zone = result.node_zone_free
            out.append((chunk, rows, result))
        chain_out = ChainCarry(
            nodes=cur,
            quota_used=qused,
            dev=dev_carry if device_state is not None else None,
            numa_zone=numa_zone if numa_state is not None else None,
            resv_view=resv_plan.view if resv_plan is not None else None,
        )
        resv_meta = (
            _ResvCarryMeta(
                binds=resv_plan.binds,
                affinity_unsched=resv_plan.affinity_unsched,
                pre_table=resv_plan.pre_table,
                post_table=resv_plan.post_table,
            )
            if resv_plan is not None
            else None
        )
        meta = CarryMeta(
            quota=qmeta,
            numa=nmeta,
            dev=dmeta,
            gangs=gang_view,
            resv=resv_meta,
            modes=self._carry_modes(),
        )
        return out, chain_out, meta

    def _numa_scoring(self):
        """NUMA-aligned Score strategy for the solver (static jit arg)."""
        if self.numa is not None and self.numa.has_topology:
            return self.numa.scoring_strategy
        return None

    def _device_scoring(self):
        """DeviceShare Score strategy for the solver (static jit arg)."""
        if self.devices is not None and self.devices.has_devices:
            return self.devices.scoring_strategy
        return None

    def _constraint_states(self, sub: Optional[np.ndarray] = None):
        """Lower the NUMA zone table and GPU slot table for the solver
        (None for whichever manager is absent/empty). Both uploads are
        versioned on their manager's lowered_version — an unchanged table
        re-uses the device-resident copy outright — and ``sub`` windows
        are gathered on device from the resident full-axis arrays."""
        numa_state = None
        if self.numa is not None and self.numa.has_topology:
            numa_state = self._resident_numa_state()
        device_state = None
        if self.devices is not None and self.devices.has_devices:
            device_state = self._resident_device_state()
        if sub is None or (numa_state is None and device_state is None):
            return numa_state, device_state
        reg = self.extender.registry
        b = bucket_size(len(sub), self.snapshot.config.min_bucket)
        key = (
            self.numa.lowered_version if numa_state is not None else None,
            self.devices.lowered_version if device_state is not None else None,
            b,
            sub.tobytes(),
            self.mesh,
        )
        cached = self._constraint_window_cache
        if cached is not None and cached[0] == key:
            reg.get("solver_state_cache_hits_total").labels(
                table="constraints_window"
            ).inc()
            return cached[1]
        idx = np.zeros((b,), np.int32)
        idx[: len(sub)] = sub
        valid = np.zeros((b,), bool)
        valid[: len(sub)] = True
        idx_d, valid_d = jnp.asarray(idx), jnp.asarray(valid)
        dp = self.devprof
        sharded = self.mesh is not None
        with self.extender.tracer.span(
            "snapshot:constraint_window_gather", cat="scheduler",
            window=len(sub),
        ):
            if numa_state is not None:
                if sharded:
                    numa_state = gather_rows_sharded(
                        self.mesh, numa_state, idx_d, valid_d,
                        devprof=dp, table="numa", window=b,
                    )
                else:
                    with (
                        dp.watch(
                            "gather_rows", stage="snapshot",
                            kind="transfer", table="numa", window=b,
                        )
                        if dp is not None
                        else _NULL_WATCH
                    ) as w:
                        numa_state = gather_rows(
                            numa_state, idx_d, valid_d
                        )
                        w.result(numa_state)
            if device_state is not None:
                if sharded:
                    device_state = gather_rows_sharded(
                        self.mesh, device_state, idx_d, valid_d,
                        devprof=dp, table="devices", window=b,
                    )
                else:
                    with (
                        dp.watch(
                            "gather_rows", stage="snapshot",
                            kind="transfer", table="devices", window=b,
                        )
                        if dp is not None
                        else _NULL_WATCH
                    ) as w:
                        device_state = gather_rows(
                            device_state, idx_d, valid_d
                        )
                        w.result(device_state)
        self._constraint_window_cache = (key, (numa_state, device_state))
        return numa_state, device_state

    def _resident_numa_state(self):
        """Device-resident full-axis NUMA zone table. An unchanged
        lowering re-uses the resident copy outright; a lowering whose
        only changes are per-node allocation deltas is refreshed by a
        jitted DIRTY-ROW SCATTER of just those rows (the managers track
        dirty node names — ROADMAP item b); only structural changes
        (shape growth, full rebuild, >50% dirty) pay a full re-upload."""
        from ..ops.numa import NumaState

        reg = self.extender.registry
        zone_free, zone_cap, policy = self.numa.arrays()
        most = self.numa.most_allocated_rows()
        key = (self.numa.lowered_version, zone_free.shape, self.mesh)
        cached = self._numa_dev_cache
        if cached is not None and cached[0] == key:
            reg.get("solver_state_cache_hits_total").labels(
                table="numa"
            ).inc()
            return cached[1]
        n_bucket = zone_free.shape[0]
        if cached is not None and cached[0][1:] == key[1:]:
            rows = self.numa.drain_lowered_dirty()
            if rows is not None and 0 < len(rows) <= n_bucket // 2:
                state = self._scatter_refresh(
                    cached[1],
                    rows,
                    lambda idx: NumaState(
                        zone_free=jnp.asarray(zone_free[idx]),
                        zone_cap=jnp.asarray(zone_cap[idx]),
                        policy=jnp.asarray(policy[idx]),
                        zone_most=jnp.asarray(most[idx]),
                    ),
                    "snapshot:numa_scatter",
                    "numa",
                )
                self._numa_dev_cache = (key, state)
                return state
        else:
            # first build or shape change: stale marks are meaningless
            self.numa.drain_lowered_dirty()
        with self.extender.tracer.span(
            "snapshot:numa_lower", cat="scheduler",
            uploaded=zone_free.shape[0],
        ):
            state = NumaState(
                zone_free=jnp.asarray(zone_free),
                zone_cap=jnp.asarray(zone_cap),
                policy=jnp.asarray(policy),
                zone_most=jnp.asarray(most),
            )
            if self.mesh is not None:
                from ..parallel.sharded import put_resident

                state = put_resident(self.mesh, state)
        reg.get("solver_h2d_rows_total").inc(float(zone_free.shape[0]))
        self._numa_dev_cache = (key, state)
        return state

    def _resident_device_state(self):
        """Device-resident full-axis GPU slot table (+ RDMA/FPGA counts).
        Same refresh ladder as the NUMA table: resident re-use →
        dirty-row scatter of just the allocation-touched rows (ROADMAP
        item b) → full re-upload only on structural change."""
        from ..ops.device import DeviceState

        reg = self.extender.registry
        slots = self.devices.slot_array()
        # GPU-only clusters trace the RDMA/FPGA feasibility, carry
        # and prefix checks OUT of the solver entirely (None pytree
        # leaves are static structure)
        has_rdma = self.devices.has_rdma
        has_fpga = self.devices.has_fpga
        key = (
            self.devices.lowered_version,
            slots.shape,
            has_rdma,
            has_fpga,
            self.mesh,
        )
        cached = self._device_dev_cache
        if cached is not None and cached[0] == key:
            reg.get("solver_state_cache_hits_total").labels(
                table="device"
            ).inc()
            return cached[1]
        n_bucket = slots.shape[0]
        if cached is not None and cached[0][1:] == key[1:]:
            rows = self.devices.drain_lowered_dirty()
            if rows is not None and 0 < len(rows) <= n_bucket // 2:
                state = self._scatter_refresh(
                    cached[1],
                    rows,
                    lambda idx: DeviceState(
                        slot_free=jnp.asarray(slots[idx]),
                        rdma_free=(
                            jnp.asarray(self.devices.rdma_array()[idx])
                            if has_rdma
                            else None
                        ),
                        fpga_free=(
                            jnp.asarray(self.devices.fpga_array()[idx])
                            if has_fpga
                            else None
                        ),
                        cap_total=jnp.asarray(
                            self.devices.cap_array()[idx]
                        ),
                    ),
                    "snapshot:device_scatter",
                    "device",
                )
                self._device_dev_cache = (key, state)
                return state
        else:
            self.devices.drain_lowered_dirty()
        with self.extender.tracer.span(
            "snapshot:device_lower", cat="scheduler", uploaded=slots.shape[0]
        ):
            state = DeviceState(
                slot_free=jnp.asarray(slots),
                rdma_free=(
                    jnp.asarray(self.devices.rdma_array())
                    if has_rdma
                    else None
                ),
                fpga_free=(
                    jnp.asarray(self.devices.fpga_array())
                    if has_fpga
                    else None
                ),
                cap_total=jnp.asarray(self.devices.cap_array()),
            )
            if self.mesh is not None:
                from ..parallel.sharded import put_resident

                state = put_resident(self.mesh, state)
        reg.get("solver_h2d_rows_total").inc(float(slots.shape[0]))
        self._device_dev_cache = (key, state)
        return state

    def solve(
        self, chunk: Sequence[Pod], sub: Optional[np.ndarray] = None
    ) -> SolveResult:
        pods = self.pod_batch(chunk)
        nodes = self.node_state(sub)
        # BeforeFilter analog: device-batch transformers.
        pods, nodes = self.extender.run_batch_transformers(pods, nodes)
        quotas = self.quota_state(chunk)
        numa_state, device_state = self._constraint_states(sub)
        node_mask = self._node_constraint_mask(
            chunk, pods.requests.shape[0], sub
        )
        if self.mesh is not None:
            from ..parallel.sharded import shard_solver_inputs

            # node/NUMA/device tables are already MESH-RESIDENT (placed
            # once at full lower, refreshed in place by the sharded
            # scatter) — only the per-cycle pod rows, mask and the tiny
            # replicated quota tables get placed here
            (
                pods,
                _,
                quotas,
                _,
                _,
                node_mask,
                _,
                _,
            ) = shard_solver_inputs(
                self.mesh,
                pods=pods,
                quotas=quotas,
                node_mask=node_mask,
            )
        dp = self.devprof
        with self.extender.tracer.span(
            "assign", cat="scheduler", pods=len(chunk)
        ):
            with (
                dp.watch(
                    "assign",
                    bucket=pods.requests.shape[0],
                    n=nodes.allocatable.shape[0],
                    quotas=quotas is not None,
                    numa=numa_state is not None,
                    devices=device_state is not None,
                    mask=node_mask is not None,
                    carry=False,
                    numa_scoring=self._numa_scoring(),
                    device_scoring=self._device_scoring(),
                    max_rounds=self.max_rounds,
                    shortlist=self._shortlist_bucket(),
                )
                if dp is not None
                else _NULL_WATCH
            ) as w:
                result = assign(
                    pods,
                    nodes,
                    self._params,
                    quotas=quotas,
                    numa=numa_state,
                    devices=device_state,
                    max_rounds=self.max_rounds,
                    cost_transform=self.extender.cost_transform,
                    # TPU-optimized partial top-k with the exact argmin
                    # pinned in slot 0 (see ops.solver) — same nominations
                    # contract, avoids lax.top_k's full variadic sort per
                    # round
                    approx_topk=True,
                    node_mask=node_mask,
                    numa_scoring=self._numa_scoring(),
                    device_scoring=self._device_scoring(),
                    shortlist_k=self._shortlist_bucket(),
                )
                w.result(result.assignment)
                return result

    def _node_constraint_mask(
        self,
        chunk: Sequence[Pod],
        p_bucket: int,
        sub: Optional[np.ndarray] = None,
    ):
        """[P, N] bool for pods carrying node constraints (nodeSelector /
        required nodeAffinity names / spec.nodeName — the upstream
        NodeAffinity+NodeName Filter plugins' semantics); None when no pod
        in the chunk has any, so the solver traces the mask out."""
        host = self._node_constraint_mask_host(chunk, p_bucket)
        if host is None:
            return None
        if sub is not None:
            # build over the full axis, then slice the sampled window
            b = bucket_size(len(sub), self.snapshot.config.min_bucket)
            out = np.zeros((p_bucket, b), bool)
            out[:, : len(sub)] = host[:, sub]
            return jnp.asarray(out)
        return jnp.asarray(host)

    def _node_constraint_mask_host(
        self, chunk: Sequence[Pod], p_bucket: int
    ) -> Optional[np.ndarray]:
        """Host build of the constraint mask, vectorized over the node
        axis: each constrained pod's row is an AND of cached label→row
        bitmaps (plus a name scatter for nodeName/affinity lists) from the
        snapshot's inverted index — the former per-pod × per-node label
        walk was the constrained scenarios' dominant lowering cost."""
        specs = [p.spec for p in chunk]
        if not any(
            s.node_selector or s.affinity_required_nodes or s.node_name
            for s in specs
        ):
            return None
        snap = self.snapshot
        n_bucket = snap.nodes.allocatable.shape[0]
        mask = np.ones((p_bucket, n_bucket), bool)
        with self.extender.tracer.span(
            "lower:node_mask", cat="scheduler", pods=len(chunk)
        ):
            for i, spec in enumerate(specs):
                if not (
                    spec.node_selector
                    or spec.affinity_required_nodes
                    or spec.node_name
                ):
                    continue
                mask[i] = snap.constraint_row(
                    node_name=spec.node_name,
                    affinity_names=spec.affinity_required_nodes,
                    selector=spec.node_selector,
                )
        return mask

    def quota_state(self, chunk: Sequence[Pod]) -> Optional[QuotaState]:
        """Lowered QuotaState, or None when no quota tree exists (the solver
        traces the quota passes out entirely)."""
        host = self._quota_host_arrays(chunk)
        if host is None:
            return None
        runtime, used = host
        reg = self.extender.registry
        key = (self.quotas.state_version, runtime.shape)
        cached = self._quota_dev_cache
        if cached is not None and cached[0] == key:
            reg.get("solver_state_cache_hits_total").labels(
                table="quota"
            ).inc()
            return cached[1]
        if runtime.shape[0] == 1:
            # pad: Q == 1 is reserved as the disabled sentinel
            pad = np.zeros((1, runtime.shape[1]), np.float32)
            runtime = np.concatenate([runtime, pad])
            used = np.concatenate([used, pad])
        with self.extender.tracer.span(
            "snapshot:quota_lower", cat="scheduler", quotas=runtime.shape[0]
        ):
            state = QuotaState(
                runtime=jnp.asarray(runtime), used=jnp.asarray(used)
            )
        self._quota_dev_cache = (key, state)
        return state

    def _quota_pending_demand(
        self, chunk: Sequence[Pod], used_rows: Optional[np.ndarray] = None
    ):
        """PURE per-leaf demand of this chunk: ``(by_leaf, np_by_leaf)``
        request-vector sums (pending + already-admitted used per leaf) —
        the inputs of the demand propagation, computed without touching
        the manager. Shared by the real mutating refresh
        (:meth:`_quota_host_arrays`) and the pipeline's speculative
        PREVIEW (open-the-gates PR: the dispatch must not overwrite the
        requests/runtime the trailing cycle's PostFilter still reads).
        ``used_rows`` substitutes the admitted-used table ([≥Q, D]; the
        chained dispatch passes the device carry's PREDICTED post-commit
        rows, since the live host ledger is still pre-commit there)."""
        from .plugins.elasticquota import (
            is_pod_non_preemptible,
            quota_name_of,
        )

        used_src = used_rows if used_rows is not None else self.quotas.used
        # Request vectors memoize on the request dict's items — clusters
        # have few distinct pod shapes, and the per-pod res_vector walk
        # was a visible slice of large quota batches.
        by_leaf: Dict[str, np.ndarray] = {}
        vec_cache: Dict[tuple, np.ndarray] = {}
        res_vector = self.snapshot.config.res_vector
        for pod in chunk:
            leaf = quota_name_of(pod)
            if leaf is None:
                continue
            key = tuple(pod.spec.requests.items())
            vec = vec_cache.get(key)
            if vec is None:
                vec = res_vector(pod.spec.requests)
                vec_cache[key] = vec
            acc = by_leaf.get(leaf)
            by_leaf[leaf] = vec.copy() if acc is None else acc + vec
        for leaf in list(by_leaf):
            idx = self.quotas.index_of(leaf)
            if idx is not None and idx < used_src.shape[0]:
                by_leaf[leaf] = by_leaf[leaf] + used_src[idx]
        # non-preemptible demand ledger for status stamping (leaf-level)
        np_by_leaf: Dict[str, np.ndarray] = {}
        for pod in chunk:
            if not is_pod_non_preemptible(pod):
                continue
            leaf = quota_name_of(pod)
            if leaf is None:
                continue
            vec = res_vector(pod.spec.requests)
            acc = np_by_leaf.get(leaf)
            np_by_leaf[leaf] = vec.copy() if acc is None else acc + vec
        return by_leaf, np_by_leaf

    def _quota_host_arrays(self, chunk: Sequence[Pod]):
        """Host-side quota refresh shared by the device lowering and the
        host reference path: propagates this chunk's demand up the tree,
        refreshes runtime, and returns the extended ``(runtime, used)``
        numpy tables (None when no quota tree exists) — no device work."""
        if self.quotas.quota_count == 0:
            return None
        # The fair-sharing budget is the live cluster capacity (without it
        # water-fill degenerates to min(min, request) and admission sticks
        # at the guaranteed tier).
        self.quotas.sync_cluster_total(self.snapshot)
        by_leaf, np_by_leaf = self._quota_pending_demand(chunk)
        self.quotas.set_leaf_requests(by_leaf)
        if np_by_leaf or self.quotas.nonpre_requests.any():
            self.quotas._ensure_capacity()
            # request = admitted non-preemptible usage everywhere, plus
            # this cycle's pending demand per leaf — request must stay a
            # superset of used even for quotas with nothing pending now
            self.quotas.nonpre_requests[:] = self.quotas.nonpre_used
            for leaf, vec in np_by_leaf.items():
                idx = self.quotas.index_of(leaf)
                if idx is not None and idx < self.quotas.nonpre_requests.shape[0]:
                    self.quotas.nonpre_requests[idx] += vec
        return self.quotas.quota_arrays_extended()

    def _estimate_of(self, pod: Pod) -> np.ndarray:
        """One estimate per pod everywhere — solver gating, Reserve commit
        and reservation fast path must charge the same number, or a pod
        admitted on its measured estimate gets re-charged at the ~5x
        larger scaled request."""
        if pod.spec.estimated:
            return self.snapshot.config.res_vector(pod.spec.estimated)
        from ..ops.estimator import estimate_pod

        return estimate_pod(self.snapshot.config, pod, self._scales)

    # ---- HA: commit-boundary fencing + write-ahead journal helpers ----

    def _maybe_compact_journal(self) -> None:
        """Threshold-gated journal compaction after a clean cycle. A
        failure — including the ``journal.compact_crash`` chaos point's
        simulated mid-rewrite death — is reported and swallowed: the
        live log is intact by construction (tmp-file + atomic rename),
        so a failed compaction only defers maintenance."""
        jnl = self.bind_journal
        if jnl is None or (
            self.journal_compact_records is None
            and self.journal_compact_bytes is None
        ):
            return
        if self.fence is not None and self._fence_epoch < 0:
            return  # revoked: maintenance is the current leader's job
        try:
            rep = jnl.maybe_compact(
                epoch=(
                    self._fence_epoch if self.fence is not None else None
                ),
                min_records=(
                    self.journal_compact_records
                    if self.journal_compact_records is not None
                    else (1 << 62)
                ),
                min_bytes=self.journal_compact_bytes,
            )
        except (JournalWriteError, StaleEpochError) as exc:
            report_exception(
                "scheduler.journal.compact",
                exc,
                registry=self.extender.registry,
            )
            return
        if rep is not None:
            self.extender.registry.get("journal_compactions_total").inc()
            if self.on_journal_compacted is not None:
                try:
                    self.on_journal_compacted()
                except JournalWriteError as exc:
                    # same contract as a failed compaction: the live
                    # claim log is intact, maintenance just deferred
                    report_exception(
                        "scheduler.journal.claim_gc",
                        exc,
                        registry=self.extender.registry,
                    )

    def _fence_stale(self) -> Optional[str]:
        """None when this scheduler's leadership grant is current (or no
        fence is wired); otherwise a human-readable staleness detail.
        The ``leader.stale_commit`` chaos point deterministically forces
        the stale verdict for tests/soak."""
        if self.chaos.fire("leader.stale_commit"):
            return "injected"
        if self.fence is None:
            return None
        try:
            self.fence.check(self._fence_epoch)
        except StaleEpochError as exc:
            return str(exc)
        return None

    def _journal_bind_entries(
        self, bound: Sequence[Tuple[Pod, str]]
    ) -> List[dict]:
        """Serialize the EXACT charge each bound pod holds in the
        snapshot (post-amplification request, estimate, prod band,
        bind-nominal CPU) so a replay re-installs it bit-identically via
        ``restore_assumed``."""
        from .plugins.elasticquota import quota_name_of

        entries: List[dict] = []
        assumed = self.snapshot._assumed
        for pod, node in bound:
            ap = assumed.get(pod.meta.uid)
            if ap is None:  # defensive: permit raced a forget
                continue
            entry = {
                "uid": pod.meta.uid,
                "node": node,
                "req": [float(x) for x in ap.request],
                "est": [float(x) for x in ap.estimate],
                "prod": bool(ap.is_prod),
                "nom": float(ap.bind_nominal_cpu),
                "conf": bool(ap.confirmed),
                # leaf quota (None = unlabeled): recovery re-charges
                # the quota chain for replayed entries without
                # needing the pod object back
                "quota": quota_name_of(pod),
            }
            # exact NUMA zone / device-slot holds (PR 6 satellite): a
            # replay restores the CHOSEN zone, cpuset and minors
            # bit-exactly — a re-lower can rebuild capacity totals but
            # not which slots were picked
            if self.numa is not None:
                numa_hold = self.numa.hold_of(pod.meta.uid, node)
                if numa_hold:
                    entry["numa"] = numa_hold
            if self.devices is not None:
                dev_hold = self.devices.hold_of(pod.meta.uid, node)
                if dev_hold:
                    entry["dev"] = dev_hold
            # fleet-tracing PR: the pod's compact lifecycle context rides
            # in the durable bind record, so a takeover's replay can
            # bridge the timeline across the dead incarnation with the
            # ORIGINAL submit stamp (obs.lifecycle.PodLifecycle.context)
            if self.lifecycle is not None:
                ctx = self.lifecycle.context(pod.meta.uid)
                if ctx is not None:
                    entry["lc"] = ctx
            entries.append(entry)
        return entries

    def _reject_chunk_journal(
        self, chunk: Sequence[Pod], exc: BaseException
    ) -> Tuple[List[Tuple[Pod, str]], List[Pod]]:
        """A journal append was refused before any mutation: reject the
        chunk (pods retry next cycle) and surface the failure."""
        reg = self.extender.registry
        report_exception("scheduler.journal", exc, registry=reg)
        self._cycle_journal_failed = True
        self._cycle_reserve_rejected = True
        self.extender.health.set(
            "commit", False, f"journal write refused: {exc!r}"
        )
        for pod in chunk:
            self._reserve_reject[pod.meta.uid] = (
                RejectStage.RESERVE,
                "journal",
                RejectReason.JOURNAL_WRITE_FAILED,
            )
        return [], list(chunk)

    def _commit(
        self,
        chunk: Sequence[Pod],
        assignment: np.ndarray,
        rows: Optional[LoweredRows] = None,
        pod_zone: Optional[np.ndarray] = None,
    ) -> Tuple[List[Tuple[Pod, str]], List[Pod]]:
        """Host-side Reserve: revalidate each nomination against live numpy
        state (the reference's Reserve mutates the scheduler cache the same
        way, ``framework_extender.go:546``). ``rows`` is the lowering for
        this chunk (the pipelined path captures it per chunk); when omitted
        the last ``pod_batch`` stash is used, guarded by a uid check.

        One batched Reserve path (``_reserve_batch``): capacity admission
        and assume charges are vectorized for every winner; only winners
        that genuinely need exact per-pod state — a NUMA zone/cpuset or
        concrete device minors — run a lean per-winner loop over the
        pre-lowered rows (the fat per-pod loop was the dominant host cost
        of the NUMA/device scenarios, VERDICT r2 #1)."""
        from .prebind import DefaultPreBind

        tr = self.extender.tracer
        self._reserve_reject = {}
        na = self.snapshot.nodes
        prebind = DefaultPreBind()
        if rows is None:
            if self._lowered.uids != tuple(p.meta.uid for p in chunk):
                raise RuntimeError(
                    "_commit called with a chunk that does not match the "
                    "last pod_batch lowering — solve() and _commit() must "
                    "run on the same chunk"
                )
            rows = self._lowered
        cpu_dim = self.snapshot._cpu_dim
        # vectorized amplified admission rows: what assume will charge
        # (bound pods' CPU ×ratio on amplified nodes)
        n_chunk = len(chunk)
        amp_col = na.cpu_amp[np.clip(assignment[:n_chunk], 0, None)]
        factor = np.where(
            rows.bind[:n_chunk] & (amp_col > 1.0), amp_col, 1.0
        )
        check_rows = rows.req
        if np.any(factor != 1.0):
            check_rows = rows.req.copy()
            check_rows[:n_chunk, cpu_dim] *= factor

        # HA fencing (failover PR): a deposed leader's in-flight commit —
        # including a CyclePipeline trailing commit whose solve was
        # dispatched before leadership was lost — must be REJECTED here,
        # at the last host boundary before the snapshot mutates, not
        # double-placed. The ``leader.stale_commit`` chaos point forces
        # the stale verdict deterministically.
        fence_detail = self._fence_stale()
        if fence_detail is not None:
            reg = self.extender.registry
            reg.get("leader_fenced_commits_total").inc()
            report_exception(
                "scheduler.commit.fenced",
                StaleEpochError(self._fence_epoch, -1)
                if fence_detail == "injected"
                else RuntimeError(fence_detail),
                registry=reg,
            )
            self.extender.health.set(
                "leader",
                True,
                f"commit fenced (stale epoch {self._fence_epoch}): "
                f"{fence_detail}",
            )
            self._cycle_reserve_rejected = True
            self._cycle_fenced = True  # flight-recorder: fenced cycle
            for pod in chunk:
                self._reserve_reject[pod.meta.uid] = (
                    RejectStage.RESERVE,
                    "leaderfence",
                    RejectReason.STALE_LEADER_EPOCH,
                )
            return [], list(chunk)
        # write-ahead intent: journal BEFORE mutate. A chunk whose intent
        # cannot be durably recorded is rejected un-mutated (its pods
        # retry), so journal replay after a crash can never miss a
        # mutation it should have known about.
        cid = self.extender.current_cycle_id
        jnl = self.bind_journal
        if jnl is not None:
            n_chunk_j = len(chunk)
            planned = [
                (chunk[i].meta.uid, self.snapshot.node_name(int(a)))
                for i, a in enumerate(assignment[:n_chunk_j])
                if a >= 0
            ]
            try:
                jnl.append_intent(self._fence_epoch, cid, planned)
            except (JournalWriteError, StaleEpochError) as exc:
                return self._reject_chunk_journal(chunk, exc)
        # transactional Reserve: every mutation inside the try below is
        # journaled, so a failure anywhere between assume and Permit
        # (the classic crash-mid-commit window, injected via
        # ``commit.crash``) rolls the chunk back to its pre-commit state
        # instead of leaking half-assumed pods; the chunk's pods then
        # retry next cycle. The try deliberately ENDS at Permit: the
        # prebind/quota-charge stages below mutate durable ledgers the
        # journal does not record — absorbing their failures here would
        # roll back assumes while the quota charges stood, double-
        # charging on retry. Their failures propagate loudly instead.
        journal = _ReserveJournal()
        try:
            with tr.span("plugin:noderesources:reserve", cat="scheduler"):
                results = self._reserve_batch(
                    chunk, assignment, rows, check_rows, prebind,
                    pod_zone=pod_zone, journal=journal,
                )
            self.chaos.fire("commit.crash")
            # Permit: all-or-nothing over gangs; roll back assumes of
            # rejects. Bypassed outright when neither the chunk nor the
            # manager knows any gang — permit can then reject nothing.
            if rows.has_gangs or self.pod_groups.has_gangs:
                with tr.span("plugin:coscheduling:permit", cat="scheduler"):
                    bound, unsched = self.pod_groups.permit(results)
                bound_uids = {p.meta.uid for p, _ in bound}
                for pod, node in results:
                    if node is not None and pod.meta.uid not in bound_uids:
                        self._reserve_reject[pod.meta.uid] = (
                            RejectStage.PERMIT,
                            "coscheduling",
                            RejectReason.GANG_INCOMPLETE,
                        )
                        self.snapshot.forget_pod(pod.meta.uid)
                        prebind.discard(pod.meta.uid)
                        if self.numa is not None:
                            self.numa.release(pod.meta.uid, node)
                        if self.devices is not None:
                            self.devices.release(pod.meta.uid, node)
            else:
                bound = [(p, n) for p, n in results if n is not None]
                unsched = [p for p, n in results if n is None]
            # acknowledge: the bind record IS the durable acknowledgement
            # — a failure here (storage or injected) raises into the
            # rollback below, so a binding is never acked without its
            # journal record and never journaled without its charge.
            if jnl is not None and bound:
                jnl.append_bind(
                    self._fence_epoch,
                    cid,
                    self._journal_bind_entries(bound),
                )
        except Exception as exc:  # noqa: BLE001 — journal rollback
            journal.rollback(self)
            reg = self.extender.registry
            reg.get("commit_rollbacks_total").inc()
            report_exception("scheduler.commit", exc, registry=reg)
            self._cycle_commit_rolled_back = True
            self.extender.health.set(
                "commit", False, f"chunk rolled back: {exc!r}"
            )
            if jnl is not None:
                # void the intent so replay treats the chunk as never
                # applied (which, after the rollback above, it wasn't).
                # Best-effort: a failed abort write leaves an open intent,
                # which replay ALSO treats as not-applied.
                try:
                    jnl.append_abort(self._fence_epoch, cid, repr(exc))
                except (JournalWriteError, StaleEpochError):
                    pass
            for pod in chunk:
                self._reserve_reject[pod.meta.uid] = (
                    RejectStage.RESERVE,
                    "journal",
                    RejectReason.COMMIT_ROLLED_BACK,
                )
            return [], list(chunk)
        if self._reserve_reject:
            # a Reserve/Permit rejection means the solver's on-device
            # commit state over-counts vs the host — the speculative
            # chain (if any) is no longer exact
            self._cycle_reserve_rejected = True
        # terminal PreBind: one merged patch per admitted pod
        # (defaultprebind/plugin.go; rejected pods' patches evaporate).
        if prebind.has_patches:
            for pod, _node in bound:
                prebind.apply(pod)
        # Durable quota accounting + victim bookkeeping for what actually
        # bound. Chains are reused from the chunk lowering and charged in
        # one vectorized scatter (the per-pod name walk + chain charge
        # was a visible slice of the quota scenario's commit); the
        # per-pod record still feeds the overuse revoker / preemptor
        # victim selection.
        with tr.span("plugin:elasticquota:charge", cat="scheduler"):
            self._charge_bound_quotas(bound, rows)
        return bound, unsched

    def _charge_bound_quotas(
        self, bound: List[Tuple[Pod, str]], rows: LoweredRows
    ) -> None:
        from .plugins.elasticquota import quota_name_of

        bound_nodes = self._bound_nodes
        bound_pods = self._bound_pods
        if self.quotas.quota_count == 0:
            for pod, node in bound:
                bound_nodes[pod.meta.uid] = node
                bound_pods[pod.meta.uid] = pod
        elif rows.quota_chain is None:
            for pod, node in bound:
                bound_nodes[pod.meta.uid] = node
                bound_pods[pod.meta.uid] = pod
                leaf = quota_name_of(pod)
                if leaf is not None:
                    self.quotas.assign_pod(leaf, pod)
        else:
            uid_to_row = {u: i for i, u in enumerate(rows.uids)}
            quotas = self.quotas
            name_of = quotas.name_of_index
            b_rows: List[int] = []
            b_pods: List[Pod] = []
            for pod, node in bound:
                uid = pod.meta.uid
                bound_nodes[uid] = node
                bound_pods[uid] = pod
                row = uid_to_row.get(uid)
                if row is None:
                    # not from this chunk's lowering (defensive)
                    leaf = quota_name_of(pod)
                    if leaf is not None:
                        quotas.assign_pod(leaf, pod)
                    continue
                b_rows.append(row)
                b_pods.append(pod)
            if b_rows:
                idx = np.asarray(b_rows)
                chains = rows.quota_chain[idx]
                leaf_l = chains[:, 0].tolist()
                has = chains[:, 0] >= 0
                if has.any():
                    quotas.charge_rows(chains[has], rows.req[idx[has]])
                for k, pod in enumerate(b_pods):
                    li = leaf_l[k]
                    if li >= 0:
                        quotas.record_assigned(name_of(li), pod)

    def _reserve_batch(
        self,
        chunk: Sequence[Pod],
        assignment: np.ndarray,
        rows: LoweredRows,
        check_rows: np.ndarray,
        prebind: "DefaultPreBind",
        pod_zone: Optional[np.ndarray] = None,
        journal: Optional[_ReserveJournal] = None,
    ) -> List[Tuple[Pod, Optional[str]]]:
        """Batched Reserve for every winner (reference plugin.go:579-627
        semantics, host cost vectorized):

        1. per-node capacity admission via segmented prefix sums in commit
           order ((-priority, arrival) — identical to the old loop),
        2. a lean per-winner pass ONLY for winners needing exact state —
           a NUMA zone/cpuset (bind pods or single-numa-node policy) or
           concrete device minors — over pre-lowered row scalars,
        3. one bulk assume for all fresh winners; idempotent per-pod
           re-assume for pods already assumed (retry/re-schedule).

        A winner rejected in step 2 keeps its admission headroom reserved
        until the next cycle (conservative under-placement inside one
        chunk, never overcommit — the managers revalidate every pick)."""
        na = self.snapshot.nodes
        snap = self.snapshot
        n_chunk = len(chunk)
        assign_c = assignment[:n_chunk]
        # commit order: (-priority, arrival), matching the loop path
        order = np.lexsort((np.arange(n_chunk), -rows.prio[:n_chunk]))
        placed = order[assign_c[order] >= 0]
        accept = np.zeros(n_chunk, bool)
        if placed.size:
            nw = assign_c[placed]
            perm = np.argsort(nw, kind="stable")
            ws = placed[perm]           # chunk rows, grouped by node,
            ns = nw[perm]               # commit order inside each group
            crows = check_rows[ws]
            starts = np.r_[True, ns[1:] != ns[:-1]]
            cums = np.cumsum(crows, axis=0)
            pos = np.arange(len(ns))
            start_idx = np.maximum.accumulate(np.where(starts, pos, 0))
            base = np.where(
                (start_idx > 0)[:, None], cums[np.maximum(start_idx - 1, 0)], 0.0
            )
            seg = cums - base
            ok = na.schedulable[ns] & np.all(
                na.requested[ns] + seg <= na.allocatable[ns] + 1e-3, axis=1
            )
            if not ok.all():
                # a rejected pod inside a segment polluted later cumsums:
                # redo those nodes' pods sequentially (exact loop
                # semantics — later smaller pods may still fit)
                bad = np.unique(ns[~ok])
                for node_idx in bad:
                    sel = ns == node_idx
                    if not na.schedulable[node_idx]:
                        ok[sel] = False
                        continue
                    running = na.requested[node_idx].copy()
                    alloc = na.allocatable[node_idx]
                    for j in np.nonzero(sel)[0]:
                        fits = bool(
                            np.all(running + crows[j] <= alloc + 1e-3)
                        )
                        ok[j] = fits
                        if fits:
                            running += crows[j]
            accept[ws[ok]] = True
            if not ok.all():
                reject = self._reserve_reject
                for j in np.nonzero(~ok)[0].tolist():
                    reject[rows.uids[ws[j]]] = (
                        RejectStage.RESERVE,
                        "noderesources",
                        RejectReason.NODE_CAPACITY_REVALIDATION,
                    )

        # ---- step 2: winners needing exact NUMA/device assignment ----
        numa_mgr = (
            self.numa
            if self.numa is not None and self.numa.has_topology
            else None
        )
        dev_mgr = (
            self.devices
            if self.devices is not None and self.devices.has_devices
            else None
        )
        needs_numa = needs_dev = None
        if numa_mgr is not None:
            from ..core.topology import NUMAPolicy

            pol = numa_mgr.policy_rows()[np.clip(assign_c, 0, None)]
            needs_numa = accept & (pol >= 0) & (
                (pol == int(NUMAPolicy.SINGLE_NUMA_NODE))
                | rows.bind[:n_chunk]
            )
            if rows.numa_required is not None:
                # numa-topology-spec pods need exact zone assignment on
                # any registered node
                needs_numa |= accept & (pol >= 0) & rows.numa_required[:n_chunk]
        if dev_mgr is not None and rows.gpu_whole is not None:
            needs_dev = accept & (
                (rows.gpu_whole[:n_chunk] > 0)
                | (rows.gpu_share[:n_chunk] > 0)
                | (rows.rdma[:n_chunk] > 0)
                | (rows.fpga[:n_chunk] > 0)
            )
        held_numa = held_dev = None
        if needs_numa is not None or needs_dev is not None:
            constrained = np.zeros(n_chunk, bool)
            if needs_numa is not None:
                constrained |= needs_numa
            if needs_dev is not None:
                constrained |= needs_dev
            if constrained.any():
                held_numa = np.zeros(n_chunk, bool)
                held_dev = np.zeros(n_chunk, bool)
                cpu_dim = snap._cpu_dim
                mem_dim = snap._res_index.get(
                    ext.RES_MEMORY, min(1, len(snap.config.resources) - 1)
                )
                node_name_of = snap.node_name
                # one tolist per column: per-element numpy indexing inside
                # the loop is ~1µs each and dominated the lean loop
                con_l = constrained.tolist()
                assign_l = assign_c.tolist()
                cpu_l = rows.req[:n_chunk, cpu_dim].tolist()
                mem_l = rows.req[:n_chunk, mem_dim].tolist()
                bind_l = rows.bind[:n_chunk].tolist()
                numa_l = (
                    needs_numa.tolist() if needs_numa is not None else None
                )
                dev_l = needs_dev.tolist() if needs_dev is not None else None
                if dev_l is not None:
                    gw_l = rows.gpu_whole[:n_chunk].tolist()
                    gs_l = rows.gpu_share[:n_chunk].tolist()
                    rd_l = rows.rdma[:n_chunk].tolist()
                    fp_l = rows.fpga[:n_chunk].tolist()
                uids = rows.uids
                con_rows = [i for i in order.tolist() if con_l[i]]
                numa_payloads: Dict[int, str] = {}
                dev_payloads: Dict[int, str] = {}
                # NUMA winners commit as ONE batch (commit order is
                # preserved per node inside allocate_batch — cross-node
                # order is irrelevant, per-node state is independent);
                # synced=True semantics: _constraint_states → numa.arrays()
                # re-based every node's amp earlier this cycle
                if numa_l is not None:
                    numa_rows = [i for i in con_rows if numa_l[i]]
                    if numa_rows:
                        req_l = (
                            rows.numa_required[:n_chunk].tolist()
                            if rows.numa_required is not None
                            else None
                        )
                        zone_l = (
                            pod_zone.tolist()
                            if pod_zone is not None
                            else None
                        )
                        payloads = numa_mgr.allocate_batch(
                            [uids[i] for i in numa_rows],
                            [chunk[i].meta.annotations for i in numa_rows],
                            [node_name_of(assign_l[i]) for i in numa_rows],
                            [cpu_l[i] for i in numa_rows],
                            [mem_l[i] for i in numa_rows],
                            [bind_l[i] for i in numa_rows],
                            required=(
                                [req_l[i] for i in numa_rows]
                                if req_l is not None
                                else None
                            ),
                            zones_hint=(
                                [zone_l[i] for i in numa_rows]
                                if zone_l is not None
                                else None
                            ),
                        )
                        for i, payload in zip(numa_rows, payloads):
                            if payload is None:
                                accept[i] = False
                                self._reserve_reject[uids[i]] = (
                                    RejectStage.RESERVE,
                                    "nodenumaresource",
                                    RejectReason.NUMA_ALLOCATION_FAILED,
                                )
                            else:
                                held_numa[i] = True
                                if journal is not None:
                                    journal.numa_holds[uids[i]] = (
                                        node_name_of(assign_l[i])
                                    )
                                if payload:
                                    numa_payloads[i] = payload
                if dev_l is not None:
                    dev_rows = [
                        i for i in con_rows if dev_l[i] and accept[i]
                    ]
                    if dev_rows:
                        # the full request dict re-derives the per-dim GPU
                        # vector (core vs memory accounted independently)
                        # — only device winners pay it
                        payloads = dev_mgr.allocate_batch(
                            [uids[i] for i in dev_rows],
                            [chunk[i].meta.annotations for i in dev_rows],
                            [node_name_of(assign_l[i]) for i in dev_rows],
                            [gw_l[i] for i in dev_rows],
                            [gs_l[i] for i in dev_rows],
                            [rd_l[i] for i in dev_rows],
                            [fp_l[i] for i in dev_rows],
                            [chunk[i].spec.requests for i in dev_rows],
                        )
                        for i, dev_payload in zip(dev_rows, payloads):
                            if dev_payload is None:
                                if held_numa[i]:
                                    numa_mgr.release(
                                        uids[i], node_name_of(assign_l[i])
                                    )
                                    held_numa[i] = False
                                    if journal is not None:
                                        journal.numa_holds.pop(
                                            uids[i], None
                                        )
                                accept[i] = False
                                self._reserve_reject[uids[i]] = (
                                    RejectStage.RESERVE,
                                    "deviceshare",
                                    RejectReason.DEVICE_ALLOCATION_FAILED,
                                )
                                continue
                            held_dev[i] = True
                            if journal is not None:
                                journal.dev_holds[uids[i]] = node_name_of(
                                    assign_l[i]
                                )
                            if dev_payload:
                                dev_payloads[i] = dev_payload
                # annotation patches held back until Permit so a
                # rolled-back pod carries no stale placement claims
                if numa_payloads or dev_payloads:
                    for i in con_rows:
                        if not accept[i]:
                            continue
                        numa_payload = numa_payloads.get(i)
                        dev_payload = dev_payloads.get(i)
                        if not (numa_payload or dev_payload):
                            continue
                        patch: Dict[str, str] = {}
                        if numa_payload:
                            patch[ext.ANNOTATION_RESOURCE_STATUS] = (
                                numa_payload
                            )
                        if dev_payload:
                            # vendor device-plugin protocol
                            # (device_plugin_adapter.go). Per-winner
                            # timestamp: device plugins disambiguate
                            # same-node pods by it, so two winners must
                            # never share a value
                            patch[ext.ANNOTATION_DEVICE_ALLOCATED] = (
                                dev_payload
                            )
                            patch.update(
                                dev_mgr.adapter_annotations(
                                    node_name_of(assign_l[i]), uids[i]
                                )
                            )
                        prebind.stage_annotations(chunk[i], patch)

        # ---- step 3: assume — bulk for fresh, per-pod for re-assumes ----
        acc_rows = np.nonzero(accept)[0]
        fresh: List[int] = []
        for i in acc_rows.tolist():
            uid = rows.uids[i]
            if uid in snap._assumed:
                node_name = snap.node_name(int(assign_c[i]))
                # capture the PRIOR charge for the Reserve journal — a
                # mid-commit failure restores it bit-exactly
                prior = snap._assumed[uid]
                if not snap.assume_pod(
                    chunk[i],
                    node_name,
                    rows.est[i],
                    confirmed=False,
                    request=rows.req[i],
                    bind_nominal_cpu=(
                        float(rows.req[i, self.snapshot._cpu_dim])
                        if rows.bind[i]
                        else 0.0
                    ),
                ):
                    # node vanished between solve and Reserve (delete
                    # race): failed Reserve, roll back per-winner holds
                    accept[i] = False
                    self._reserve_reject[uid] = (
                        RejectStage.RESERVE,
                        "snapshot",
                        RejectReason.NODE_VANISHED,
                    )
                    if held_dev is not None and held_dev[i]:
                        dev_mgr.release(uid, node_name)
                    if held_numa is not None and held_numa[i]:
                        numa_mgr.release(uid, node_name)
                    if journal is not None:
                        journal.dev_holds.pop(uid, None)
                        journal.numa_holds.pop(uid, None)
                    prebind.discard(uid)
                elif journal is not None:
                    journal.reassumed.append((uid, prior))
            else:
                fresh.append(i)
        if fresh:
            f = np.asarray(fresh)
            bind_noms = np.where(
                rows.bind[f], rows.req[f, self.snapshot._cpu_dim], 0.0
            )
            snap.assume_pods_bulk(
                [chunk[i] for i in fresh],
                assign_c[f],
                check_rows[f],
                rows.est[f],
                rows.is_prod[f],
                bind_noms,
            )
            if journal is not None:
                journal.fresh.extend(rows.uids[i] for i in fresh)
        results: List[Tuple[Pod, Optional[str]]] = []
        node_name_of = snap.node_name
        accept_l = accept.tolist()
        assign_l2 = assign_c.tolist()
        for i in order.tolist():
            if accept_l[i]:
                results.append((chunk[i], node_name_of(assign_l2[i])))
            else:
                results.append((chunk[i], None))
        return results
