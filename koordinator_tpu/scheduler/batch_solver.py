"""BatchScheduler: snapshot → jitted solver → host-side Reserve commit.

The rebuild's analog of the reference's scheduling cycle
(``cmd/koord-scheduler/app/server.go:356-453`` setup + upstream
``scheduleOne``): instead of popping one pod at a time, pending pods are
drained in priority-bucketed batches, lowered to dense arrays, solved on TPU
(``ops.solver.assign``), and the nominations are committed host-side with
revalidation — the solver proposes, Reserve disposes (SURVEY §7 hard part
(a)). Rejected nominations simply stay pending for the next batch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..api import extension as ext
from ..api.types import Pod
from ..core.snapshot import ClusterSnapshot, SnapshotConfig, bucket_size
from ..ops import estimator
from ..ops.solver import NodeState, PodBatch, SolverParams, SolveResult, assign


@dataclasses.dataclass
class LoadAwareArgs:
    """LoadAwareScheduling plugin args (reference
    ``pkg/scheduler/apis/config/types.go`` ``LoadAwareSchedulingArgs``).

    Thresholds are percent of allocatable per resource name; 0/absent
    disables the check for that dim. ``estimator_scales`` mirrors
    DefaultEstimator's per-resource scaling factors.
    """

    usage_thresholds: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 65.0, ext.RES_MEMORY: 95.0}
    )
    prod_usage_thresholds: Mapping[str, float] = dataclasses.field(default_factory=dict)
    resource_weights: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 1.0, ext.RES_MEMORY: 1.0}
    )
    estimator_scales: Mapping[str, float] = dataclasses.field(default_factory=dict)
    node_metric_expiration_s: float = 180.0
    aggregated_usage_type: str = "p95"

    def solver_params(self, config: SnapshotConfig) -> SolverParams:
        res = config.resources

        def vec(table: Mapping[str, float], default: float = 0.0) -> jnp.ndarray:
            return jnp.asarray(
                [float(table.get(r, default)) for r in res], jnp.float32
            )

        return SolverParams(
            usage_thresholds=vec(self.usage_thresholds),
            prod_thresholds=vec(self.prod_usage_thresholds),
            score_weights=vec(self.resource_weights),
        )

    def scale_vector(self, config: SnapshotConfig) -> np.ndarray:
        return estimator.scale_vector(config.resources, self.estimator_scales)


@dataclasses.dataclass
class ScheduleOutcome:
    bound: List[Tuple[Pod, str]]
    unschedulable: List[Pod]
    rounds_used: int = 0


class BatchScheduler:
    """Drains pending pods through the TPU solver in fixed-shape batches."""

    def __init__(
        self,
        snapshot: Optional[ClusterSnapshot] = None,
        args: Optional[LoadAwareArgs] = None,
        batch_bucket: int = 4096,
        max_rounds: int = 16,
    ):
        self.snapshot = snapshot or ClusterSnapshot()
        self.args = args or LoadAwareArgs()
        # wire plugin args into metric ingest (agg percentile + expiry)
        self.snapshot.agg_type = self.args.aggregated_usage_type
        self.snapshot.metric_expiry_s = self.args.node_metric_expiration_s
        self.batch_bucket = batch_bucket
        self.max_rounds = max_rounds
        self._params = self.args.solver_params(self.snapshot.config)
        self._scales = self.args.scale_vector(self.snapshot.config)

    # ---- device lowering ----

    def node_state(self) -> NodeState:
        na = self.snapshot.nodes
        est_used = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
        return NodeState(
            allocatable=jnp.asarray(na.allocatable),
            requested=jnp.asarray(na.requested),
            estimated_used=jnp.asarray(est_used),
            prod_used=jnp.asarray(na.prod_usage + na.assigned_pending_prod),
            metric_fresh=jnp.asarray(na.metric_fresh),
            schedulable=jnp.asarray(na.schedulable),
        )

    def pod_batch(self, pods: Sequence[Pod], bucket: Optional[int] = None) -> PodBatch:
        arrays = self.snapshot.build_pods(list(pods))
        b = bucket or bucket_size(len(pods), self.snapshot.config.min_bucket)
        if arrays.requests.shape[0] != b:
            raise ValueError("pod bucket mismatch")
        est = arrays.requests * self._scales[None, :]
        is_prod = arrays.prio_class == int(ext.PriorityClass.PROD)
        return PodBatch(
            requests=jnp.asarray(arrays.requests),
            estimate=jnp.asarray(est),
            priority=jnp.asarray(arrays.priority),
            is_prod=jnp.asarray(is_prod),
            valid=jnp.asarray(arrays.valid),
            gang_id=jnp.asarray(arrays.gang_id),
        )

    # ---- scheduling cycle ----

    def schedule(self, pending: Sequence[Pod]) -> ScheduleOutcome:
        bound: List[Tuple[Pod, str]] = []
        unsched: List[Pod] = []
        rounds = 0
        for start in range(0, max(len(pending), 1), self.batch_bucket):
            chunk = list(pending[start : start + self.batch_bucket])
            if not chunk:
                break
            result = self.solve(chunk)
            rounds += int(result.rounds_used)
            b, u = self._commit(chunk, np.asarray(result.assignment))
            bound.extend(b)
            unsched.extend(u)
        return ScheduleOutcome(bound=bound, unschedulable=unsched, rounds_used=rounds)

    def solve(self, chunk: Sequence[Pod]) -> SolveResult:
        pods = self.pod_batch(chunk)
        nodes = self.node_state()
        return assign(pods, nodes, self._params, max_rounds=self.max_rounds)

    def _commit(
        self, chunk: Sequence[Pod], assignment: np.ndarray
    ) -> Tuple[List[Tuple[Pod, str]], List[Pod]]:
        """Host-side Reserve: revalidate each nomination against live numpy
        state (the reference's Reserve mutates the scheduler cache the same
        way, ``framework_extender.go:546``)."""
        na = self.snapshot.nodes
        bound: List[Tuple[Pod, str]] = []
        unsched: List[Pod] = []
        order = sorted(
            range(len(chunk)), key=lambda i: (-(chunk[i].spec.priority or 0), i)
        )
        for i in order:
            pod, node_idx = chunk[i], int(assignment[i])
            if node_idx < 0:
                unsched.append(pod)
                continue
            req = self.snapshot.config.res_vector(pod.spec.requests)
            if not bool(
                np.all(
                    na.requested[node_idx] + req
                    <= na.allocatable[node_idx] + 1e-3
                )
                and na.schedulable[node_idx]
            ):
                unsched.append(pod)
                continue
            est = req * self._scales
            self.snapshot.assume_pod(pod, self.snapshot.node_name(node_idx), est)
            bound.append((pod, self.snapshot.node_name(node_idx)))
        return bound, unsched
