"""DefaultPreBind: accumulated object mutations applied as one patch.

Rebuild of ``pkg/scheduler/plugins/defaultprebind/plugin.go`` +
``frameworkext/interface.go:221-224`` (ApplyPodMutation): during
Reserve/PreBind, plugins stage annotation/label mutations against a pod's
*pending patch* instead of writing the object; after Permit admits the
pod, the terminal PreBind applies everything as a single merged patch —
one apiserver PATCH in the reference, one in-place update here. Pods
rolled back by Permit never see their staged mutations.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from ..api.types import Pod


@dataclasses.dataclass
class PodPatch:
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)

    def merge(self, other: "PodPatch") -> None:
        self.annotations.update(other.annotations)
        self.labels.update(other.labels)

    @property
    def empty(self) -> bool:
        return not self.annotations and not self.labels


class DefaultPreBind:
    """Per-cycle mutation accumulator + terminal apply."""

    def __init__(self) -> None:
        self._patches: Dict[str, PodPatch] = {}
        self.applied_total = 0

    def stage_annotations(self, pod: Pod, annotations: Dict[str, str]) -> None:
        self._patches.setdefault(pod.meta.uid, PodPatch()).annotations.update(
            annotations
        )

    def stage_labels(self, pod: Pod, labels: Dict[str, str]) -> None:
        self._patches.setdefault(pod.meta.uid, PodPatch()).labels.update(labels)

    def discard(self, pod_uid: str) -> None:
        """Permit rejected the pod: staged mutations evaporate."""
        self._patches.pop(pod_uid, None)

    def apply(self, pod: Pod) -> bool:
        """Terminal PreBind for one admitted pod: one merged patch."""
        patch = self._patches.pop(pod.meta.uid, None)
        if patch is None or patch.empty:
            return False
        pod.meta.annotations.update(patch.annotations)
        pod.meta.labels.update(patch.labels)
        self.applied_total += 1
        return True

    def pending(self) -> List[str]:
        return list(self._patches)

    @property
    def has_patches(self) -> bool:
        """Whether anything was staged this cycle — lets the commit skip
        the per-pod terminal apply entirely on patch-free chunks."""
        return bool(self._patches)
