"""Framework extension machinery around the batch solver.

Rebuild of ``pkg/scheduler/frameworkext/`` — the reference's "framework of
the framework" that wraps every scheduling profile
(``frameworkext/interface.go:37-76``):

* **Transformer chain** (``interface.go:84-109``, impl
  ``framework_extender.go:222-315``): ``BeforePreFilter`` /
  ``BeforeFilter`` / ``BeforeScore`` hooks that may rewrite the pod or the
  cluster view before the built-in phases. Here the phases are tensor
  programs, so transformers rewrite host ``Pod`` objects before lowering
  (:meth:`FrameworkExtender.run_pre_batch_transformers`) or the lowered
  device batch/cost tensors (:meth:`run_batch_transformers`,
  :meth:`run_cost_transformers`).
* **SchedulerMonitor** (``scheduler_monitor.go:43-47,60+``): watchdog that
  records when each pod's scheduling attempt started; a sweep (default
  every 10 s) flags pods stuck longer than the 30 s timeout into the
  ``scheduling_timeout_total`` metric and the slow-pod log.
* **Error-handler dispatcher** (``errorhandler_dispatcher.go``, registered
  at ``app/server.go:439,450``): chained handlers intercept scheduling
  failures; the first handler returning True consumes the failure (the
  reference's reservation error handler works this way), otherwise the
  default handler records it.
* **Debug score/filter dump** (``frameworkext/debug.go:1-90``, flags at
  ``app/server.go:334-335``): per-batch top-N score tables and filter
  failure tallies, exposed over the services engine as
  ``/debug/flags/s``-style output.
* **Services engine** (``frameworkext/services/``): an HTTP server where
  plugins install handlers (``InstallAPIHandler``); serves ``/metrics``
  (Prometheus text), debug dumps, and per-plugin endpoints.
* **Scheduler metrics** (``pkg/scheduler/metrics/metrics.go:38-83``).

The NextPod hook (``interface.go:226-230``) lives in
``plugins.coscheduling.PodGroupManager.order_pending`` and the reservation
extension points in ``plugins.reservation`` — this module is the shared
spine they plug into.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.types import Pod
from ..obs import HealthRegistry, RejectionLog, Tracer, ensure_exceptions_counter
from ..utils.metrics import Registry

# ---------------------------------------------------------------------------
# Scheduler metrics (reference pkg/scheduler/metrics/metrics.go:38-83)
# ---------------------------------------------------------------------------


def scheduler_registry(reg: Optional[Registry] = None) -> Registry:
    """Create (or populate a caller-supplied) registry with the scheduler
    metric set — callers passing their own Registry still get every metric
    the batch cycle touches."""
    reg = reg or Registry(namespace="koord_scheduler")
    reg.counter(
        "scheduling_timeout_total",
        "pods whose scheduling attempt exceeded the monitor timeout",
    )
    reg.histogram(
        "elastic_quota_process_latency_seconds",
        "latency of elastic-quota admission passes",
    )
    reg.gauge(
        "waiting_gang_group_number",
        "gang groups currently gated before the solver",
    )
    reg.histogram(
        "solver_batch_latency_seconds",
        "device latency of one solver batch",
    )
    reg.counter("scheduled_pods_total", "pods bound by the batch scheduler")
    reg.counter("unschedulable_pods_total", "pods left unschedulable")
    reg.histogram(
        "cycle_latency_seconds",
        "wall time of one scheduling cycle",
    )
    reg.histogram(
        "stage_latency_seconds",
        "wall time per scheduling-cycle stage",
        labels=("stage",),
    )
    reg.counter(
        "rejections_total",
        "pods rejected, attributed to the killing stage/plugin/reason",
        labels=("stage", "plugin", "reason"),
    )
    reg.counter(
        "solver_h2d_rows_total",
        "node-axis rows uploaded to device for solver state (full "
        "re-lowers plus dirty-row scatters plus table uploads)",
    )
    reg.counter(
        "solver_state_cache_hits_total",
        "solver state lowerings served from the device-resident cache "
        "without a host re-lower/upload",
        labels=("table",),
    )
    # robustness PR: fault-injection + hardening visibility
    reg.counter(
        "fault_injected_total",
        "faults injected by the chaos layer, per named point",
        labels=("point",),
    )
    reg.counter(
        "solver_fallback_total",
        "solver dispatch failures, labeled by the ladder level fallen to "
        "(1 = per-chunk, 2 = host numpy reference)",
        labels=("level",),
    )
    reg.counter(
        "solver_shortlist_fallback_total",
        "solver rounds where the candidate-shortlist exactness bound could "
        "not prove the pruned node axis decision-identical and the round "
        "re-nominated over the full axis (cause: bound = a gathered best "
        "cost reached the plan-time bound; infeasible = a gated pod had no "
        "feasible shortlist candidate left)",
        labels=("cause",),
    )
    reg.counter(
        "cycle_deadline_exceeded_total",
        "scheduling cycles that hit the per-cycle deadline and deferred "
        "their remaining chunks to the next cycle",
    )
    reg.counter(
        "retry_attempts_total",
        "retries performed by shared RetryPolicy call sites",
        labels=("site",),
    )
    reg.counter(
        "commit_rollbacks_total",
        "chunk commits rolled back by the transactional Reserve journal",
    )
    # perf PR 4: cross-cycle solve pipelining + resident PodBatch interning
    reg.counter(
        "pod_intern_hits_total",
        "pod rows served from the interned (uid, spec-hash) lowering "
        "cache instead of a fresh per-pod parse",
    )
    reg.counter(
        "pipeline_speculation_total",
        "speculatively dispatched cross-cycle solves, by consume outcome",
        labels=("outcome",),
    )
    reg.counter(
        "pipeline_prepare_stalls_total",
        "prepare-worker stalls/deaths that degraded a pipelined cycle "
        "to the serial path",
    )
    # distributed-observability PR: gate introspection — which named
    # _gates_ok gate kept a cycle serial (the evidence base the "open
    # the speculation gates" roadmap item works from)
    reg.counter(
        "pipeline_gate_closed_total",
        "pipelined cycles forced serial, attributed to the specific "
        "closed speculation gate (one increment per closed gate per "
        "gated cycle)",
        labels=("gate",),
    )
    reg.gauge(
        "solver_pipeline_depth",
        "overlapped pipeline stages in flight at the last pump return "
        "(0 = idle; each in-flight batch counts 1 plus 1 more when its "
        "speculative solve is on device — depth>1 pipelining holds "
        "several)",
    )
    # open-the-gates PR: carried quota/NUMA/device/gang state validation
    reg.counter(
        "pipeline_carry_mismatch_total",
        "speculations discarded by consume-time carry validation, "
        "attributed to the diverging table (host/device divergence, a "
        "mid-pipeline subsystem arrival, or the pipeline.carry_mismatch "
        "chaos point)",
        labels=("table",),
    )
    reg.gauge(
        "claim_tombstones_live",
        "settled (tombstoned) uids currently retained by the cross-"
        "shard ClaimTable, sampled after each tombstone GC sweep",
    )
    # HA PR: fenced leader failover + write-ahead bind journal
    reg.counter(
        "leader_fenced_commits_total",
        "chunk commits rejected by the leadership fence (a deposed "
        "leader's in-flight commit, or an injected stale epoch)",
    )
    reg.counter(
        "leader_transitions_total",
        "leadership grants observed by this scheduler (takeovers and "
        "re-elections; renews do not count)",
    )
    reg.gauge(
        "leader_epoch",
        "fencing epoch of the current leadership grant "
        "(-1 = revoked/standby)",
    )
    reg.counter(
        "journal_writes_total",
        "write-ahead bind-journal records appended, by op",
        labels=("op",),
    )
    reg.counter(
        "journal_write_failures_total",
        "bind-journal appends refused (storage failure, injected "
        "journal.write_fail, or a stale-epoch write)",
    )
    reg.counter(
        "recovery_replayed_total",
        "assumed/bound charges re-installed from the bind journal on "
        "warm-standby takeover or crash restart",
    )
    reg.counter(
        "journal_compactions_total",
        "run-loop journal compactions (threshold-gated checkpoint "
        "rewrites; failed/crashed attempts are NOT counted — the live "
        "log is intact and the next threshold retries)",
    )
    # state-integrity PR: checksummed journals, verified checkpoints,
    # resident-state anti-entropy scrubbing
    reg.counter(
        "journal_corrupt_records_total",
        "journal-store records quarantined by load-time CRC/seq "
        "screening (media corruption or write holes; a torn final "
        "line is an unacknowledged append, not corruption), per store",
        labels=("store",),
    )
    reg.counter(
        "recovery_checkpoint_fallback_total",
        "recoveries that fell back to a full-history journal replay "
        "because a checkpoint recovery image failed its digest check "
        "(or the checkpoint.digest_mismatch chaos point forced it)",
    )
    reg.counter(
        "resident_scrub_rows_total",
        "device-resident rows audited by the anti-entropy scrubber's "
        "rotating window (re-lowered from host truth and compared "
        "bit-exact)",
    )
    reg.counter(
        "resident_scrub_divergence_total",
        "resident rows found diverged from host truth by the scrubber "
        "and self-healed through the dirty-row scatter, per table",
        labels=("table",),
    )
    # overload-control PR: QoS-aware admission + brownout ladder +
    # solver-channel circuit breaker
    reg.counter(
        "overload_shed_total",
        "queued/arriving pods shed by the QoS-aware admission "
        "controller (terminal: a shed pod leaves a resubmit ticket), "
        "per priority band",
        labels=("band",),
    )
    reg.counter(
        "overload_deferred_total",
        "pod arrivals parked by QoS-aware admission (band over its "
        "queue budget, or the brownout ladder defers the band)",
        labels=("band",),
    )
    reg.gauge(
        "brownout_level",
        "current brownout-ladder level (0 = normal … 4 = shed FREE)",
    )
    reg.counter(
        "brownout_transitions_total",
        "brownout-ladder level transitions, by direction",
        labels=("direction",),
    )
    reg.gauge(
        "solver_breaker_state",
        "snapshot-channel circuit-breaker state "
        "(0 = closed, 1 = open, 2 = half-open probe)",
    )
    reg.counter(
        "controller_decisions_total",
        "control-plane decisions recorded on the decision ledger, by "
        "controller and action",
        labels=("controller", "action"),
    )
    reg.counter(
        "shadow_divergence_total",
        "shadow-policy proposals that diverged from the acting "
        "controller's decision (shadows never act)",
        labels=("controller",),
    )
    reg.counter(
        "poison_quarantined_total",
        "pods blamed on the poison-quarantine ledger or rejected at the "
        "cycle gate because a live blame matched their spec fingerprint",
    )
    reg.counter(
        "poison_bisect_probes_total",
        "throwaway lowering probes run by the poison-batch bisection "
        "while isolating the minimal blame set",
    )
    reg.gauge(
        "snapshot_staleness_seconds",
        "age of the oldest undelivered informer event (0 when every "
        "watch is caught up; a connected-but-silent stall grows it)",
    )
    reg.counter(
        "stale_evidence_refusals_total",
        "evidence-hungry actions (preemption, descheduler eviction, "
        "topology split) refused because informer snapshots were stale",
        labels=("action",),
    )
    reg.counter(
        "crash_loop_backoffs_total",
        "boot backoffs imposed by the crash-loop governor after K rapid "
        "deaths within its horizon",
    )
    ensure_exceptions_counter(reg)
    return reg


# ---------------------------------------------------------------------------
# SchedulerMonitor (reference frameworkext/scheduler_monitor.go)
# ---------------------------------------------------------------------------


class SchedulerMonitor:
    """Watchdog over in-flight scheduling attempts.

    ``start_monitor(pod)`` when an attempt begins, ``complete(pod)`` when it
    ends (the reference wraps scheduleOne the same way); :meth:`sweep`
    (reference: every 10 s) counts attempts older than ``timeout_s``
    (reference: 30 s) into the timeout metric and returns them for logging.
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        period_s: float = 10.0,
        timeout_s: float = 30.0,
    ):
        self.period_s = period_s
        self.timeout_s = timeout_s
        self.registry = scheduler_registry(registry)
        self._inflight: Dict[str, Tuple[str, float]] = {}
        self._lock = threading.Lock()
        self._last_sweep = 0.0
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start_background(self) -> None:
        """Start the watchdog goroutine-analog: a daemon thread sweeping
        every ``period_s`` (the reference's 10 s wait.Until). The batch
        cycle is synchronous, so only a concurrent sweeper can flag a
        solver hang — in-flight pods whose attempt started > timeout ago."""
        if self._sweeper is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.period_s):
                for name in self.sweep():
                    print(f"koord-scheduler: pod {name} scheduling timeout")

        self._sweeper = threading.Thread(target=loop, daemon=True)
        self._sweeper.start()

    def stop_background(self) -> None:
        self._stop.set()
        self._sweeper = None

    def start_monitor(self, pod: Pod, now: Optional[float] = None) -> None:
        with self._lock:
            self._inflight[pod.meta.uid] = (
                pod.meta.name,
                time.monotonic() if now is None else now,
            )

    def complete(self, pod: Pod) -> None:
        with self._lock:
            self._inflight.pop(pod.meta.uid, None)

    def start_batch(self, pods: Sequence[Pod], now: Optional[float] = None) -> None:
        """One lock round for a whole cycle's admissions (the per-pod
        lock/dict pair was a visible slice of large batches)."""
        stamp = time.monotonic() if now is None else now
        with self._lock:
            inflight = self._inflight
            for pod in pods:
                inflight[pod.meta.uid] = (pod.meta.name, stamp)

    def complete_batch(self, pods: Sequence[Pod]) -> None:
        with self._lock:
            pop = self._inflight.pop
            for pod in pods:
                pop(pod.meta.uid, None)

    def sweep(self, now: Optional[float] = None) -> List[str]:
        """Returns names of timed-out pods; call at period_s cadence."""
        now = time.monotonic() if now is None else now
        if now - self._last_sweep < self.period_s:
            return []
        self._last_sweep = now
        timed_out = []
        with self._lock:
            for uid, (name, started) in list(self._inflight.items()):
                if now - started > self.timeout_s:
                    timed_out.append(name)
                    del self._inflight[uid]
        c = self.registry.get("scheduling_timeout_total")
        for _ in timed_out:
            c.inc()
        return timed_out


# ---------------------------------------------------------------------------
# Error-handler dispatcher (reference frameworkext/errorhandler_dispatcher.go)
# ---------------------------------------------------------------------------

ErrorHandler = Callable[[Pod, str], bool]


class ErrorHandlerDispatcher:
    """Chain of scheduling-failure interceptors.

    ``register_pre`` handlers run before the default handler; the first
    returning True consumes the failure (e.g. the reservation error handler
    re-queues the reserve pod instead of marking it failed). ``set_default``
    replaces the terminal handler.
    """

    def __init__(self, max_failures: int = 512):
        import collections

        self._pre: List[ErrorHandler] = []
        self._post: List[ErrorHandler] = []
        self._default: ErrorHandler = lambda pod, msg: False
        #: bounded recent-failure log (a standing set of unschedulable pods
        #: appends per cycle — same ring-buffer shape as the koordlet
        #: auditor)
        self.failures = collections.deque(maxlen=max_failures)

    def register_pre(self, handler: ErrorHandler) -> None:
        self._pre.append(handler)

    def register_post(self, handler: ErrorHandler) -> None:
        self._post.append(handler)

    def set_default(self, handler: ErrorHandler) -> None:
        self._default = handler

    def handle(self, pod: Pod, message: str) -> None:
        self.failures.append((pod.meta.name, message))
        for h in self._pre:
            if h(pod, message):
                return
        self._default(pod, message)
        for h in self._post:
            h(pod, message)


# ---------------------------------------------------------------------------
# Debug dumps (reference frameworkext/debug.go, /debug/flags/s|f)
# ---------------------------------------------------------------------------


@dataclass
class DebugScoresDumper:
    """Captures per-batch top-N nominations like the reference's score table
    (``debug.go:1-90``); enabled/size-controlled at runtime via the services
    engine (the reference's POST /debug/flags/s)."""

    top_n: int = 0  # 0 = disabled
    last_table: List[Dict[str, object]] = field(default_factory=list)

    def capture(
        self,
        pods: Sequence[Pod],
        node_names: Sequence[str],
        cost: np.ndarray,
        assignment: np.ndarray,
    ) -> None:
        if self.top_n <= 0 or cost.size == 0:
            return
        table: List[Dict[str, object]] = []
        k = min(self.top_n, cost.shape[1])
        for i, pod in enumerate(pods):
            row = cost[i]
            idx = np.argsort(row)[:k]
            table.append(
                {
                    "pod": pod.meta.name,
                    "assigned": (
                        node_names[assignment[i]] if assignment[i] >= 0 else ""
                    ),
                    "topScores": [
                        {"node": node_names[j], "cost": float(row[j])}
                        for j in idx
                        if np.isfinite(row[j])
                    ],
                }
            )
        self.last_table = table

    def render(self) -> str:
        return json.dumps(self.last_table, indent=1)


@dataclass
class DebugFiltersDumper:
    """Filter-failure tally per mask stage (reference logs which plugin
    filtered each node; the batched analog is a per-stage rejected-node
    count captured at solve time)."""

    enabled: bool = False
    last_tally: Dict[str, int] = field(default_factory=dict)

    def capture(self, stage_rejections: Dict[str, int]) -> None:
        if self.enabled:
            self.last_tally = dict(stage_rejections)

    def render(self) -> str:
        return json.dumps(self.last_tally, indent=1)


# ---------------------------------------------------------------------------
# Services engine (reference frameworkext/services/)
# ---------------------------------------------------------------------------


class ServicesEngine:
    """Plugin-installable HTTP API (reference gin engine,
    ``InstallAPIHandler`` at ``app/server.go:337``). Routes:
      /metrics               — Prometheus exposition
      /healthz               — per-subsystem degraded/ok aggregate (200/503)
      /trace                 — Chrome trace JSON (GET), sampling (POST)
      /slo                   — per-shard SLO state (targets, burn rates)
      /debug/scores          — last score table (GET), top-N (POST body int)
      /debug/filters         — filter tally
      /debug/rejections      — rejection records + per-stage tally
      /debug/pipeline        — speculation-gate introspection (which
                               named gate keeps this config serial)
      /debug/decisions       — controller decision ledger (inputs →
                               action → state per tick, crash-surviving)
      /debug/flightrecorder  — last-N per-cycle summaries (crash-
                               surviving black box)
      /debug/brownout        — brownout-ladder level, burn, transitions
      /debug/scrub           — anti-entropy scrubber state (cursor,
                               rows audited, divergences healed per
                               table, last window digests)
      /debug/compiles        — solver compile/retrace ledger (traces per
                               entry point, signature diffs, compile wall)
      /debug/profile         — solver observatory status; ?cycles=N arms
                               an on-demand device-timeline capture window
      /apis/v1/<plugin>/…    — handlers installed by plugins
    """

    def __init__(
        self,
        registry: Registry,
        scores: DebugScoresDumper,
        filters: DebugFiltersDumper,
        tracer: Optional[Tracer] = None,
        rejections: Optional[RejectionLog] = None,
        health: Optional[HealthRegistry] = None,
    ):
        self.registry = registry
        self.scores = scores
        self.filters = filters
        self.tracer = tracer or Tracer(enabled=False)
        self.rejections = rejections or RejectionLog()
        self.health = health
        #: wired post-construction by their owners: the SLO tracker
        #: (ShardedScheduler), the flight recorder (BatchScheduler.
        #: attach_flight_recorder) and the pipeline's gate-report
        #: callable (CyclePipeline) — None until then, and the routes
        #: answer accordingly
        self.slo = None
        self.flightrecorder = None
        self.decisions = None
        self.devprof = None
        #: brownout-ladder controller (overload-control PR) — wired by
        #: the stream/sharded scheduler when overload control is on
        self.brownout = None
        #: anti-entropy scrubber report callable (state-integrity PR) —
        #: wired by BatchScheduler when scrubbing is enabled
        self.scrub: Optional[Callable[[], Dict[str, object]]] = None
        self.gate_info: Optional[Callable[[], Dict[str, object]]] = None
        self._routes: Dict[str, Callable[[str], Tuple[int, str]]] = {}
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    def install(
        self, plugin: str, path: str, handler: Callable[[str], Tuple[int, str]]
    ) -> None:
        self._routes[f"/apis/v1/{plugin}{path}"] = handler

    def dispatch(self, method: str, path: str, body: str = "") -> Tuple[int, str]:
        path, _, query = path.partition("?")
        if path == "/metrics":
            return 200, self.registry.expose()
        if path == "/healthz":
            if self.health is None:
                return 200, json.dumps({"ok": True, "subsystems": {}})
            return (200 if self.health.ok() else 503), self.health.render()
        if path == "/trace":
            if method == "POST":
                flag = body.strip()
                if flag not in ("0", "1", "true", "false"):
                    return 400, "bad sampling flag (want 0/1/true/false)"
                self.tracer.enabled = flag in ("1", "true")
                if not self.tracer.enabled:
                    self.tracer.clear()
                return 200, str(self.tracer.enabled)
            doc = self.tracer.to_chrome_trace()
            if self.devprof is not None:
                # device-lane events from the observatory's capture
                # window merge under their host stage spans (same
                # monotonic clock, re-based on the tracer's epoch)
                self.devprof.extend_chrome(doc, self.tracer.epoch)
            return 200, json.dumps(doc)
        if path == "/slo":
            if self.slo is None:
                return 404, "no SLO tracker wired"
            return 200, self.slo.render()
        if path == "/debug/pipeline":
            if self.gate_info is None:
                return 200, json.dumps({"pipelined": False})
            return 200, json.dumps(self.gate_info(), indent=1)
        if path == "/debug/flightrecorder":
            if self.flightrecorder is None:
                return 404, "no flight recorder wired"
            return 200, self.flightrecorder.render()
        if path == "/debug/decisions":
            if self.decisions is None:
                return 404, "no decision ledger wired"
            return 200, self.decisions.render()
        if path == "/debug/brownout":
            if self.brownout is None:
                return 404, "no brownout controller wired"
            return 200, self.brownout.render()
        if path == "/debug/scrub":
            if self.scrub is None:
                return 404, "no resident-state scrubber wired"
            return 200, json.dumps(self.scrub(), indent=1)
        if path == "/debug/compiles":
            if self.devprof is None:
                return 404, "no solver observatory wired"
            return 200, self.devprof.ledger.render()
        if path == "/debug/profile":
            if self.devprof is None:
                return 404, "no solver observatory wired"
            # /debug/profile?cycles=N (or POST body N) arms an on-demand
            # capture window: the next N cycles run with fenced,
            # device-lane-recorded solver dispatches
            params = dict(
                kv.split("=", 1) for kv in query.split("&") if "=" in kv
            )
            raw = params.get("cycles", body.strip() if method == "POST" else "")
            if raw:
                try:
                    cycles = int(raw)
                except ValueError:
                    return 400, "bad cycles (want an integer)"
                return 200, json.dumps(
                    self.devprof.capture(cycles), indent=1
                )
            return 200, self.devprof.render()
        if path == "/debug/rejections":
            if method == "POST":
                return 405, "rejection log is read-only"
            return 200, self.rejections.render()
        if path == "/debug/scores":
            if method == "POST":
                try:
                    self.scores.top_n = int(body.strip() or "0")
                except ValueError:
                    return 400, "bad top-n"
                return 200, str(self.scores.top_n)
            return 200, self.scores.render()
        if path == "/debug/filters":
            if method == "POST":
                self.filters.enabled = body.strip() in ("1", "true")
                return 200, str(self.filters.enabled)
            return 200, self.filters.render()
        handler = self._routes.get(path)
        if handler is None:
            return 404, "not found"
        return handler(body)

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        engine = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _run(self, method: str):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length).decode() if length else ""
                code, text = engine.dispatch(method, self.path, body)
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def log_message(self, *args):
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


# ---------------------------------------------------------------------------
# Scheduling queue + scheduler adapter
# (reference frameworkext/scheduler_adapter.go:85-190)
# ---------------------------------------------------------------------------


class SchedulingQueue:
    """Active / backoff / unschedulable pools with the queue operations the
    reference adapter exposes to plugins: ``activate`` pulls named pods
    back into the active pool (coscheduling uses this to co-activate a
    gang), ``move_all_to_active_or_backoff`` is the cluster-event flush
    (new node, reservation freed → every unschedulable pod retries)."""

    def __init__(self, backoff_s: float = 5.0):
        self.backoff_s = backoff_s
        self._active: Dict[str, Pod] = {}
        self._backoff: Dict[str, Tuple[Pod, float]] = {}
        self._unschedulable: Dict[str, Pod] = {}

    def remove(self, pod_uid: str) -> None:
        self._active.pop(pod_uid, None)
        self._backoff.pop(pod_uid, None)
        self._unschedulable.pop(pod_uid, None)

    def add(self, pod: Pod) -> None:
        # a pod lives in exactly one pool — re-adding (pod update,
        # forget_pod) must not leave a stale backoff/unschedulable entry
        # that would drain it a second time
        self.remove(pod.meta.uid)
        self._active[pod.meta.uid] = pod

    def mark_backoff(self, pod: Pod, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.remove(pod.meta.uid)
        self._backoff[pod.meta.uid] = (pod, now + self.backoff_s)

    def mark_unschedulable(self, pod: Pod) -> None:
        self.remove(pod.meta.uid)
        self._unschedulable[pod.meta.uid] = pod

    def activate(self, pod_uids: Sequence[str]) -> int:
        """Adapter ``Activate``: named pods skip backoff/unschedulable."""
        n = 0
        for uid in pod_uids:
            entry = self._backoff.pop(uid, (None, 0.0))[0]
            entry = entry or self._unschedulable.pop(uid, None)
            if entry is not None:
                self._active[uid] = entry
                n += 1
        return n

    def move_all_to_active_or_backoff(self) -> int:
        """Adapter ``MoveAllToActiveOrBackoffQueue`` on a cluster event."""
        n = len(self._unschedulable)
        self._active.update(self._unschedulable)
        self._unschedulable.clear()
        return n

    def drain_active(self, now: Optional[float] = None) -> List[Pod]:
        """Pods ready for the next batch: active + expired backoff."""
        now = time.monotonic() if now is None else now
        for uid, (pod, until) in list(self._backoff.items()):
            if now >= until:
                del self._backoff[uid]
                self._active[uid] = pod
        out = list(self._active.values())
        self._active.clear()
        return out

    @property
    def pending_counts(self) -> Dict[str, int]:
        return {
            "active": len(self._active),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
        }


class SchedulerAdapter:
    """Plugin-facing facade over the snapshot (cache ops) and the queue
    (reference ``scheduler_adapter.go``: AddPod/AssumePod/ForgetPod/
    InvalidNodeInfo + queue Activate/MoveAll...). The snapshot's dense
    arrays double as the scheduler cache, so cache ops delegate there."""

    def __init__(self, snapshot, queue: Optional[SchedulingQueue] = None):
        self.snapshot = snapshot
        self.queue = queue or SchedulingQueue()

    def assume_pod(self, pod: Pod, node_name: str) -> bool:
        if not self.snapshot.assume_pod(pod, node_name):
            return False
        self.queue.remove(pod.meta.uid)
        return True

    def forget_pod(self, pod: Pod) -> None:
        self.snapshot.forget_pod(pod.meta.uid)
        self.queue.add(pod)

    def invalidate_node(self, node_name: str) -> None:
        """InvalidNodeInfo: metric-derived state for the node is stale —
        drop its freshness bit so masks degrade like an expired NodeMetric."""
        idx = self.snapshot.node_id(node_name)
        if idx is not None:
            self.snapshot.nodes.metric_fresh[idx] = False
            # direct array poke: the device-resident NodeState must see it
            self.snapshot.touch_rows([idx])


# ---------------------------------------------------------------------------
# FrameworkExtender
# ---------------------------------------------------------------------------

PodTransformer = Callable[[Pod], Optional[Pod]]


class FrameworkExtender:
    """The shared spine: transformer chains + monitor + error dispatch +
    debug + services, attached to a BatchScheduler.

    The reference builds one of these per scheduling profile and swaps it
    into ``sched.Profiles`` (``app/server.go:431-437``) so every framework
    call routes through it; here the BatchScheduler calls the hooks at the
    equivalent cycle points.
    """

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = scheduler_registry(registry)
        self.monitor = SchedulerMonitor(registry=self.registry)
        self.errors = ErrorHandlerDispatcher()
        self.scores = DebugScoresDumper()
        self.filters = DebugFiltersDumper()
        #: cycle tracer (sampling off by default; POST /trace flips it)
        self.tracer = Tracer(enabled=False)
        #: per-decision rejection attribution, counted into
        #: rejections_total{stage,plugin,reason}
        self.rejections = RejectionLog(
            counter=self.registry.get("rejections_total")
        )
        #: per-subsystem degraded/ok state served as /healthz — the
        #: fallback ladder, deadline degrade, commit journal and (when
        #: wired) the statehub informers all report here
        self.health = HealthRegistry()
        self.services = ServicesEngine(
            self.registry,
            self.scores,
            self.filters,
            tracer=self.tracer,
            rejections=self.rejections,
            health=self.health,
        )
        #: monotonically increasing scheduling-cycle id joining spans,
        #: metrics and rejection records for one cycle
        self._cycle_seq = 0
        self._pre_batch: List[PodTransformer] = []
        self._batch_transformers: List[Callable] = []
        self._cost_transformers: List[Callable] = []
        self._composed_cost: Optional[Callable] = None

    def begin_cycle(self) -> int:
        """Allocate the next cycle id (called once per external
        scheduling cycle; the preemption retry reuses its parent's)."""
        self._cycle_seq += 1
        return self._cycle_seq

    @property
    def current_cycle_id(self) -> int:
        return self._cycle_seq

    # -- registration (reference PluginFactoryProxy interception:
    # frameworkext/framework_extender_factory.go intercepts plugin
    # construction; plugins implementing transformer interfaces register)

    def register_pod_transformer(self, fn: PodTransformer) -> None:
        """BeforePreFilter analog: rewrite the host pod before lowering.
        Returning None drops the pod from the batch (unschedulable)."""
        self._pre_batch.append(fn)

    def register_batch_transformer(self, fn) -> None:
        """BeforeFilter analog: fn(PodBatch, NodeState) -> (PodBatch, NodeState)."""
        self._batch_transformers.append(fn)

    def register_cost_transformer(self, fn) -> None:
        """BeforeScore analog: fn(cost[P,N]) -> cost[P,N] (device-side)."""
        self._cost_transformers.append(fn)
        self._composed_cost = None

    @property
    def cost_transform(self):
        """Composed BeforeScore chain with a stable identity so the jitted
        solver (which hashes it as a static arg) does not retrace per call."""
        if not self._cost_transformers:
            return None
        if self._composed_cost is None:
            chain = tuple(self._cost_transformers)

            def composed(cost, _chain=chain):
                for fn in _chain:
                    cost = fn(cost)
                return cost

            self._composed_cost = composed
        return self._composed_cost

    # -- hook invocation from the batch cycle

    def run_pre_batch_transformers(
        self, pods: Sequence[Pod]
    ) -> Tuple[List[Pod], List[Pod]]:
        kept: List[Pod] = []
        dropped: List[Pod] = []
        for pod in pods:
            out: Optional[Pod] = pod
            for fn in self._pre_batch:
                out = fn(out)
                if out is None:
                    break
            if out is None:
                dropped.append(pod)
                self.errors.handle(pod, "rejected by pod transformer")
            else:
                kept.append(out)
        return kept, dropped

    def run_batch_transformers(self, pod_batch, node_state):
        for fn in self._batch_transformers:
            pod_batch, node_state = fn(pod_batch, node_state)
        return pod_batch, node_state

    def run_cost_transformers(self, cost):
        for fn in self._cost_transformers:
            cost = fn(cost)
        return cost
