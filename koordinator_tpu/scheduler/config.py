"""Versioned componentconfig: decode → default → validate plugin args.

Rebuild of ``pkg/scheduler/apis/config/`` (``types.go:31-305`` canonical
args, ``v1``/``v1beta3`` decoders with ``SetDefaults_*``, and
``validation/validation_pluginargs.go``): a scheduler configuration is a
mapping of profile → plugin → raw args dict; the version tag is checked
(v1 and v1beta3 share spellings for these args), absent keys fall back to
the canonical dataclass defaults, and validation rejects out-of-range
values with field paths via :class:`ConfigError`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..api import extension as ext
from ..descheduler.low_node_load import LowNodeLoadArgs
from .batch_solver import LoadAwareArgs

#: v1 and v1beta3 share field spellings for these args, but do NOT decode
#: identically everywhere: the reference's hand-written v1beta3
#: conversion overrides LoadAwareSchedulingArgs.FilterExpiredNodeMetrics
#: to true regardless of the configured value
#: (``v1beta3/conversion_plugin.go:25-33``), while v1 honors it
#: (generated conversion). ``decode_load_aware`` implements that split.
SUPPORTED_VERSIONS = ("v1", "v1beta3")

#: reference defaults (v1beta3/defaults.go) applied only when the key is
#: ABSENT — an explicit empty map stays empty ("0/absent disables the
#: check"), matching the reference's nil-vs-empty distinction
DEFAULT_ESTIMATED_SCALING = {ext.RES_CPU: 0.85, ext.RES_MEMORY: 0.70}
AGG_TYPES = ("avg", "p50", "p90", "p95", "p99")


class ConfigError(ValueError):
    """Decode/validation failure with a field path (the reference's
    field.Invalid errors)."""

    def __init__(self, path: str, message: str):
        super().__init__(f"{path}: {message}")
        self.path = path


@dataclasses.dataclass
class NodeNUMAResourceArgs:
    """types.go NodeNUMAResourceArgs subset the rebuild consumes."""

    default_cpu_bind_policy: str = "FullPCPUs"
    scoring_strategy: str = "LeastAllocated"    # or MostAllocated


@dataclasses.dataclass
class ElasticQuotaArgs:
    delay_evict_time_s: float = 300.0
    revoke_pods_interval_s: float = 60.0
    default_quota_group_max: Mapping[str, float] = dataclasses.field(
        default_factory=dict
    )
    quota_group_namespace: str = "koordinator-system"
    enable_check_parent_quota: bool = False
    disable_default_quota_preemption: bool = True


@dataclasses.dataclass
class CoschedulingArgs:
    default_timeout_s: float = 600.0
    controller_workers: int = 1


@dataclasses.dataclass
class DeviceShareArgs:
    allocator: str = ""
    scoring_strategy: str = "LeastAllocated"


@dataclasses.dataclass
class ReservationArgs:
    enable_preemption: bool = False
    min_candidate_nodes_percentage: int = 10
    gc_duration_s: float = 24 * 3600.0


@dataclasses.dataclass
class SolverTuningArgs:
    """Rebuild-side solver tuning (no reference counterpart — the
    reference has ``percentageOfNodesToScore`` sampling; the batched
    solver's analog is the decision-identical candidate shortlist).

    ``shortlist_k`` is the per-pod candidate-shortlist width for the
    constrained round solver: the dispatcher rounds it UP to the next
    power of two (static-arg bucketing, retrace hygiene) and the solver
    statically disables pruning when K covers the node axis anyway.
    0 disables pruning outright (full ``[P, N]`` round body)."""

    shortlist_k: int = 64


def _num(raw: Mapping[str, Any], key: str, default: float) -> float:
    if key not in raw:
        return default
    try:
        return float(raw[key])
    except (TypeError, ValueError):
        raise ConfigError(key, f"not a number: {raw[key]!r}") from None


def _int(raw: Mapping[str, Any], key: str, default: int) -> int:
    if key not in raw:
        return default
    try:
        return int(raw[key])
    except (TypeError, ValueError):
        raise ConfigError(key, f"not an integer: {raw[key]!r}") from None


def _table(raw: Any, key: str = "") -> Dict[str, float]:
    if not isinstance(raw, Mapping):
        return {}
    try:
        return {str(k): float(v) for k, v in raw.items()}
    except (TypeError, ValueError):
        raise ConfigError(key or "<map>", "values must be numbers") from None


def _set_if_present(
    kwargs: Dict[str, Any], raw: Mapping[str, Any], key: str, field: str
) -> None:
    """Map a raw map-valued field onto a dataclass kwarg only when the
    user supplied it — absent keys fall through to the dataclass default
    factory, keeping the defaults in ONE place (the args dataclass)."""
    if key in raw:
        kwargs[field] = _table(raw.get(key), key)


def decode_load_aware(
    raw: Mapping[str, Any], api_version: str = "v1"
) -> LoadAwareArgs:
    """v1/v1beta3 LoadAwareSchedulingArgs → canonical, with the reference's
    defaulting (defaults.go:89-116: merge estimator scales key-wise).

    The versions genuinely diverge on ``filterExpiredNodeMetrics``: the
    v1beta3 conversion FORCES it true after the field copy
    (``v1beta3/conversion_plugin.go:25-33``), while v1 passes the
    configured value through (default true when absent,
    ``v1/defaults.go:91-93``). ``enableScheduleWhenNodeMetricsExpired``
    defaults false (strict) in both (``defaults.go:94-95``)."""
    kwargs: Dict[str, Any] = {}
    _set_if_present(kwargs, raw, "usageThresholds", "usage_thresholds")
    _set_if_present(kwargs, raw, "prodUsageThresholds", "prod_usage_thresholds")
    _set_if_present(kwargs, raw, "resourceWeights", "resource_weights")
    # estimator scales: key-wise merge over the defaults (defaults.go:106-115)
    scales = dict(DEFAULT_ESTIMATED_SCALING)
    scales.update(_table(raw.get("estimatedScalingFactors"), "estimatedScalingFactors"))
    kwargs["estimator_scales"] = scales
    kwargs["node_metric_expiration_s"] = _num(
        raw, "nodeMetricExpirationSeconds", 180.0
    )
    agg = raw.get("aggregated") or {}
    kwargs["aggregated_usage_type"] = str(
        agg.get("usageAggregationType", raw.get("usageAggregationType", "p95"))
    )
    if api_version == "v1beta3":
        kwargs["filter_expired_node_metrics"] = True
    else:
        kwargs["filter_expired_node_metrics"] = bool(
            raw.get("filterExpiredNodeMetrics", True)
        )
    kwargs["enable_schedule_when_node_metrics_expired"] = bool(
        raw.get("enableScheduleWhenNodeMetricsExpired", False)
    )
    return LoadAwareArgs(**kwargs)


def validate_load_aware(args: LoadAwareArgs, path: str = "loadAware") -> None:
    if args.node_metric_expiration_s <= 0:
        raise ConfigError(
            f"{path}.nodeMetricExpirationSeconds",
            "should be a positive value",
        )
    for name, table in (
        ("usageThresholds", args.usage_thresholds),
        ("prodUsageThresholds", args.prod_usage_thresholds),
    ):
        for res, val in table.items():
            if not 0.0 <= val <= 100.0:
                raise ConfigError(
                    f"{path}.{name}[{res}]", f"threshold {val} outside [0, 100]"
                )
    for res, val in args.resource_weights.items():
        if val <= 0:
            raise ConfigError(
                f"{path}.resourceWeights[{res}]", "weight must be positive"
            )
    for res, val in args.estimator_scales.items():
        if val <= 0:
            raise ConfigError(
                f"{path}.estimatedScalingFactors[{res}]",
                "scaling factor must be positive",
            )
    if args.aggregated_usage_type not in AGG_TYPES:
        raise ConfigError(
            f"{path}.aggregated.usageAggregationType",
            f"unknown aggregation {args.aggregated_usage_type!r}",
        )


def decode_node_numa(raw: Mapping[str, Any]) -> NodeNUMAResourceArgs:
    return NodeNUMAResourceArgs(
        default_cpu_bind_policy=str(
            raw.get("defaultCPUBindPolicy", "FullPCPUs")
        ),
        scoring_strategy=str(
            (raw.get("scoringStrategy") or {}).get("type", "LeastAllocated")
        ),
    )


def validate_node_numa(args: NodeNUMAResourceArgs, path: str = "nodeNUMA") -> None:
    if args.default_cpu_bind_policy not in ("FullPCPUs", "SpreadByPCPUs"):
        raise ConfigError(
            f"{path}.defaultCPUBindPolicy",
            f"unknown policy {args.default_cpu_bind_policy!r}",
        )
    if args.scoring_strategy not in ("LeastAllocated", "MostAllocated"):
        raise ConfigError(
            f"{path}.scoringStrategy.type",
            f"unknown strategy {args.scoring_strategy!r}",
        )


def decode_elastic_quota(raw: Mapping[str, Any]) -> ElasticQuotaArgs:
    return ElasticQuotaArgs(
        delay_evict_time_s=_num(raw, "delayEvictTime", 300.0),
        revoke_pods_interval_s=_num(raw, "revokePodInterval", 60.0),
        default_quota_group_max=_table(
            raw.get("defaultQuotaGroupMax"), "defaultQuotaGroupMax"
        ),
        quota_group_namespace=str(
            raw.get("quotaGroupNamespace", "koordinator-system")
        ),
        enable_check_parent_quota=bool(raw.get("enableCheckParentQuota", False)),
        disable_default_quota_preemption=bool(
            raw.get("disableDefaultQuotaPreemption", True)
        ),
    )


def validate_elastic_quota(args: ElasticQuotaArgs, path: str = "elasticQuota") -> None:
    if args.delay_evict_time_s < 0:
        raise ConfigError(f"{path}.delayEvictTime", "must be >= 0")
    if args.revoke_pods_interval_s < 0:
        raise ConfigError(f"{path}.revokePodInterval", "must be >= 0")
    for res, val in args.default_quota_group_max.items():
        if val < 0:
            raise ConfigError(f"{path}.defaultQuotaGroupMax[{res}]", "must be >= 0")


def decode_coscheduling(raw: Mapping[str, Any]) -> CoschedulingArgs:
    return CoschedulingArgs(
        default_timeout_s=_num(raw, "defaultTimeout", 600.0),
        controller_workers=_int(raw, "controllerWorkers", 1),
    )


def validate_coscheduling(args: CoschedulingArgs, path: str = "coscheduling") -> None:
    if args.default_timeout_s <= 0:
        raise ConfigError(f"{path}.defaultTimeout", "must be positive")
    if args.controller_workers < 1:
        raise ConfigError(f"{path}.controllerWorkers", "must be >= 1")


def decode_device_share(raw: Mapping[str, Any]) -> DeviceShareArgs:
    return DeviceShareArgs(
        allocator=str(raw.get("allocator", "")),
        scoring_strategy=str(
            (raw.get("scoringStrategy") or {}).get("type", "LeastAllocated")
        ),
    )


def validate_device_share(args: DeviceShareArgs, path: str = "deviceShare") -> None:
    if args.scoring_strategy not in ("LeastAllocated", "MostAllocated"):
        raise ConfigError(
            f"{path}.scoringStrategy.type",
            f"unknown strategy {args.scoring_strategy!r}",
        )


def decode_solver_tuning(raw: Mapping[str, Any]) -> SolverTuningArgs:
    return SolverTuningArgs(shortlist_k=_int(raw, "shortlistK", 64))


def validate_solver_tuning(
    args: SolverTuningArgs, path: str = "solverTuning"
) -> None:
    if args.shortlist_k < 0:
        raise ConfigError(
            f"{path}.shortlistK", "must be >= 0 (0 disables pruning)"
        )


def decode_reservation(raw: Mapping[str, Any]) -> ReservationArgs:
    return ReservationArgs(
        enable_preemption=bool(raw.get("enablePreemption", False)),
        min_candidate_nodes_percentage=_int(
            raw, "minCandidateNodesPercentage", 10
        ),
        gc_duration_s=_num(raw, "gcDurationSeconds", 24 * 3600.0),
    )


def validate_reservation(args: ReservationArgs, path: str = "reservation") -> None:
    if not 0 <= args.min_candidate_nodes_percentage <= 100:
        raise ConfigError(
            f"{path}.minCandidateNodesPercentage", "must be in [0, 100]"
        )


def decode_low_node_load(raw: Mapping[str, Any]) -> LowNodeLoadArgs:
    kwargs: Dict[str, Any] = {}
    _set_if_present(kwargs, raw, "highThresholds", "high_thresholds")
    _set_if_present(kwargs, raw, "lowThresholds", "low_thresholds")
    _set_if_present(kwargs, raw, "prodHighThresholds", "prod_high_thresholds")
    _set_if_present(kwargs, raw, "resourceWeights", "resource_weights")
    if "useDeviationThresholds" in raw:
        kwargs["use_deviation_thresholds"] = bool(raw["useDeviationThresholds"])
    if "nodeFit" in raw:
        kwargs["node_fit"] = bool(raw["nodeFit"])
    kwargs["anomaly_condition_count"] = _int(
        raw.get("anomalyCondition") or {}, "consecutiveAbnormalities", 2
    )
    return LowNodeLoadArgs(**kwargs)


def decode_low_node_load_pools(raw: Mapping[str, Any]):
    """NodePools (types_loadaware.go:93-122): each entry carries its own
    thresholds decoded with the same rules as the top level."""
    from ..descheduler.low_node_load import NodePool

    pools = []
    seen = set()
    for entry in raw.get("nodePools") or []:
        if not isinstance(entry, Mapping) or not entry.get("name"):
            raise ConfigError("lowNodeLoad.nodePools", f"bad entry {entry!r}")
        if entry["name"] in seen:
            raise ConfigError(
                "lowNodeLoad.nodePools", f"duplicate pool name {entry['name']!r}"
            )
        seen.add(entry["name"])
        selector = (entry.get("nodeSelector") or {}).get("matchLabels") or {}
        args = decode_low_node_load(entry)
        validate_low_node_load(args, f"lowNodeLoad.nodePools[{entry['name']}]")
        pools.append(
            NodePool(
                name=str(entry["name"]),
                node_selector=dict(selector),
                args=args,
            )
        )
    return pools


def validate_low_node_load(args: LowNodeLoadArgs, path: str = "lowNodeLoad") -> None:
    for res, hi in dict(args.high_thresholds).items():
        lo = dict(args.low_thresholds).get(res, 0.0)
        if lo > hi:
            raise ConfigError(
                f"{path}.lowThresholds[{res}]",
                f"low threshold {lo} above high threshold {hi}",
            )
    if args.anomaly_condition_count < 1:
        raise ConfigError(
            f"{path}.anomalyCondition.consecutiveAbnormalities", "must be >= 1"
        )


_PLUGINS = {
    "LoadAwareScheduling": (decode_load_aware, validate_load_aware),
    "NodeNUMAResource": (decode_node_numa, validate_node_numa),
    "ElasticQuota": (decode_elastic_quota, validate_elastic_quota),
    "Coscheduling": (decode_coscheduling, validate_coscheduling),
    "DeviceShare": (decode_device_share, validate_device_share),
    "Reservation": (decode_reservation, validate_reservation),
    "LowNodeLoad": (decode_low_node_load, validate_low_node_load),
}


def decode_plugin_args(
    plugin: str, raw: Mapping[str, Any], api_version: str = "v1"
):
    """Decode + default + validate one plugin's args. Raises ConfigError."""
    if api_version not in SUPPORTED_VERSIONS:
        raise ConfigError("apiVersion", f"unsupported version {api_version!r}")
    if plugin not in _PLUGINS:
        raise ConfigError("plugins", f"unknown plugin {plugin!r}")
    decode, validate = _PLUGINS[plugin]
    if plugin == "LoadAwareScheduling":
        # the only args with a version-divergent decode (see
        # decode_load_aware's conversion notes)
        args = decode(raw or {}, api_version=api_version)
    else:
        args = decode(raw or {})
    validate(args)
    return args


def decode_profile(
    profile: Mapping[str, Any], api_version: str = "v1"
) -> Dict[str, Any]:
    """One scheduler profile's pluginConfig list → {plugin: args}."""
    out: Dict[str, Any] = {}
    for entry in profile.get("pluginConfig", []):
        name = entry.get("name", "")
        out[name] = decode_plugin_args(
            name, entry.get("args", {}), api_version
        )
    return out
