"""Latency-oriented continuous admission (the north star's second clause).

The batch scheduler's throughput mode drains a backlog in bucket-sized
chunks — a pod's scheduling latency is then bounded below by its chunk's
drain position. This module is the other operating point: a
:class:`StreamScheduler` pumps *adaptive* batches — each cycle schedules
exactly the pods that arrived while the previous cycle was in flight
(capped), so a pod's enqueue→bind latency is its queue wait plus one
cycle. Combined with kube-scheduler node sampling
(``BatchScheduler.percentage_of_nodes_to_score``, the reference's
``WithPercentageOfNodesToScore`` passthrough at
``cmd/koord-scheduler/app/server.go:411`` — upstream's adaptive default
scores only 5% of a 10k-node cluster), one cycle at 10k nodes is a few
milliseconds of solve over the sampled window.

The reference's latency discipline is the SchedulerMonitor watchdog
(``frameworkext/scheduler_monitor.go:43-47``); here the monitor wraps
every cycle the same way via the underlying ``BatchScheduler``.
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..api.types import Pod
from .batch_solver import BatchScheduler, ScheduleOutcome


class StreamScheduler:
    """Continuous admission pump over a :class:`BatchScheduler`.

    ``submit`` enqueues arrivals (stamping arrival time); ``pump`` runs
    one adaptive-batch cycle and returns per-pod outcomes with measured
    enqueue→decision latency. Unschedulable pods are re-queued up to
    ``max_retries`` cycles (their latency clock keeps running — the
    north-star latency is enqueue→bind, not attempt-scoped).

    ``pipelined=True`` selects the cross-cycle pipelined pump mode (perf
    PR 4): each ``pump`` hands its batch to a :class:`CyclePipeline` —
    which dispatches the batch's solves chained off the previous cycle's
    on-device commit state while that cycle's host Reserve trails behind
    — and returns the PREVIOUS batch's decisions (one-pump lag; call
    :meth:`flush` to drain the tail). Decisions are identical to the
    serial pump; only the overlap differs."""

    def __init__(
        self,
        scheduler: BatchScheduler,
        max_batch: int = 256,
        max_retries: int = 3,
        pipelined: bool = False,
        prepare_timeout_s: float = 5.0,
    ):
        self.scheduler = scheduler
        self.max_batch = max_batch
        self.max_retries = max_retries
        self._queue: Deque[Tuple[Pod, float, int]] = deque()
        self._pipe = None
        #: uid -> (arrival stamp, tries) for pods inside the pipeline
        self._inflight_meta: Dict[str, Tuple[float, int]] = {}
        if pipelined:
            from .pipeline import CyclePipeline

            self._pipe = CyclePipeline(
                scheduler, prepare_timeout_s=prepare_timeout_s
            )

    def submit(self, pod: Pod, now: Optional[float] = None) -> None:
        self._queue.append(
            (pod, _time.perf_counter() if now is None else now, 0)
        )

    def backlog(self) -> int:
        return len(self._queue)

    def close(self) -> None:
        if self._pipe is not None:
            self._pipe.close()

    def pump(self) -> List[Tuple[Pod, Optional[str], float]]:
        """One cycle: schedule up to ``max_batch`` queued pods. Returns
        ``(pod, node|None, latency_s)`` for every pod DECIDED this cycle
        — bound pods and pods that exhausted their retries; retried pods
        return to the queue with their original arrival stamp. In
        pipelined mode the returned decisions belong to the PREVIOUS
        pump's batch (the new batch's solve is in flight)."""
        if self._pipe is not None:
            return self._pump_pipelined()
        if not self._queue:
            return []
        batch: List[Tuple[Pod, float, int]] = []
        for _ in range(min(self.max_batch, len(self._queue))):
            batch.append(self._queue.popleft())
        meta = {p.meta.uid: (t, tries) for p, t, tries in batch}
        with self.scheduler.extender.tracer.span(
            "pump", cat="scheduler", batch=len(batch)
        ) as sp:
            out = self.scheduler.schedule([p for p, _t, _n in batch])
            t_done = _time.perf_counter()
            results: List[Tuple[Pod, Optional[str], float]] = []
            for pod, node in out.bound:
                t_arr, _tries = meta[pod.meta.uid]
                results.append((pod, node, t_done - t_arr))
            for pod in out.unschedulable:
                t_arr, tries = meta[pod.meta.uid]
                if tries + 1 < self.max_retries:
                    self._queue.append((pod, t_arr, tries + 1))
                else:
                    results.append((pod, None, t_done - t_arr))
            sp.set(
                bound=len(out.bound),
                unschedulable=len(out.unschedulable),
                backlog=len(self._queue),
            )
        return results

    # ---- pipelined mode ----

    def _pump_pipelined(self) -> List[Tuple[Pod, Optional[str], float]]:
        if not self._queue and not self._pipe.inflight:
            return []
        batch: List[Tuple[Pod, float, int]] = []
        for _ in range(min(self.max_batch, len(self._queue))):
            batch.append(self._queue.popleft())
        with self.scheduler.extender.tracer.span(
            "pump", cat="scheduler", batch=len(batch), pipelined=True
        ) as sp:
            for pod, t_arr, tries in batch:
                self._inflight_meta[pod.meta.uid] = (t_arr, tries)
            out = self._pipe.feed([p for p, _t, _n in batch])
            results = self._absorb(out)
            sp.set(
                decided=len(results),
                backlog=len(self._queue),
            )
        return results

    def _absorb(
        self, out: Optional[ScheduleOutcome]
    ) -> List[Tuple[Pod, Optional[str], float]]:
        if out is None:
            return []
        t_done = _time.perf_counter()
        results: List[Tuple[Pod, Optional[str], float]] = []
        for pod, node in out.bound:
            t_arr, _tries = self._inflight_meta.pop(pod.meta.uid)
            results.append((pod, node, t_done - t_arr))
        for pod in out.unschedulable:
            t_arr, tries = self._inflight_meta.pop(pod.meta.uid)
            if tries + 1 < self.max_retries:
                self._queue.append((pod, t_arr, tries + 1))
            else:
                results.append((pod, None, t_done - t_arr))
        return results

    def drain_for_handoff(self) -> List[Tuple[Pod, Optional[str], float]]:
        """Leadership loss: discard pipeline speculation and flush the
        trailing commit through the fencing check (see
        :meth:`CyclePipeline.drain_for_handoff`); queued AND fence-
        rejected pods stay queued for the next leader WITHOUT a retry
        charge — a fencing rejection is not a scheduling verdict, so it
        must never burn the pod's ``max_retries`` budget (repeated flaps
        would otherwise fail pods that were never genuinely evaluated).
        Serial mode has nothing in flight — returns []."""
        if self._pipe is None:
            return []
        out = self._pipe.drain_for_handoff()
        if out is None:
            return []
        t_done = _time.perf_counter()
        results: List[Tuple[Pod, Optional[str], float]] = []
        for pod, node in out.bound:  # fence still held: a real decision
            t_arr, _tries = self._inflight_meta.pop(pod.meta.uid)
            results.append((pod, node, t_done - t_arr))
        for pod in out.unschedulable:
            t_arr, tries = self._inflight_meta.pop(pod.meta.uid)
            self._queue.append((pod, t_arr, tries))
        return results

    def flush(self) -> List[Tuple[Pod, Optional[str], float]]:
        """Drain everything: pump until the queue is empty, then complete
        the pipeline's in-flight cycle(s). Retried pods cycle back through
        until decided. Serial mode simply pumps the queue dry."""
        results: List[Tuple[Pod, Optional[str], float]] = []
        if self._pipe is None:
            while self._queue:
                results.extend(self.pump())
            return results
        while True:
            while self._queue:
                results.extend(self.pump())
            results.extend(self._absorb(self._pipe.flush()))
            if not self._queue and not self._pipe.inflight:
                return results
