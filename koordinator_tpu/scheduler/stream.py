"""Latency-oriented continuous admission (the north star's second clause).

The batch scheduler's throughput mode drains a backlog in bucket-sized
chunks — a pod's scheduling latency is then bounded below by its chunk's
drain position. This module is the other operating point: a
:class:`StreamScheduler` pumps *adaptive* batches — each cycle schedules
exactly the pods that arrived while the previous cycle was in flight
(capped), so a pod's enqueue→bind latency is its queue wait plus one
cycle. Combined with kube-scheduler node sampling
(``BatchScheduler.percentage_of_nodes_to_score``, the reference's
``WithPercentageOfNodesToScore`` passthrough at
``cmd/koord-scheduler/app/server.go:411`` — upstream's adaptive default
scores only 5% of a 10k-node cluster), one cycle at 10k nodes is a few
milliseconds of solve over the sampled window.

The reference's latency discipline is the SchedulerMonitor watchdog
(``frameworkext/scheduler_monitor.go:43-47``); here the monitor wraps
every cycle the same way via the underlying ``BatchScheduler``.
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..api.types import Pod
from ..obs.rejections import RejectReason
from ..runtime.containment import spec_fingerprint
from .batch_solver import BatchScheduler, ScheduleOutcome


class StreamScheduler:
    """Continuous admission pump over a :class:`BatchScheduler`.

    ``submit`` enqueues arrivals (stamping arrival time); ``pump`` runs
    one adaptive-batch cycle and returns per-pod outcomes with measured
    enqueue→decision latency. Unschedulable pods are re-queued up to
    ``max_retries`` cycles (their latency clock keeps running — the
    north-star latency is enqueue→bind, not attempt-scoped).

    ``pipelined=True`` selects the cross-cycle pipelined pump mode (perf
    PR 4): each ``pump`` hands its batch to a :class:`CyclePipeline` —
    which dispatches the batch's solves chained off the previous cycle's
    on-device commit state while that cycle's host Reserve trails behind
    — and returns the PREVIOUS batch's decisions (one-pump lag; call
    :meth:`flush` to drain the tail). Decisions are identical to the
    serial pump; only the overlap differs. ``pipeline_depth`` > 1
    (open-the-gates PR) lets the pipeline hold up to that many
    speculative solves in flight (decisions then lag up to
    ``pipeline_depth`` pumps; the flush loop drains them all). The
    value is a CEILING (open the last gates PR): the pipeline's
    adaptive depth controller degrades the effective window to 1 under
    sustained speculation churn and restores the max on quiet
    stretches — see :class:`~.pipeline._DepthController`.

    Distributed observability (fleet-tracing PR): ``lifecycle`` (a
    :class:`~..obs.lifecycle.PodLifecycle`) receives per-pod
    enqueue/dispatch/decide/ack events stamped with ``shard``;
    ``slo`` (a :class:`~..obs.slo.SloTracker`) gets one placement-latency
    sample per bound pod and one queue-age sample (oldest queued pod)
    per pump. Both default None — the disabled path is one
    attribute-is-None check per site. Lifecycle event timestamps come
    from the TRACKER's clock so a sim-clock soak and a wall-clock bench
    each stay in one time domain."""

    def __init__(
        self,
        scheduler: BatchScheduler,
        max_batch: int = 256,
        max_retries: int = 3,
        pipelined: bool = False,
        pipeline_depth: int = 1,
        prepare_timeout_s: float = 5.0,
        feed_gate=None,
        lifecycle=None,
        slo=None,
        shard: int = -1,
        overload=None,
    ):
        self.scheduler = scheduler
        self.max_batch = max_batch
        self.max_retries = max_retries
        #: optional predicate evaluated as queued pods are popped into a
        #: cycle's batch (PR 6: the cross-shard single-winner CLAIM — a
        #: pod fanned out to several shards' queues is fed only by the
        #: shard that wins its claim; losers drop it here, silently)
        self.feed_gate = feed_gate
        self.lifecycle = lifecycle
        self.slo = slo
        self.shard = int(shard)
        #: QoS-aware bounded admission (overload-control PR): an
        #: :class:`~..runtime.overload.AdmissionController`. PROD/MID
        #: always enter the live queue; BATCH/FREE past their band's
        #: budget (or a browning ladder) park in ``_deferred`` — fed
        #: only once pressure clears — and are SHED (terminal lifecycle
        #: event + resubmit ticket) once deferral outlives the band's
        #: age limit. None = every path below is one attribute check.
        self.overload = overload
        #: parked BATCH/FREE arrivals, FIFO, stamps/tries intact
        self._deferred: Deque[Tuple[Pod, float, int]] = deque()
        #: live-queue depth per priority band (int(PriorityClass) keys),
        #: maintained only while ``overload`` is wired
        self._band_live: Dict[int, int] = {}
        if overload is not None:
            overload.bind_registry(scheduler.extender.registry)
            if scheduler.decision_ledger is not None:
                # decision observatory: admission verdicts and brownout
                # moves record beside the depth controller's choices
                overload.attach_decisions(scheduler.decision_ledger)
            bo = overload.brownout
            if bo is not None:
                if scheduler.brownout is None:
                    scheduler.brownout = bo
                bo.bind_registry(scheduler.extender.registry)
                bo.attach_health(scheduler.extender.health)
                if scheduler.extender.services.brownout is None:
                    scheduler.extender.services.brownout = bo
                if scheduler.decision_ledger is not None:
                    bo.attach_decisions(scheduler.decision_ledger)
                if scheduler.flight_recorder is not None:
                    bo.attach_flight(scheduler.flight_recorder)
        if lifecycle is not None and scheduler.lifecycle is None:
            # the scheduler embeds each pod's compact trace context in
            # its bind-journal records (crash-bridged timelines)
            scheduler.lifecycle = lifecycle
        if slo is not None and scheduler.extender.services.slo is None:
            # single-leader deployments get their /slo from the stream's
            # tracker (the sharded path serves the fleet-merged view)
            scheduler.extender.services.slo = slo
        self._queue: Deque[Tuple[Pod, float, int]] = deque()
        self._pipe = None
        #: uid -> (arrival stamp, tries) for pods inside the pipeline
        self._inflight_meta: Dict[str, Tuple[float, int]] = {}
        if pipelined:
            from .pipeline import CyclePipeline

            self._pipe = CyclePipeline(
                scheduler,
                prepare_timeout_s=prepare_timeout_s,
                depth=pipeline_depth,
            )

    def submit(self, pod: Pod, now: Optional[float] = None) -> str:
        """Enqueue one arrival. Returns the admission verdict —
        ``"admit"`` (live queue), ``"defer"`` (parked until band
        pressure clears) or ``"shed"`` (terminal: the pod left a
        resubmit ticket on the overload controller). Without an
        overload controller every submit is an admit."""
        arrival = _time.perf_counter() if now is None else now
        ov = self.overload
        if ov is not None:
            band = pod.priority_class
            verdict = ov.admit(
                pod,
                self._band_live.get(int(band), 0),
                shard=self.shard if self.shard >= 0 else None,
            )
            if verdict == ov.SHED:
                ov.shed(pod, self.shard, arrival, detail="admission")
                return "shed"
            if verdict == ov.DEFER:
                self._deferred.append((pod, arrival, 0))
                ov.note_deferred(band)
                lc = self.lifecycle
                if lc is not None:
                    if not lc.seen(pod.meta.uid):
                        lc.submitted(pod.meta.uid)
                    lc.event(
                        pod.meta.uid, "enqueue", shard=self.shard,
                        detail="deferred",
                    )
                return "defer"
            self._band_live[int(band)] = (
                self._band_live.get(int(band), 0) + 1
            )
        self._queue.append((pod, arrival, 0))
        lc = self.lifecycle
        if lc is not None:
            # a pod the tracker never saw gets its ``submit`` anchor here
            # (unsharded deployments have no router to stamp it)
            if not lc.seen(pod.meta.uid):
                lc.submitted(pod.meta.uid)
            lc.event(pod.meta.uid, "enqueue", shard=self.shard)
        return "admit"

    def backlog(self) -> int:
        return len(self._queue)

    def deferred_backlog(self) -> int:
        """Parked BATCH/FREE arrivals awaiting band headroom (not part
        of :meth:`backlog` — spill fan-out and queue-depth hints must
        not treat deliberately parked pods as live pressure)."""
        return len(self._deferred)

    def close(self) -> None:
        if self._pipe is not None:
            self._pipe.close()

    # ---- QoS-aware admission plumbing (overload-control PR) ----

    def _band_add(self, pod: Pod, d: int) -> None:
        """Live-queue band accounting — called at every point a pod
        enters or permanently leaves ``self._queue`` while admission
        control is wired (one attribute check when it is not)."""
        if self.overload is None:
            return
        b = int(pod.priority_class)
        self._band_live[b] = self._band_live.get(b, 0) + d

    def _overload_sweep(self) -> None:
        """Once per pump: age the deferred parking lot. Each parked pod
        is, in order — SHED when the brownout ladder sheds its band;
        kept parked while its band is still deferred (over budget or
        browning), unless its age passed the band's limit (then SHED:
        budget AND age limits both exceeded); else PROMOTED into the
        live queue with its original stamp/tries — the latency clock
        never restarted."""
        ov = self.overload
        if ov is None or not self._deferred:
            return
        now = ov.clock()
        keep: Deque[Tuple[Pod, float, int]] = deque()
        while self._deferred:
            pod, arr, tries = self._deferred.popleft()
            band = pod.priority_class
            if ov.sheds_now(band):
                ov.shed(pod, self.shard, arr, detail="brownout")
                continue
            if ov.still_deferred(
                band, self._band_live.get(int(band), 0)
            ):
                if now - arr > ov.age_limit(band):
                    ov.shed(pod, self.shard, arr, detail="aged_out")
                else:
                    keep.append((pod, arr, tries))
                continue
            self._band_add(pod, +1)
            self._queue.append((pod, arr, tries))
            if self.lifecycle is not None:
                self.lifecycle.event(
                    pod.meta.uid, "enqueue", shard=self.shard,
                    detail="promoted",
                )
        self._deferred = keep

    def pump(self) -> List[Tuple[Pod, Optional[str], float]]:
        """One cycle: schedule up to ``max_batch`` queued pods. Returns
        ``(pod, node|None, latency_s)`` for every pod DECIDED this cycle
        — bound pods and pods that exhausted their retries; retried pods
        return to the queue with their original arrival stamp. In
        pipelined mode the returned decisions belong to the PREVIOUS
        pump's batch (the new batch's solve is in flight)."""
        if self._pipe is not None:
            return self._pump_pipelined()
        self._overload_sweep()
        self._observe_queue_age()
        if not self._queue:
            return []
        batch = self._next_batch()
        if not batch:
            # every popped pod was claim-dropped (another shard won) or
            # the feed gate went stale — don't burn a full scheduler
            # cycle on zero pods
            return []
        self._note_dispatch(batch)
        meta = {p.meta.uid: (t, tries) for p, t, tries in batch}
        with self.scheduler.extender.tracer.span(
            "pump", cat="scheduler", batch=len(batch)
        ) as sp:
            self.scheduler._queue_depth_hint = len(self._queue)
            out = self.scheduler.schedule([p for p, _t, _n in batch])
            t_done = _time.perf_counter()
            fenced = self._fenced_now()
            results: List[Tuple[Pod, Optional[str], float]] = []
            for pod, node in out.bound:
                t_arr, _tries = meta[pod.meta.uid]
                lat = t_done - t_arr
                self._note_bound(pod, node, lat)
                results.append((pod, node, lat))
            for pod in out.unschedulable:
                t_arr, tries = meta[pod.meta.uid]
                if fenced:
                    # a fencing rejection is not a scheduling verdict:
                    # the cycle ran under a revoked/superseded grant, so
                    # the pod re-queues WITHOUT burning its retry budget
                    # (same rule drain_for_handoff applies) — otherwise
                    # leader churn terminally fails pods that were never
                    # genuinely evaluated
                    self._band_add(pod, +1)
                    self._queue.append((pod, t_arr, tries))
                elif self._shed_quarantined(pod, t_arr):
                    # decided terminally via the ticketed shed path
                    results.append((pod, None, t_done - t_arr))
                elif tries + 1 < self.max_retries:
                    self._band_add(pod, +1)
                    self._queue.append((pod, t_arr, tries + 1))
                else:
                    self._note_exhausted(pod)
                    results.append((pod, None, t_done - t_arr))
            sp.set(
                bound=len(out.bound),
                unschedulable=len(out.unschedulable),
                backlog=len(self._queue),
            )
        return results

    # ---- distributed-observability hooks (fleet-tracing PR) ----

    def _observe_queue_age(self) -> None:
        """One queue-age SLI sample per pump: the OLDEST queued pod's
        wait — backlog growth shows here before throughput moves. Read
        on the SLO tracker's clock, so callers must stamp arrivals in
        the same time domain they built the tracker with. An EMPTY
        queue samples zero (overload-control PR): a drained backlog is
        evidence of health, and without it a post-storm burn window
        would freeze at its worst samples forever — the brownout ladder
        (and the topology controller) could never observe recovery."""
        if self.slo is not None:
            self.slo.observe_queue_age(
                self.shard,
                max(0.0, self.slo.clock() - self._queue[0][1])
                if self._queue
                else 0.0,
            )

    def _note_dispatch(self, batch) -> None:
        if self.lifecycle is not None:
            for pod, _t, _tries in batch:
                self.lifecycle.event(
                    pod.meta.uid, "dispatch", shard=self.shard
                )

    def _note_bound(self, pod: Pod, node: str, lat: float) -> None:
        """decide + terminal ack events, plus the placement-latency SLI
        sample — taken from the LIFECYCLE clock's e2e span when a
        tracker is wired (one time domain end to end), else from the
        pump's own measured latency."""
        lc = self.lifecycle
        if lc is not None:
            lc.event(pod.meta.uid, "decide", shard=self.shard, detail=node)
            e2e = lc.acked(pod.meta.uid, self.shard, node)
            if self.slo is not None and e2e is not None:
                self.slo.observe_latency(self.shard, e2e)
        elif self.slo is not None:
            self.slo.observe_latency(self.shard, lat)

    def _shed_quarantined(self, pod: Pod, t_arr: float) -> bool:
        """Poison-quarantined exit path (gray-failure containment PR):
        a pod the quarantine ledger blames cannot place until its SPEC
        changes — re-queueing it only burns retry budget on a verdict
        that is deterministic. Shed it through the admission
        controller's ticketed path with reason POISON_QUARANTINED: the
        terminal lifecycle event fires and the resubmit ticket stays
        REDEEMABLE (a changed fingerprint lifts the blame at the cycle
        gate and the resubmitted pod schedules normally). Returns True
        when the pod was shed; False (no overload controller, no
        ledger, or no live blame) keeps the ordinary retry path."""
        ov = self.overload
        q = self.scheduler.quarantine
        if ov is None or q is None:
            return False
        if not q.blamed(pod.meta.uid, spec_fingerprint(pod)):
            return False
        ov.shed(
            pod,
            self.shard,
            t_arr,
            detail="poison_quarantined",
            reason=RejectReason.POISON_QUARANTINED.value,
        )
        return True

    def _note_exhausted(self, pod: Pod) -> None:
        """Terminally unschedulable (retry budget burned): a ``decide``
        with no node — the timeline stays open for the caller to either
        re-route (new enqueue) or delete (``gone``)."""
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid, "decide", shard=self.shard,
                detail="unschedulable",
            )

    def _next_batch(self) -> List[Tuple[Pod, float, int]]:
        """Pop up to ``max_batch`` queue entries, dropping pods that fail
        the ``feed_gate`` (their claim belongs to another shard — the
        winner schedules them; this queue simply forgets them).

        A gate that raises :class:`StaleEpochError` means OUR claim
        authority is gone (this shard's owner was deposed), which is
        very different from losing one pod's claim: nobody else holds
        these pods, so dropping them would lose them forever. The item
        goes back to the queue — intact, for the handoff — and batch
        collection stops (the whole queue is under the same dead
        epoch)."""
        from ..core.journal import StaleEpochError

        batch: List[Tuple[Pod, float, int]] = []
        while len(batch) < self.max_batch and self._queue:
            item = self._queue.popleft()
            if self.feed_gate is not None:
                try:
                    admitted = self.feed_gate(item[0])
                except StaleEpochError:
                    self._queue.appendleft(item)
                    break
                if not admitted:
                    # claim loser: the WINNING shard schedules this pod
                    # — a queue-drop, but not a terminal one (claim_lost
                    # was stamped at the gate; koordlint shed-paths
                    # exemption documents this site)
                    self._band_add(item[0], -1)
                    continue
            self._band_add(item[0], -1)
            batch.append(item)
        return batch

    # ---- pipelined mode ----

    def _pump_pipelined(self) -> List[Tuple[Pod, Optional[str], float]]:
        self._overload_sweep()
        self._observe_queue_age()
        if not self._queue and not self._pipe.inflight:
            return []
        batch = self._next_batch()
        if not batch and not self._pipe.inflight:
            # nothing to feed and nothing in flight to absorb (the queue
            # was non-empty but every pod was claim-dropped or the gate
            # went stale) — skip the empty cycle
            return []
        self._note_dispatch(batch)
        with self.scheduler.extender.tracer.span(
            "pump", cat="scheduler", batch=len(batch), pipelined=True
        ) as sp:
            for pod, t_arr, tries in batch:
                self._inflight_meta[pod.meta.uid] = (t_arr, tries)
            self.scheduler._queue_depth_hint = len(self._queue)
            out = self._pipe.feed([p for p, _t, _n in batch])
            results = self._absorb(out)
            sp.set(
                decided=len(results),
                backlog=len(self._queue),
            )
        return results

    def _fenced_now(self) -> bool:
        """True while the underlying scheduler's leadership grant is
        revoked or superseded — its rejections this cycle are fencing
        artifacts, not scheduling verdicts (no retry charge). Read-only:
        must NOT go through ``_fence_stale`` (that evaluates the
        ``leader.stale_commit`` chaos point, which belongs to the commit
        boundary)."""
        sched = self.scheduler
        if sched.fence is None:
            return False
        from ..core.journal import StaleEpochError

        try:
            sched.fence.check(sched._fence_epoch)
        except StaleEpochError:
            return True
        return False

    def _absorb(
        self, out: Optional[ScheduleOutcome]
    ) -> List[Tuple[Pod, Optional[str], float]]:
        if out is None:
            return []
        t_done = _time.perf_counter()
        fenced = self._fenced_now()
        results: List[Tuple[Pod, Optional[str], float]] = []
        for pod, node in out.bound:
            t_arr, _tries = self._inflight_meta.pop(pod.meta.uid)
            lat = t_done - t_arr
            self._note_bound(pod, node, lat)
            results.append((pod, node, lat))
        for pod in out.unschedulable:
            t_arr, tries = self._inflight_meta.pop(pod.meta.uid)
            if fenced:
                # fencing rejection ≠ scheduling verdict: no retry charge
                self._band_add(pod, +1)
                self._queue.append((pod, t_arr, tries))
            elif self._shed_quarantined(pod, t_arr):
                # decided terminally via the ticketed shed path
                results.append((pod, None, t_done - t_arr))
            elif tries + 1 < self.max_retries:
                self._band_add(pod, +1)
                self._queue.append((pod, t_arr, tries + 1))
            else:
                self._note_exhausted(pod)
                results.append((pod, None, t_done - t_arr))
        return results

    def drain_for_handoff(self) -> List[Tuple[Pod, Optional[str], float]]:
        """Leadership loss: discard pipeline speculation and flush the
        trailing commit through the fencing check (see
        :meth:`CyclePipeline.drain_for_handoff`); queued AND fence-
        rejected pods stay queued for the next leader WITHOUT a retry
        charge — a fencing rejection is not a scheduling verdict, so it
        must never burn the pod's ``max_retries`` budget (repeated flaps
        would otherwise fail pods that were never genuinely evaluated).
        Serial mode has nothing in flight — returns []."""
        if self._pipe is None:
            return []
        out = self._pipe.drain_for_handoff()
        if out is None:
            return []
        t_done = _time.perf_counter()
        results: List[Tuple[Pod, Optional[str], float]] = []
        for pod, node in out.bound:  # fence still held: a real decision
            t_arr, _tries = self._inflight_meta.pop(pod.meta.uid)
            lat = t_done - t_arr
            self._note_bound(pod, node, lat)
            results.append((pod, node, lat))
        for pod in out.unschedulable:
            t_arr, tries = self._inflight_meta.pop(pod.meta.uid)
            self._band_add(pod, +1)
            self._queue.append((pod, t_arr, tries))
        return results

    def extract_queued(
        self, event: Optional[str] = "handoff"
    ) -> List[Tuple[Pod, float, int]]:
        """Shard handoff (PR 6): hand the ENTIRE queue — arrival stamps
        and retry counts intact — to the caller, emptying it. ``event``
        names the lifecycle stage each extracted pod records (default
        the graceful ``handoff``); a CRASH caller passes None and stamps
        its own ``orphan`` events — a killed queue must never read as a
        clean drain in the pod's post-mortem timeline. Used when
        a shard's ownership moves to another scheduler incarnation: the
        donor's queued pods are re-routed to the new owner, keeping
        their latency clocks running (the north-star latency is
        enqueue→bind, and a handoff is not an enqueue). Deferred
        (parked) pods ride along — a handoff must never strand the
        admission parking lot on a dead owner."""
        out = list(self._queue) + list(self._deferred)
        self._queue.clear()
        self._deferred.clear()
        self._band_live.clear()
        if self.lifecycle is not None and event is not None:
            for pod, _arr, _tries in out:
                self.lifecycle.event(
                    pod.meta.uid, event, shard=self.shard
                )
        return out

    def resubmit(self, pod: Pod, arrival: float, tries: int) -> None:
        """Re-enqueue a pod handed off from another incarnation's queue
        with its original arrival stamp and retry budget."""
        self._band_add(pod, +1)
        self._queue.append((pod, arrival, tries))
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid, "resubmit", shard=self.shard
            )

    def flush(self) -> List[Tuple[Pod, Optional[str], float]]:
        """Drain everything: pump until the queue is empty, then complete
        the pipeline's in-flight cycle(s). Retried pods cycle back through
        until decided. Serial mode simply pumps the queue dry. A flush is
        a TERMINAL drain: deferred pods are promoted unconditionally
        first — the operator asked for every verdict, so admission
        deferral (a wait-for-headroom policy) no longer applies."""
        if self.overload is not None:
            while self._deferred:
                pod, arr, tries = self._deferred.popleft()
                self._band_add(pod, +1)
                self._queue.append((pod, arr, tries))
        results: List[Tuple[Pod, Optional[str], float]] = []
        if self._pipe is None:
            while self._queue:
                res = self.pump()
                results.extend(res)
                if not res and self._fenced_now():
                    # revoked grant: every cycle re-queues the whole
                    # batch (no retry charge) — the queue is the next
                    # leader's to drain, not ours to spin on
                    return results
            return results
        while True:
            while self._queue:
                res = self.pump()
                results.extend(res)
                if not res and self._fenced_now():
                    return results
            results.extend(self._absorb(self._pipe.flush()))
            if not self._queue and not self._pipe.inflight:
                return results
