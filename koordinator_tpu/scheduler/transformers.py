"""Informer-side pod transformers.

Rebuild of ``pkg/util/transformer/pod_transformer.go`` (installed by
``SetupCustomInformers`` / applied to every pod object before the
scheduler sees it): deprecated resource names translate to current ones,
the scheduler-name label overrides spec.schedulerName, and — behind the
PriorityTransformer gate — the koordinator.sh/priority label overrides
spec.priority. Register with
``FrameworkExtender.register_pod_transformer`` (the BeforePreFilter-era
slot) or call :func:`transform_pod` directly at ingest.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import extension as ext
from ..api.types import Pod
from ..utils.features import SCHEDULER_GATES

#: deprecated → current resource names (reference
#: ``apis/extension/deprecated.go:48-60``; the deprecated device names
#: live under the kubernetes.io/ prefix, the deprecated batch tier under
#: the bare koordinator.sh/ domain)
DEPRECATED_RESOURCES: Dict[str, str] = {
    f"{ext.DOMAIN}/batch-cpu": ext.RES_BATCH_CPU,
    f"{ext.DOMAIN}/batch-memory": ext.RES_BATCH_MEMORY,
    "kubernetes.io/gpu": ext.RES_GPU,
    "kubernetes.io/rdma": ext.RES_RDMA,
    "kubernetes.io/fpga": ext.RES_FPGA,
    "kubernetes.io/gpu-core": ext.RES_GPU_CORE,
    "kubernetes.io/gpu-memory": ext.RES_GPU_MEMORY,
    "kubernetes.io/gpu-memory-ratio": ext.RES_GPU_MEMORY_RATIO,
}

#: the scheduler-name label wins over spec (``multi_scheduler.go:28-33``)
LABEL_SCHEDULER_NAME = f"scheduling.{ext.DOMAIN}/scheduler-name"


def transform_deprecated_resources(pod: Pod) -> Pod:
    """``TransformDeprecatedBatchResources`` +
    ``TransformDeprecatedDeviceResources``: rename in place; a current
    name already present wins over its deprecated alias."""
    for store in (pod.spec.requests, pod.spec.limits):
        for old, new in DEPRECATED_RESOURCES.items():
            if old in store:
                value = store.pop(old)
                store.setdefault(new, value)
    return pod


def transform_scheduler_name(pod: Pod) -> Pod:
    """``TransformSchedulerName``: the label overrides spec."""
    name = pod.meta.labels.get(LABEL_SCHEDULER_NAME)
    if name:
        pod.spec.scheduler_name = name
    return pod


def transform_koord_priority(pod: Pod) -> Pod:
    """``TransformKoordPriorityClassFunc`` (PriorityTransformer gate): the
    koordinator.sh/priority label value overrides spec.priority."""
    if not SCHEDULER_GATES.enabled("PriorityTransformer"):
        return pod
    raw = pod.meta.labels.get(ext.LABEL_POD_PRIORITY)
    if raw is not None:
        try:
            pod.spec.priority = int(raw)
        except ValueError:
            pass
    return pod


def transform_pod(pod: Pod) -> Optional[Pod]:
    """The full chain, in the reference's installation order."""
    pod = transform_deprecated_resources(pod)
    pod = transform_scheduler_name(pod)
    return transform_koord_priority(pod)


def install(extender) -> None:
    """Register the chain on a FrameworkExtender (the analog of
    ``SetupCustomInformers`` at ``app/server.go:377-378``)."""
    extender.register_pod_transformer(transform_pod)
