"""NodeNUMAResource host-side manager: zone accounting + exact cpusets.

Rebuild of the reference plugin's control plane
(``pkg/scheduler/plugins/nodenumaresource/plugin.go:60-74,251-313,579-627``
and ``resource_manager.go:194-225``): parses the pod's
``scheduling.koordinator.sh/resource-spec`` annotation (CPU bind policy),
keeps per-node zone allocations + a CPU accumulator, and at PreBind writes
``scheduling.koordinator.sh/resource-status`` with the exclusive cpuset and
chosen NUMA zone. Zone *feasibility* for all (pod, node) pairs is computed
on TPU (``ops.numa``); this class owns the per-winner exact assignment.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from ...api import extension as ext
from ...api.types import Pod
from ...core.snapshot import ClusterSnapshot
from ...core.topology import (
    CPUAccumulator,
    CPUBindPolicy,
    CPUTopology,
    NUMAPolicy,
    format_cpuset_sorted,
)

#: zone resource dims lowered to the solver (prefix of the snapshot axis)
ZONE_DIMS = 2  # cpu milli, memory MiB


def parse_resource_spec(pod: Pod) -> CPUBindPolicy:
    raw = pod.meta.annotations.get(ext.ANNOTATION_RESOURCE_SPEC)
    if not raw:
        return CPUBindPolicy.DEFAULT
    try:
        spec = json.loads(raw)
        return CPUBindPolicy(spec.get("preferredCPUBindPolicy", "Default"))
    except (ValueError, KeyError, AttributeError, TypeError):
        # user-supplied annotation: any malformed shape degrades to Default
        return CPUBindPolicy.DEFAULT


def wants_numa(pod: Pod) -> bool:
    """LSR/LSE pods with integer CPU requests need exclusive, aligned CPUs
    (reference ``plugin.go:251-313`` requiredCPUBindPolicy resolution) —
    one predicate shared with the snapshot's amplified-CPU charging."""
    return ext.wants_cpu_bind(pod)


@dataclasses.dataclass
class _NodeNUMA:
    topology: CPUTopology
    policy: NUMAPolicy
    #: [Z][ZONE_DIMS] allocatable per zone (plain lists: the per-winner
    #: zone bookkeeping is pure-Python float math — numpy overhead per
    #: tiny op dominated the commit hot path)
    zone_alloc: List[List[float]]
    #: [Z][ZONE_DIMS] allocated per zone
    zone_used: List[List[float]]
    accumulator: CPUAccumulator
    #: CPU amplification ratio the zone capacities were registered with
    cpu_amp: float = 1.0
    #: physical (unamplified) zone CPU milli, for ratio re-sync
    phys_zone_cpu: List[float] = dataclasses.field(default_factory=list)
    #: pod uid -> (zone, charged vec, nominal bind cpu milli — 0 if the
    #: charge was nominal/shared)
    owners: Dict[str, Tuple[int, List[float], float]] = dataclasses.field(
        default_factory=dict
    )
    #: node-level bind-policy constraint (LabelNodeCPUBindPolicy):
    #: "FullPCPUsOnly" forces whole-core takes, "SpreadByPCPUs" spreads
    node_bind_policy: str = ""
    #: node-level zone pick strategy (LabelNodeNUMAAllocateStrategy):
    #: "" follows the plugin default — LeastAllocated (spread), flipping
    #: to MostAllocated when the scoring strategy is MostAllocated
    #: (reference GetDefaultNUMAAllocateStrategy, util.go:33-39);
    #: an explicit label overrides per node (util.go:41-47)
    numa_allocate_strategy: str = ""


class NUMAManager:
    """Per-node NUMA state; lowers zone arrays aligned to snapshot indices."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        max_zones: int = 4,
        scoring_strategy: Optional[str] = None,
    ):
        self.snapshot = snapshot
        self.max_zones = max_zones
        #: "LeastAllocated" | "MostAllocated" | None — NUMA-aligned Score
        #: strategy (reference NodeNUMAResourceArgs.ScoringStrategy)
        self.scoring_strategy = scoring_strategy
        self._nodes: Dict[str, _NodeNUMA] = {}
        #: policy_rows cache, invalidated on register_node / node churn
        self._policy_cache: Optional[np.ndarray] = None
        self._policy_cache_epoch = -1
        #: incremental zone-array lowering cache (see DeviceManager's
        #: ``_lowered``): full rebuild on node churn (node_epoch) or a
        #: cpu_amp change (amp vector compared each call — re-upserts
        #: don't bump the epoch); per-row refresh for allocation deltas
        self._zone_cache: Optional[Tuple[np.ndarray, ...]] = None
        self._zone_epoch = -1
        self._zone_dirty: set = set()
        self._amp_seen: Optional[np.ndarray] = None
        #: bumped whenever arrays() actually changes the lowered zone
        #: tables (full rebuild or a dirty-row flush) — the scheduler keys
        #: its device-resident NumaState upload off it
        self.lowered_version = 0
        #: snapshot row indices whose lowered rows changed since the last
        #: drain_lowered_dirty() — the scheduler scatters ONLY these into
        #: its device-resident NumaState instead of re-uploading the whole
        #: [N, Z, DN] table (ROADMAP item b); a full rebuild sets the
        #: wholesale flag instead
        self._scatter_rows: set = set()
        self._scatter_full = True

    def _mark_dirty(self, node_name: str) -> None:
        if self._zone_cache is not None:
            self._zone_dirty.add(node_name)

    def register_node(
        self,
        node_name: str,
        topology: CPUTopology,
        policy: NUMAPolicy = NUMAPolicy.NONE,
        memory_per_zone_mib: float = 0.0,
        cpu_amp: Optional[float] = None,
    ) -> None:
        """``cpu_amp`` defaults to the snapshot's node amplification ratio;
        zone CPU capacity is registered in *amplified* space (reference
        ``amplifyNUMANodeResources``, ``plugin.go:630-632``) — bound pods'
        zone charges amplify with it, the cpuset accumulator stays
        physical."""
        if cpu_amp is None:
            idx = self.snapshot.node_id(node_name)
            cpu_amp = (
                float(self.snapshot.nodes.cpu_amp[idx]) if idx is not None else 1.0
            )
        cpu_amp = max(float(cpu_amp), 1.0)
        z = topology.num_numa_nodes
        zone_alloc = [[0.0] * ZONE_DIMS for _ in range(self.max_zones)]
        phys = [0.0] * self.max_zones
        for zone in range(min(z, self.max_zones)):
            n_cpus = len(topology.cpus_in_numa(zone))
            phys[zone] = n_cpus * 1000.0
            zone_alloc[zone][0] = phys[zone] * cpu_amp
            zone_alloc[zone][1] = memory_per_zone_mib
        self._nodes[node_name] = _NodeNUMA(
            topology=topology,
            policy=policy,
            zone_alloc=zone_alloc,
            zone_used=[[0.0] * ZONE_DIMS for _ in range(self.max_zones)],
            accumulator=CPUAccumulator(topology),
            cpu_amp=cpu_amp,
            phys_zone_cpu=phys,
        )
        self._policy_cache = None
        self._mark_dirty(node_name)

    #: NodeResourceTopology.topologyPolicy string → solver policy
    _POLICY_BY_NAME = {
        "None": NUMAPolicy.NONE,
        "BestEffort": NUMAPolicy.BEST_EFFORT,
        "Restricted": NUMAPolicy.RESTRICTED,
        "SingleNUMANode": NUMAPolicy.SINGLE_NUMA_NODE,
    }

    def register_from_topology(self, report) -> None:
        """Ingest a NodeResourceTopology report (the koordlet's CR write,
        ``states_noderesourcetopology.go``) — the reference's
        NodeNUMAResource plugin consumes exactly this CRD via informer.
        Rebuilds the node's zone tables and cpuset accumulator, and
        pre-takes the kubelet-reserved CPUs so the scheduler can never
        hand them out."""
        from ...api.types import NodeResourceTopology  # noqa: F401 (doc)
        from ...core.topology import CPUInfo

        if not report.cpu_topology:
            return
        cpus = [
            CPUInfo(cpu_id=cid, core_id=core, numa_node=numa, socket=sock)
            for cid, (core, numa, sock) in sorted(
                report.cpu_topology.items()
            )
        ]
        topo = CPUTopology(cpus=cpus)
        policy = self._POLICY_BY_NAME.get(
            report.topology_policy, NUMAPolicy.NONE
        )
        mem_per_zone = 0.0
        for zone in report.zones:
            mem = float(zone.allocatable.get(ext.RES_MEMORY, 0.0))
            mem_per_zone = max(mem_per_zone, mem)
        self.register_node(
            report.meta.name,
            topo,
            policy,
            memory_per_zone_mib=mem_per_zone,
        )
        zone_of = {c.cpu_id: c.numa_node for c in cpus}
        st = self._nodes[report.meta.name]

        charged: set = set()

        def pre_take(owner: str, cpu_ids) -> None:
            # overlapping reservations (system-QoS inside the kubelet
            # reserved set is common) must charge each CPU's zone ONCE
            ids = set(int(c) for c in cpu_ids) - charged
            if not ids:
                return
            charged.update(ids)
            st.accumulator.take_reserved(owner, ids)
            # zone feasibility must see the taken cores as used too
            for cid in ids:
                zone = zone_of.get(cid)
                if zone is not None and zone < self.max_zones:
                    st.zone_used[zone][0] += 1000.0 * st.cpu_amp

        pre_take("kubelet-reserved", report.kubelet_reserved_cpus)
        ann = report.meta.annotations or {}
        # kubelet static-policy Guaranteed pods' cpusets + the kubelet
        # policy's own reservedCPUs + the exclusive SYSTEM-QoS carve-out
        # (AnnotationNodeCPUAllocs / AnnotationKubeletCPUManagerPolicy /
        # AnnotationNodeSystemQOSResource): none of these CPUs may ever
        # be handed to a cpuset-bound pod by this scheduler
        from ...core.topology import parse_cpuset

        for alloc in ext.parse_node_cpu_allocs(ann):
            owner = f"kubelet-alloc/{alloc.get('uid') or alloc.get('name', '?')}"
            pre_take(owner, parse_cpuset(str(alloc.get("cpuset", ""))))
        kubelet = ext.parse_kubelet_cpu_manager_policy(ann)
        if kubelet and kubelet.get("reservedCPUs"):
            pre_take(
                "kubelet-policy-reserved",
                parse_cpuset(str(kubelet["reservedCPUs"])),
            )
        sysqos = ext.parse_system_qos_resource(ann)
        if sysqos and sysqos.get("cpusetExclusive", True):
            pre_take("system-qos", parse_cpuset(str(sysqos["cpuset"])))
        # node-level bind-policy / NUMA allocate-strategy labels
        # (LabelNodeCPUBindPolicy / LabelNodeNUMAAllocateStrategy) ride in
        # on the report's labels when published through it
        labels = report.meta.labels or {}
        st.node_bind_policy = labels.get(ext.LABEL_NODE_CPU_BIND_POLICY, "")
        st.numa_allocate_strategy = labels.get(
            ext.LABEL_NODE_NUMA_ALLOCATE_STRATEGY, ""
        )

    def unregister_node(self, node_name: str) -> None:
        """Drop a node's topology (NodeResourceTopology deleted)."""
        self._nodes.pop(node_name, None)
        self._policy_cache = None
        # the cached zone row must zero out (node_epoch doesn't bump —
        # the Node itself may remain in the snapshot)
        self._mark_dirty(node_name)

    def _sync_amp(self, node_name: str, st: _NodeNUMA) -> None:
        """Re-base zone capacities and bound charges onto the snapshot's
        *live* amplification ratio. register_node may have run before the
        Node upsert (ratio unknown → 1.0) or the annotation may have
        changed since; the solver always amplifies with the live ratio, so
        the manager must live in the same space."""
        idx = self.snapshot.node_id(node_name)
        if idx is None:
            return
        live = max(float(self.snapshot.nodes.cpu_amp[idx]), 1.0)
        if live == st.cpu_amp:
            return
        for zone in range(self.max_zones):
            st.zone_alloc[zone][0] = st.phys_zone_cpu[zone] * live
        for uid, (zone, charged, nominal_cpu) in list(st.owners.items()):
            if nominal_cpu <= 0 or zone < 0:
                continue
            new_charge = nominal_cpu * live
            st.zone_used[zone][0] += new_charge - charged[0]
            st.owners[uid] = (zone, [new_charge] + charged[1:], nominal_cpu)
        st.cpu_amp = live

    def node(self, name: str) -> Optional[_NodeNUMA]:
        return self._nodes.get(name)

    # ---- solver lowering ----

    def _refresh_zone_row(self, name: str) -> None:
        zone_free, zone_cap, policy, most = self._zone_cache
        idx = self.snapshot.node_id(name)
        if idx is None:
            return
        st = self._nodes.get(name)
        if st is None:
            zone_free[idx] = 0.0
            zone_cap[idx] = 0.0
            policy[idx] = 0
            most[idx] = False
            return
        self._sync_amp(name, st)
        alloc = np.asarray(st.zone_alloc, np.float32)
        zone_free[idx] = alloc - np.asarray(st.zone_used, np.float32)
        zone_cap[idx] = alloc
        policy[idx] = int(st.policy)
        most[idx] = self._most_allocated(st)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(zone_free [N, Z, DN], zone_cap [N, Z, DN], policy [N]) aligned
        to snapshot rows. Unregistered nodes report zero capacity (always
        NUMA-feasible). Incrementally cached: rebuilding every row each
        scheduling cycle was the latency stream's dominant fixed cost.
        Callers must treat the returned arrays as read-only snapshots
        for immediate lowering."""
        epoch = self.snapshot.node_epoch
        n_bucket = self.snapshot.nodes.allocatable.shape[0]
        amp = self.snapshot.nodes.cpu_amp
        if (
            self._zone_cache is None
            or self._zone_epoch != epoch
            or self._zone_cache[0].shape[0] != n_bucket
        ):
            self._zone_cache = (
                np.zeros((n_bucket, self.max_zones, ZONE_DIMS), np.float32),
                np.zeros((n_bucket, self.max_zones, ZONE_DIMS), np.float32),
                np.zeros((n_bucket,), np.int8),
                np.zeros((n_bucket,), bool),
            )
            self._zone_epoch = epoch
            self._zone_dirty = set()
            for name in self._nodes:
                self._refresh_zone_row(name)
            self._amp_seen = amp.copy()
            self.lowered_version += 1
            self._scatter_full = True
            self._scatter_rows.clear()
        else:
            if self._amp_seen is None or not np.array_equal(
                self._amp_seen, amp
            ):
                # re-upserts don't bump node_epoch, but an amplification
                # change re-bases zone capacities — refresh changed rows
                changed = (
                    np.nonzero(amp != self._amp_seen)[0]
                    if self._amp_seen is not None
                    and self._amp_seen.shape == amp.shape
                    else range(min(len(amp), n_bucket))
                )
                for idx in changed:
                    try:
                        name = self.snapshot.node_name(int(idx))
                    except IndexError:
                        continue
                    if name in self._nodes:
                        self._zone_dirty.add(name)
                self._amp_seen = amp.copy()
            if self._zone_dirty:
                for name in self._zone_dirty:
                    self._refresh_zone_row(name)
                    idx = self.snapshot.node_id(name)
                    if idx is not None:
                        self._scatter_rows.add(int(idx))
                self._zone_dirty = set()
                self.lowered_version += 1
        return self._zone_cache[:3]

    def drain_lowered_dirty(self) -> Optional[np.ndarray]:
        """Snapshot row indices whose lowered zone rows changed since the
        last drain, or None for a full rebuild (see
        :func:`..plugins.drain_scatter_marks`). Call AFTER :meth:`arrays`
        (which flushes pending dirty names into the lowered cache)."""
        from . import drain_scatter_marks

        return drain_scatter_marks(self)

    def touch_lowered_rows(self, rows) -> None:
        """Mark lowered rows stale for the resident mirror WITHOUT a
        host-side change (anti-entropy scrubber heal path): the next
        resident refresh re-scatters host truth into exactly these
        rows."""
        self._scatter_rows.update(int(r) for r in rows)
        self.lowered_version += 1

    def most_allocated_rows(self) -> np.ndarray:
        """[N] bool MostAllocated zone-pick strategy per snapshot row
        (``_most_allocated`` resolution), for the solver's on-device zone
        selection; shares the zone-array cache refresh."""
        self.arrays()
        return self._zone_cache[3]

    @property
    def has_topology(self) -> bool:
        return bool(self._nodes)

    def policy_rows(self) -> np.ndarray:
        """int8 NUMA policy per snapshot row; -1 = unregistered node. The
        batched commit uses this to split winners into the vectorized
        no-NUMA path vs the per-winner exact-assignment path. Cached per
        snapshot node-epoch (rebuilt on register_node / node churn)."""
        epoch = self.snapshot.node_epoch
        if (
            self._policy_cache is not None
            and self._policy_cache_epoch == epoch
        ):
            return self._policy_cache
        n_bucket = self.snapshot.nodes.allocatable.shape[0]
        out = np.full((n_bucket,), -1, np.int8)
        for name, st in self._nodes.items():
            idx = self.snapshot.node_id(name)
            if idx is not None:
                out[idx] = int(st.policy)
        self._policy_cache = out
        self._policy_cache_epoch = epoch
        return out

    def _most_allocated(self, st: _NodeNUMA) -> bool:
        """Effective zone-pick strategy for a node: explicit label, else
        the plugin default derived from the scoring strategy
        (GetDefaultNUMAAllocateStrategy + GetNUMAAllocateStrategy)."""
        if st.numa_allocate_strategy == ext.NODE_NUMA_STRATEGY_MOST_ALLOCATED:
            return True
        if st.numa_allocate_strategy == ext.NODE_NUMA_STRATEGY_LEAST_ALLOCATED:
            return False
        return self.scoring_strategy == "MostAllocated"

    @staticmethod
    def _forced_bind_policy(st: _NodeNUMA):
        """LabelNodeCPUBindPolicy override, or None to use the pod's."""
        if st.node_bind_policy == ext.NODE_CPU_BIND_POLICY_FULL_PCPUS_ONLY:
            return CPUBindPolicy.FULL_PCPUS
        if st.node_bind_policy == ext.NODE_CPU_BIND_POLICY_SPREAD_BY_PCPUS:
            return CPUBindPolicy.SPREAD_BY_PCPUS
        return None

    # ---- per-winner exact assignment (PreBind) ----

    def allocate(self, pod: Pod, node_name: str) -> Optional[Mapping[str, str]]:
        """Commit a pod onto a node: choose a zone, take an exclusive cpuset
        if required, and return the resource-status annotation patch
        (``plugin.go:579-627``). Returns None when NUMA placement fails —
        the caller treats it like a failed Reserve."""
        requests = pod.spec.requests
        numa_spec = ext.parse_numa_topology_spec(pod.meta.annotations)
        payload = self.allocate_lowered(
            pod.meta.uid,
            pod.meta.annotations,
            node_name,
            float(requests.get(ext.RES_CPU, 0.0)),
            float(requests.get(ext.RES_MEMORY, 0.0)),
            wants_numa(pod),
            required=bool(
                numa_spec
                and numa_spec.get("numaTopologyPolicy") == "SingleNUMANode"
            ),
        )
        if payload is None:
            return None
        if not payload:
            return {}
        return {ext.ANNOTATION_RESOURCE_STATUS: payload}

    def allocate_lowered(
        self,
        uid: str,
        annotations: Mapping[str, str],
        node_name: str,
        cpu_milli: float,
        mem_mib: float,
        bind: bool,
        synced: bool = False,
        required: bool = False,
    ) -> Optional[str]:
        """Lean core of ``allocate`` for the batched commit: all request
        parsing is already lowered by the caller (BatchScheduler's chunk
        rows). Returns the resource-status JSON payload, ``""`` when there
        is nothing to record, or None on failed placement. ``synced=True``
        asserts the caller ran ``arrays()`` (which re-bases every node's
        amplification) earlier in the same single-threaded cycle, so the
        per-winner ratio re-sync is skipped."""
        st = self._nodes.get(node_name)
        if st is None:
            return ""
        if not synced:
            self._sync_amp(node_name, st)
        most_allocated = self._most_allocated(st)
        req0, req1 = cpu_milli, mem_mib
        # record the nominal bind charge for every bound pod — even at
        # ratio 1.0 — so a later annotation change can re-base it
        nominal_cpu = cpu_milli if bind else 0.0
        if bind and st.cpu_amp > 1.0:
            # zone capacities are amplified space: a bound pod's physical
            # cores charge ×ratio (AmplifyResourceList, plugin.go:636-640);
            # the accumulator below still takes the physical core count
            req0 = cpu_milli * st.cpu_amp
        zone = -1
        if st.policy == NUMAPolicy.SINGLE_NUMA_NODE or bind or required:
            # strategy-ordered fitting zone (pure-Python: Z is tiny and
            # this runs once per winner; ZONE_DIMS is fixed at 2)
            cpu_need = req0 - 1e-3
            mem_need = req1 - 1e-3
            best_util = None
            for z, alloc in enumerate(st.zone_alloc):
                used = st.zone_used[z]
                if alloc[0] - used[0] < cpu_need or alloc[1] - used[1] < mem_need:
                    continue
                util = (used[0] + 1.0) / (alloc[0] + 1.0)
                if (
                    best_util is None
                    or (util > best_util if most_allocated else util < best_util)
                ):
                    best_util = util
                    zone = z
            if zone < 0 and (
                st.policy == NUMAPolicy.SINGLE_NUMA_NODE or required
            ):
                return None

        cpuset_str = None
        if bind:
            n_cpus = int(cpu_milli // 1000)
            policy = self._forced_bind_policy(st)
            if policy is None:
                raw = annotations.get(ext.ANNOTATION_RESOURCE_SPEC)
                if raw:
                    try:
                        policy = CPUBindPolicy(
                            json.loads(raw).get("preferredCPUBindPolicy", "Default")
                        )
                    except (ValueError, KeyError, AttributeError, TypeError):
                        policy = CPUBindPolicy.DEFAULT
                else:
                    policy = CPUBindPolicy.DEFAULT
            cpuset = st.accumulator.take(
                uid,
                n_cpus,
                policy=policy,
                numa=zone if zone >= 0 else None,
            )
            if cpuset is None:
                return None
            cpuset_str = format_cpuset_sorted(sorted(cpuset))
        if zone >= 0:
            used = st.zone_used[zone]
            used[0] += req0
            used[1] += req1
            st.owners[uid] = (zone, [req0, req1], nominal_cpu)
        if zone >= 0 or cpuset_str is not None:
            self._mark_dirty(node_name)
        # hand-rendered resource-status JSON: json.dumps per winner was a
        # visible slice of the commit loop (payload shape is fixed)
        if cpuset_str is not None and zone >= 0:
            return (
                '{"cpuset": "%s", "numaNodeResources": [{"node": %d}]}'
                % (cpuset_str, zone)
            )
        if cpuset_str is not None:
            return '{"cpuset": "%s"}' % cpuset_str
        if zone >= 0:
            return '{"numaNodeResources": [{"node": %d}]}' % zone
        return ""

    def allocate_batch(
        self,
        uids: List[str],
        annotations: List[Mapping[str, str]],
        node_names: List[str],
        cpu_milli: List[float],
        mem_mib: List[float],
        bind: List[bool],
        required: Optional[List[bool]] = None,
        zones_hint: Optional[List[int]] = None,
    ) -> List[Optional[str]]:
        """Batched :meth:`allocate_lowered` over one chunk's winners in
        commit order (VERDICT r3 #1: the per-winner Python loop was the
        NUMA scenario's host wall). Winners are grouped by node — per-node
        state is independent, so only the order WITHIN a node matters and
        the input order is preserved there. Per node, the zone pick, zone
        charge and cpuset take run with node state hoisted out of the
        loop and cpusets taken through ``CPUAccumulator.take_bulk``.
        Assumes the caller ran ``arrays()`` earlier this cycle
        (``synced=True`` semantics of :meth:`allocate_lowered`).

        ``zones_hint`` (VERDICT r4 #4) carries the solver's ON-DEVICE
        zone picks (−1 = no zone): a hinted zone is fit-verified and
        used directly, skipping the strategy scan; a stale/unfit hint
        falls back to the host pick, so the hint is an accelerator,
        never a correctness dependency."""
        n = len(uids)
        results: List[Optional[str]] = [""] * n
        by_node: Dict[str, List[int]] = {}
        for i, name in enumerate(node_names):
            lst = by_node.get(name)
            if lst is None:
                by_node[name] = [i]
            else:
                lst.append(i)
        single = int(NUMAPolicy.SINGLE_NUMA_NODE)
        spec_key = ext.ANNOTATION_RESOURCE_SPEC
        default_pol = CPUBindPolicy.DEFAULT
        for name, rows_i in by_node.items():
            st = self._nodes.get(name)
            if st is None:
                continue
            self._mark_dirty(name)
            policy_single = int(st.policy) == single
            amp = st.cpu_amp
            zone_alloc = st.zone_alloc
            zone_used = st.zone_used
            owners = st.owners
            # node-level overrides (LabelNodeCPUBindPolicy /
            # LabelNodeNUMAAllocateStrategy); the unlabeled default
            # follows the scoring strategy (util.go:33-39)
            most_allocated = self._most_allocated(st)
            forced_pol = self._forced_bind_policy(st)
            # phase 1: zone pick + zone charge per winner (sequential
            # within the node — later winners see earlier charges)
            zones: List[int] = []
            reqs0: List[float] = []
            take_reqs = []
            take_rows: List[int] = []
            for i in rows_i:
                b = bind[i]
                req_single = required[i] if required is not None else False
                if not (policy_single or b or req_single):
                    zones.append(-1)
                    reqs0.append(0.0)
                    continue
                req0 = cpu_milli[i]
                if b and amp > 1.0:
                    req0 *= amp
                cpu_need = req0 - 1e-3
                mem_need = mem_mib[i] - 1e-3
                zone = None
                if zones_hint is not None:
                    hint = zones_hint[i]
                    if hint is not None and 0 <= hint < len(zone_alloc):
                        alloc_h = zone_alloc[hint]
                        used_h = zone_used[hint]
                        if (
                            alloc_h[0] - used_h[0] >= cpu_need
                            and alloc_h[1] - used_h[1] >= mem_need
                        ):
                            zone = hint
                    # hint == -1 (device saw no fitting zone) falls
                    # through to the host scan: the carried device table
                    # can be stale-pessimistic (host-rejected winners are
                    # not refunded into it mid-batch), and the hint must
                    # stay an accelerator, never a correctness dependency
                if zone is None:
                    best_util = None
                    zone = -1
                    for z, alloc in enumerate(zone_alloc):
                        used = zone_used[z]
                        if (
                            alloc[0] - used[0] < cpu_need
                            or alloc[1] - used[1] < mem_need
                        ):
                            continue
                        util = (used[0] + 1.0) / (alloc[0] + 1.0)
                        if (
                            best_util is None
                            or (
                                util > best_util
                                if most_allocated
                                else util < best_util
                            )
                        ):
                            best_util = util
                            zone = z
                if zone < 0 and (policy_single or req_single):
                    results[i] = None
                    zones.append(-2)        # rejected
                    reqs0.append(0.0)
                    continue
                zones.append(zone)
                reqs0.append(req0)
                if zone >= 0:
                    # charge now: the NEXT winner's pick must see it
                    used = zone_used[zone]
                    used[0] += req0
                    used[1] += mem_mib[i]
                if b:
                    if forced_pol is not None:
                        pol = forced_pol
                    else:
                        raw = annotations[i].get(spec_key)
                        if raw:
                            try:
                                pol = CPUBindPolicy(
                                    json.loads(raw).get(
                                        "preferredCPUBindPolicy", "Default"
                                    )
                                )
                            except (ValueError, KeyError, AttributeError, TypeError):
                                pol = default_pol
                        else:
                            pol = default_pol
                    take_reqs.append(
                        (
                            uids[i],
                            int(cpu_milli[i] // 1000),
                            pol,
                            zone if zone >= 0 else None,
                        )
                    )
                    take_rows.append(i)
            # phase 2: bulk cpuset takes for this node's bind winners
            if take_reqs:
                cpusets = st.accumulator.take_bulk(take_reqs)
            else:
                cpusets = []
            # phase 3: payloads + owner records (+ rollback of failed takes)
            k = 0
            for j, i in enumerate(rows_i):
                zone = zones[j]
                if zone == -2:
                    continue
                cpuset_str = None
                if bind[i]:
                    cpuset = cpusets[k]
                    k += 1
                    if cpuset is None:
                        # roll the zone charge back — nothing was taken
                        if zone >= 0:
                            used = zone_used[zone]
                            used[0] -= reqs0[j]
                            used[1] -= mem_mib[i]
                        results[i] = None
                        continue
                    cpuset_str = format_cpuset_sorted(sorted(cpuset))
                if zone >= 0:
                    owners[uids[i]] = (
                        zone,
                        [reqs0[j], mem_mib[i]],
                        cpu_milli[i] if bind[i] else 0.0,
                    )
                if cpuset_str is not None and zone >= 0:
                    results[i] = (
                        '{"cpuset": "%s", "numaNodeResources": [{"node": %d}]}'
                        % (cpuset_str, zone)
                    )
                elif cpuset_str is not None:
                    results[i] = '{"cpuset": "%s"}' % cpuset_str
                elif zone >= 0:
                    results[i] = '{"numaNodeResources": [{"node": %d}]}' % zone
        return results

    def reset_allocations(self) -> None:
        """Free every zone and cpuset hold (full-resync path)."""
        from ...core.topology import CPUAccumulator

        for st in self._nodes.values():
            st.zone_used = [[0.0] * ZONE_DIMS for _ in st.zone_alloc]
            st.owners.clear()
            st.accumulator = CPUAccumulator(st.topology)
        self._zone_cache = None

    def release(self, pod_uid: str, node_name: str) -> None:
        st = self._nodes.get(node_name)
        if st is None:
            return
        self._mark_dirty(node_name)
        st.accumulator.release(pod_uid)
        entry = st.owners.pop(pod_uid, None)
        if entry is not None:
            zone, req, _nominal = entry
            used = st.zone_used[zone]
            for d in range(ZONE_DIMS):
                used[d] -= req[d]

    # ---- exact-hold journal coverage (HA PR 6 satellite) ----

    def hold_of(self, pod_uid: str, node_name: str) -> Optional[dict]:
        """JSON-serializable snapshot of the pod's exact NUMA hold —
        zone charge (amplified request vector + bind-nominal CPU) and
        exclusive cpuset — for the write-ahead bind journal, so a
        takeover restores the hold bit-exactly via :meth:`restore_hold`
        instead of relying on a re-lower (which cannot recover WHICH
        zone/cpus were chosen)."""
        st = self._nodes.get(node_name)
        if st is None:
            return None
        entry = st.owners.get(pod_uid)
        cpus = st.accumulator.cpuset_of(pod_uid)
        if entry is None and not cpus:
            return None
        hold: dict = {}
        if entry is not None:
            zone, req, nominal = entry
            hold["zone"] = int(zone)
            hold["zreq"] = [float(x) for x in req]
            hold["znom"] = float(nominal)
        if cpus:
            hold["cpus"] = sorted(int(c) for c in cpus)
        return hold

    def restore_hold(self, pod_uid: str, node_name: str, hold: dict) -> None:
        """Re-install a journaled hold on a recovering instance
        (idempotent: a pod already holding on this node is left alone —
        the statehub resync may have re-registered it first)."""
        st = self._nodes.get(node_name)
        if st is None:
            return
        if pod_uid in st.owners or st.accumulator.cpuset_of(pod_uid):
            return
        self._mark_dirty(node_name)
        cpus = hold.get("cpus")
        if cpus:
            st.accumulator.take_reserved(pod_uid, {int(c) for c in cpus})
        zone = int(hold.get("zone", -1))
        if zone >= 0 and zone < len(st.zone_used):
            req = [float(x) for x in hold.get("zreq", [0.0] * ZONE_DIMS)]
            used = st.zone_used[zone]
            for d in range(min(ZONE_DIMS, len(req))):
                used[d] += req[d]
            st.owners[pod_uid] = (zone, req, float(hold.get("znom", 0.0)))
