"""Reservation plugin: capacity held by ghost pods, consumed by owners.

Rebuild of ``pkg/scheduler/plugins/reservation/`` + the frameworkext
reservation cache (``reservation_info.go:1-495``): a Reservation is
scheduled like a pod (the "reserve pod"), holds its capacity on the chosen
node, and later pods matching its owner selectors allocate *from* the
reservation instead of from node free capacity (the reference restores
reserved resources into NodeInfo via transformers before Filter;
here the ghost hold + pre-match commit achieves the same accounting).
AllocateOnce reservations are consumed whole; TTL expiry releases holds.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ...api import extension as ext

from ...api.types import (
    RESERVATION_ALLOCATE_POLICY_RESTRICTED,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
    ReservationOwner,
    ReservationPhase,
)

GHOST_PRIORITY = 9800  # reserve pods schedule in the prod band


def _ghost_uid(reservation: Reservation) -> str:
    return f"reservation-ghost/{reservation.meta.name}"


def reservation_from_operating_pod(pod: Pod) -> Reservation:
    """A Reservation view over a pod operating in Reservation mode
    (reference ``operating_pod.go`` + ``reservation_info.go``
    NewReservationInfoFromPod): requests are the pod's requests, owners
    come from the reservation-owners annotation."""
    owners = []
    for item in ext.parse_reservation_owners(pod.meta.annotations):
        if not isinstance(item, dict):
            continue
        selector = (item.get("labelSelector") or {}).get("matchLabels") or {}
        owners.append(
            ReservationOwner(
                label_selector=dict(selector),
                namespace=item.get("namespace"),
            )
        )
    return Reservation(
        meta=ObjectMeta(
            name=pod.meta.name,
            namespace=pod.meta.namespace,
            labels=dict(pod.meta.labels),
            annotations=dict(pod.meta.annotations),
        ),
        requests=dict(pod.spec.requests),
        owners=owners,
        allocate_once=True,
    )


def matches_owner(reservation: Reservation, pod: Pod) -> bool:
    """Owner matching (reference ``apis/scheduling/v1alpha1/reservation_types
    .go`` ReservationOwner: label selector and/or namespace)."""
    if not reservation.owners:
        return False
    for owner in reservation.owners:
        if not owner.label_selector and owner.namespace is None:
            continue  # an empty owner matches nothing, not everything
        if owner.namespace is not None and owner.namespace != pod.meta.namespace:
            continue
        if all(
            pod.meta.labels.get(k) == v
            for k, v in owner.label_selector.items()
        ):
            return True
    return False


class ResvView:
    """Pure overlay over the live reservation/snapshot state for the
    pipeline's dispatch-side fast-path PREVIEW (open the last speculation
    gates PR). Reads fall through to the live objects; predicted
    mutations accumulate in the overlay dicts only — the manager and the
    snapshot are never touched (the quota-preview purity discipline).
    A chained dispatch seeds its view from the upstream speculation's
    view (``clone``), so cycle N+1's preview runs against cycle N's
    PREDICTED post-fast-path state; the consuming cycle validates every
    prediction by value (``BatchScheduler._carry_consume_ok``) before a
    speculation built on this view may be kept."""

    __slots__ = (
        "mgr", "phase", "allocated", "owners", "ledger", "assumed",
        "node_req", "_cands", "_nom", "version",
    )

    def __init__(self, mgr: "ReservationManager"):
        self.mgr = mgr
        #: lazy per-PREVIEW candidate cache (see candidates()) — reset
        #: on clone so a carried view never serves a stale list
        self._cands: Optional[List[Reservation]] = None
        #: lazy vectorized nomination arrays over the overlay state
        #: (state-integrity PR satellite), invalidated by ``version``
        #: which bumps on every predicted mutation
        self._nom = None
        self.version = 0
        #: name -> predicted phase (terminal transitions)
        self.phase: Dict[str, ReservationPhase] = {}
        #: name -> predicted allocated dict (full copy once touched)
        self.allocated: Dict[str, Dict[str, float]] = {}
        #: name -> predicted current_owners list (full copy once touched)
        self.owners: Dict[str, List[str]] = {}
        #: name -> predicted owner ledger {uid: consumed} (copy on touch)
        self.ledger: Dict[str, Dict[str, Dict[str, float]]] = {}
        #: uid -> predicted assume entry (None = predicted forgotten);
        #: entries are (request_vec, estimate_vec, is_prod) host rows
        self.assumed: Dict[str, Optional[tuple]] = {}
        #: node idx -> predicted delta on snapshot.nodes.requested
        self.node_req: Dict[int, "np.ndarray"] = {}

    def clone(self) -> "ResvView":
        out = ResvView(self.mgr)
        out._nom = None
        out.version = 0
        out.phase = dict(self.phase)
        out.allocated = {k: dict(v) for k, v in self.allocated.items()}
        out.owners = {k: list(v) for k, v in self.owners.items()}
        out.ledger = {
            k: {u: dict(c) for u, c in v.items()}
            for k, v in self.ledger.items()
        }
        out.assumed = dict(self.assumed)
        out.node_req = {k: v.copy() for k, v in self.node_req.items()}
        out._cands = None
        return out

    # ---- overlay reads ----

    def phase_of(self, r: Reservation) -> ReservationPhase:
        return self.phase.get(r.meta.name, r.phase)

    def allocated_of(self, r: Reservation) -> Dict[str, float]:
        return self.allocated.get(r.meta.name, r.allocated)

    def owners_of(self, r: Reservation) -> List[str]:
        return self.owners.get(r.meta.name, r.current_owners)

    def assumed_entry(self, uid: str) -> Optional[tuple]:
        """Predicted (request, estimate, is_prod) for ``uid``'s snapshot
        assume, falling through to the live entry; None = no hold."""
        if uid in self.assumed:
            return self.assumed[uid]
        ap = self.mgr.scheduler.snapshot._assumed.get(uid)
        if ap is None or ap.absorbed:
            # absorbed pods carry no pending estimate; the fast path
            # never touches them — treat as no predictable hold
            return None if ap is None else (ap.request, None, ap.is_prod)
        return (ap.request, ap.estimate, ap.is_prod)

    def node_requested(self, idx: int) -> "np.ndarray":
        import numpy as np  # noqa: F811 — local like spill_fits_node

        row = self.mgr.scheduler.snapshot.nodes.requested[idx]
        delta = self.node_req.get(idx)
        return row if delta is None else row + delta

    # ---- overlay writes (predicted mutations) ----

    def _alloc_mut(self, r: Reservation) -> Dict[str, float]:
        return self.allocated.setdefault(r.meta.name, dict(r.allocated))

    def _owners_mut(self, r: Reservation) -> List[str]:
        return self.owners.setdefault(
            r.meta.name, list(r.current_owners)
        )

    def _ledger_mut(self, name: str) -> Dict[str, Dict[str, float]]:
        return self.ledger.setdefault(
            name,
            {
                u: dict(c)
                for u, c in self.mgr._owner_requests.get(name, {}).items()
            },
        )

    def add_node_delta(self, idx: int, delta: "np.ndarray") -> None:
        cur = self.node_req.get(idx)
        self.node_req[idx] = delta.copy() if cur is None else cur + delta

    def candidates(self) -> List[Reservation]:
        """The preview's candidate list, built ONCE per preview run (a
        clone resets the cache): rebuilding it inside every per-pod
        ``match`` call re-creates exactly the O(R)-per-pod re-validation
        hot spot ``begin_cycle``'s cycle cache exists to remove. Safe to
        cache for one preview: nothing under ``snapshot.lock`` adds
        reservations or removes nodes mid-preview, and predicted phase
        transitions are filtered per candidate by ``phase_of`` at use."""
        if self._cands is None:
            self._cands = self.mgr._preview_candidates(self)
        return self._cands


def _reservation_order(r: Reservation) -> Optional[int]:
    """Non-zero integer order label, or None (reference
    ``findMostPreferredReservationByOrder``: unparseable/zero = unordered)."""
    raw = r.meta.labels.get(ext.LABEL_RESERVATION_ORDER, "")
    if not raw:
        return None
    try:
        order = int(raw)
    except ValueError:
        return None
    return order if order != 0 else None


def _score_reservation(
    pod: Pod, r: Reservation, allocated: Optional[Dict[str, float]] = None
) -> float:
    """MostAllocated fit score over the reservation's own resource dims
    (reference ``scoring.go:196-209`` scoreReservation): mean of
    ``100·min(req+allocated ≤ cap)/cap``; dims the pod would overflow
    contribute 0. ``allocated`` substitutes the live ledger (the
    pipeline preview passes its overlay view's)."""
    if allocated is None:
        allocated = r.allocated
    resources = {k: v for k, v in r.requests.items() if v > 0}
    if not resources:
        return 0.0
    s = 0.0
    for k, cap in resources.items():
        req = pod.spec.requests.get(k, 0.0) + allocated.get(k, 0.0)
        # same epsilon as the match() capacity filter: float accumulation
        # noise must not zero the tightest dim of an exact-fit candidate
        if req <= cap + 1e-6:
            s += 100.0 * min(req, cap) / cap
    return s / len(resources)


class ReservationManager:
    """Schedules pending reservations as ghost pods and brokers matches."""

    def __init__(
        self,
        scheduler: "BatchScheduler",
        gc_duration_s: float = 24 * 3600.0,
        clock=None,
    ):
        import time as _t

        #: every reservation timestamp (available/terminal) and every
        #: default `now` comes from this one clock, so an injected
        #: simulated clock measures TTL/GC windows consistently
        self._clock = clock if clock is not None else _t.time
        self.scheduler = scheduler
        scheduler.reservations = self  # enable the pre-match commit path
        self._reservations: Dict[str, Reservation] = {}
        #: per-cycle Available candidate cache (see begin_cycle)
        self._cycle_candidates: Optional[List[Reservation]] = None
        self._cycle_epoch = -1
        #: bumped on ANY nomination-relevant mutation (phase, allocated,
        #: owners, requests, node assignment) — the vectorized match
        #: arrays (state-integrity PR satellite) key their cache on it
        self._ledger_version = 0
        #: (candidate list object, ledger version, arrays) — strong ref
        #: to the list keeps identity comparison sound
        self._nom_cache = None
        #: terminal reservations are deleted after this long (reference
        #: controller/garbage_collection.go, ReservationArgs.GCDuration)
        self.gc_duration_s = gc_duration_s
        #: reservation name -> {pod uid: requests at allocate time}, for
        #: owner-drift refunds (controller.go:221-260 syncStatus)
        self._owner_requests: Dict[str, Dict[str, Dict[str, float]]] = {}
        #: reservation name -> when it went FAILED/SUCCEEDED (GC base)
        self._terminal_time: Dict[str, float] = {}
        #: reservations backed by operating-mode pods: name -> the pod
        #: whose own assume IS the capacity hold (operating_pod.go)
        self._operating: Dict[str, Pod] = {}

    def _bump_ledger(self) -> None:
        """Invalidate the vectorized nomination arrays: call after ANY
        mutation of phase / allocated / owners / requests / node
        assignment (node CAPACITY rows are read live at match time and
        need no bump)."""
        self._ledger_version += 1
        self._nom_cache = None

    def add(self, reservation: Reservation) -> None:
        # a re-created name must not inherit the old incarnation's
        # terminal clock or owner ledger (premature GC / stale refunds)
        self._terminal_time.pop(reservation.meta.name, None)
        self._owner_requests.pop(reservation.meta.name, None)
        self._reservations[reservation.meta.name] = reservation
        self._cycle_candidates = None
        self._bump_ledger()

    def get(self, name: str) -> Optional[Reservation]:
        return self._reservations.get(name)

    def owner_ledger(self, name: str) -> Dict[str, Dict[str, float]]:
        """{pod uid: requests} recorded at allocate time for a
        reservation's live owners (read-only view for invariant checks)."""
        return dict(self._owner_requests.get(name, {}))

    def list(self) -> List[Reservation]:
        return list(self._reservations.values())

    def _hold_uid(self, r: Reservation) -> str:
        """Uid of the snapshot assume holding this reservation's capacity:
        the operating pod's own uid when the reservation IS a pod, the
        synthetic ghost uid otherwise."""
        op = self._operating.get(r.meta.name)
        return op.meta.uid if op is not None else _ghost_uid(r)

    def ingest_operating_pod(self, pod: Pod) -> Optional[Reservation]:
        """Register a Reservation-operating-mode pod as a reservation
        (reference ``pod_eventhandler.go``): a bound pod's existing assume
        becomes the capacity hold and the reservation is immediately
        Available; a pending pod registers Pending and becomes Available
        when its bind is ingested again."""
        if not ext.is_reservation_operating_mode(pod):
            return None
        r = self._reservations.get(pod.meta.name)
        if r is None:
            r = reservation_from_operating_pod(pod)
            # a pod already stamped with a current owner was consumed in a
            # previous incarnation (restart / resync after GC) — register
            # it Succeeded, never as fresh capacity (the annotation exists
            # precisely to make consumption durable, operating_pod.go:36)
            if pod.meta.annotations.get(
                ext.ANNOTATION_RESERVATION_CURRENT_OWNER
            ):
                r.allocated = dict(r.requests)
                self.add(r)
                self._operating[r.meta.name] = pod
                r.node_name = pod.spec.node_name
                self._set_terminal(r, ReservationPhase.SUCCEEDED)
                return r
            self.add(r)
        self._operating[r.meta.name] = pod
        if pod.spec.node_name and r.phase == ReservationPhase.PENDING:
            r.phase = ReservationPhase.AVAILABLE
            r.node_name = pod.spec.node_name
            r.available_time = self._clock()
            self._bump_ledger()
            # the pod's own charge is the hold — pin it against expiry
            if self.scheduler.snapshot.is_assumed(pod.meta.uid):
                self.scheduler.snapshot.confirm_pod(pod.meta.uid)
            self._cycle_candidates = None
        return r

    # ---- scheduling the reserve pods ----

    def _ghost_pod(self, r: Reservation) -> Pod:
        return Pod(
            meta=ObjectMeta(
                name=f"reserve-{r.meta.name}",
                namespace="koordinator-reservation",
                uid=_ghost_uid(r),
            ),
            spec=PodSpec(requests=dict(r.requests), priority=GHOST_PRIORITY),
        )

    def schedule_pending(self) -> int:
        """Run pending reservations through the solver; returns how many
        became Available (reference Bind updates Reservation status
        instead of pod binding, ``plugin.go:849-888``)."""
        pending = [
            r
            for r in self._reservations.values()
            if r.phase == ReservationPhase.PENDING
            # operating-pod reservations become Available through their
            # own pod's bind (ingest_operating_pod), never via a ghost —
            # a ghost here would double-charge and leak a confirmed hold
            and r.meta.name not in self._operating
        ]
        if not pending:
            return 0
        ghosts = {_ghost_uid(r): r for r in pending}
        outcome = self.scheduler.schedule([self._ghost_pod(r) for r in pending])

        self._cycle_candidates = None
        self._bump_ledger()
        for pod, node in outcome.bound:
            r = ghosts[pod.meta.uid]
            r.phase = ReservationPhase.AVAILABLE
            r.node_name = node
            r.available_time = self._clock()
            self._resize_to_allocation(r, pod)
            # the ghost hold's lifecycle is owned here, not by a
            # pod_assumed sync — without confirmation expire_assumed()
            # would silently drop an Available reservation's capacity
            self.scheduler.snapshot.confirm_pod(pod.meta.uid)
        return len(outcome.bound)

    def _resize_to_allocation(self, r: Reservation, ghost: Pod) -> None:
        """ResizePod extension point (reference
        ``frameworkext/framework_extender_factory.go:280-298`` +
        ``deviceshare/plugin.go:519-539``): after Reserve, a reserve pod
        that got a concrete device allocation has its allocatable resized
        to the allocated device resources
        (``UpdateReservePodWithAllocatable`` merge semantics — allocated
        names override, other requests stay). A reservation created with
        ``nvidia.com/gpu: 2`` thereby exposes
        ``koordinator.sh/gpu-memory-ratio: 200`` to owner matching. Gated
        on the ResizePod scheduler feature (``scheduler_features.go``)."""
        import json

        from ...utils.features import SCHEDULER_GATES

        if not SCHEDULER_GATES.enabled("ResizePod"):
            return
        raw = ghost.meta.annotations.get(ext.ANNOTATION_DEVICE_ALLOCATED)
        if not raw:
            return
        try:
            payload = json.loads(raw)
        except (ValueError, TypeError):
            return
        if not isinstance(payload, dict):
            return
        allocated: Dict[str, float] = {}
        for items in payload.values():
            if not isinstance(items, list):
                continue
            for item in items:
                if not isinstance(item, dict):
                    continue
                for name, qty in (item.get("resources") or {}).items():
                    try:
                        allocated[name] = allocated.get(name, 0.0) + float(qty)
                    except (TypeError, ValueError):
                        continue
        self._bump_ledger()  # requests (owner-matching capacity) mutate
        for name, qty in allocated.items():
            r.requests[name] = qty
        if ext.RES_GPU_MEMORY_RATIO in allocated:
            # the allocation IS the GPU capacity in normalized units —
            # keeping the raw nvidia.com/gpu dim too would double the
            # reservation's apparent GPU capacity for owner matching
            # (the reference normalizes GPU requests at PreFilter,
            # deviceshare/plugin.go preparePod)
            r.requests.pop(ext.RES_GPU, None)

    def expire(self, now: Optional[float] = None) -> List[str]:
        """Fail Available reservations past their TTL with no owners,
        releasing their holds. Returns the expired names."""
        now = now if now is not None else self._clock()
        expired: List[str] = []
        for r in list(self._reservations.values()):
            if (
                r.phase == ReservationPhase.AVAILABLE
                and r.ttl_s is not None
                and not r.current_owners
                and r.available_time is not None
                and now - r.available_time > r.ttl_s
            ):
                self.expire_reservation(r.meta.name)
                expired.append(r.meta.name)
        return expired

    # ---- owner matching / allocation ----

    def remaining(
        self, r: Reservation, view: Optional[ResvView] = None
    ) -> Dict[str, float]:
        alloc = r.allocated if view is None else view.allocated_of(r)
        return {
            k: v - alloc.get(k, 0.0) for k, v in r.requests.items()
        }

    def consumed_and_spill(
        self, r: Reservation, pod: Pod, view: Optional[ResvView] = None
    ) -> tuple[Dict[str, float], Dict[str, float]]:
        """Single source of truth for the allocate-policy arithmetic
        (reservation_types.go:78-97): per dim, ``consumed`` is what the
        owner takes FROM the reservation (min(request, remaining) of
        declared dims) and ``spill`` what must come from node free
        capacity (the Aligned overflow plus every undeclared dim). Used
        by candidate matching, the commit headroom check, and the
        allocation charge — they must never diverge."""
        remaining = self.remaining(r, view)
        consumed: Dict[str, float] = {}
        spill: Dict[str, float] = {}
        for k, v in pod.spec.requests.items():
            credit = (
                min(v, max(remaining.get(k, 0.0), 0.0))
                if k in r.requests
                else 0.0
            )
            if credit > 1e-9:
                consumed[k] = credit
            if v - credit > 1e-6:
                spill[k] = v - credit
        return consumed, spill

    def spill_fits_node(
        self,
        r: Reservation,
        spill: Dict[str, float],
        view: Optional[ResvView] = None,
    ) -> bool:
        """Whether the reservation's node has free capacity for the
        owner's spill (beyond every live hold, the ghost included).
        ``view`` substitutes the predicted requested row (the preview's
        node overlay — upstream speculative commits + predicted fast
        binds) for the live snapshot row."""
        if not spill:
            return True
        if r.node_name is None:
            return False
        snap = self.scheduler.snapshot
        idx = snap.node_id(r.node_name)
        if idx is None:
            return False
        import numpy as np

        na = snap.nodes
        requested = (
            na.requested[idx] if view is None else view.node_requested(idx)
        )
        return bool(
            na.schedulable[idx]
            and np.all(
                requested + snap.config.res_vector(spill)
                <= na.allocatable[idx] + 1e-3
            )
        )

    def match(
        self, pod: Pod, view: Optional[ResvView] = None
    ) -> Optional[Reservation]:
        """Nominate the best matching Available reservation for ``pod``
        (reference nominator, ``nominator.go:207-279`` + ``scoring.go``):
        collect every candidate whose owners match and whose remaining
        capacity covers the pod, then (1) a reservation carrying the
        smallest non-zero ``reservation-order`` label wins outright
        (``findMostPreferredReservationByOrder``), else (2) pick the
        highest MostAllocated fit score — mean over the reservation's
        resource dims of ``100·(pod request + already allocated)/
        allocatable`` (``scoreReservation``), i.e. the tightest fit, so
        small pods drain small reservations before fragmenting big ones.
        A pod carrying the reservation-affinity annotation additionally
        restricts the candidate set by name or reservation labels; a pod
        labeled reservation-ignored never matches (reservation.go:97-99).

        ``view`` (open the last gates PR) runs the SAME nomination
        against a pure overlay — predicted phases/allocations/owners and
        predicted node capacity — without touching the live per-cycle
        candidate cache; the pipeline's dispatch-side preview is exactly
        this call, so a preview and the consuming cycle's real match can
        only diverge when the state between them really changed (and the
        consume-time table comparison then discards the speculation).

        State-integrity PR satellite: the per-pod scan is VECTORIZED —
        numpy over the candidate axis for the capacity/spill/score
        arithmetic (the host hot spot at hundreds of live reservations,
        both on the serial drain and the fast-path preview), with the
        candidate matrices cached per (candidate list, ledger version)
        and owner selectors de-duplicated by signature.
        :meth:`_match_scalar` keeps the reference loop; the equivalence
        test holds them decision-identical over randomized populations.
        """
        return self._match_vector(pod, view)

    def _match_scalar(
        self, pod: Pod, view: Optional[ResvView] = None
    ) -> Optional[Reservation]:
        """Reference per-candidate loop (pre-vectorization semantics)."""
        if ext.is_reservation_ignored(pod):
            return None
        affinity = ext.parse_reservation_affinity(pod.meta.annotations)
        exact_names = ext.parse_exact_match_reservation_spec(
            pod.meta.annotations
        )
        best: Optional[Reservation] = None
        best_score = -1.0
        best_order: Optional[int] = None
        for r in (
            self._candidates() if view is None else view.candidates()
        ):
            phase = r.phase if view is None else view.phase_of(r)
            if phase != ReservationPhase.AVAILABLE:
                continue  # consumed earlier in this same cycle
            owners = (
                r.current_owners if view is None else view.owners_of(r)
            )
            if r.allocate_once and owners:
                continue
            if affinity is not None:
                name = affinity.get("name")
                if name:
                    if r.meta.name != name:
                        continue
                else:
                    selector = affinity.get("reservationSelector") or {}
                    if not all(
                        r.meta.labels.get(k) == v for k, v in selector.items()
                    ):
                        continue
            if not matches_owner(r, pod):
                continue
            # exact-match spec: the listed resource names must compare
            # exactly equal between the pod's requests and the
            # reservation's allocatable (transformer.go:122,138)
            if exact_names is not None and not ext.exact_match_reservation(
                pod.spec.requests, r.requests, exact_names
            ):
                continue
            # allocate-policy fit (reference plugin.go:405-415):
            # Restricted — dims the reservation DECLARES must fit within
            # its remaining capacity (fitsReservation, i.e. no spill on a
            # declared dim); Aligned/Default — the pod allocates from the
            # reservation first and may spill to node free capacity. A
            # candidate whose spill cannot fit its node is skipped HERE so
            # a drained-but-preferred reservation can never shadow a
            # feasible one (reviewer finding r3).
            consumed, spill = self.consumed_and_spill(r, pod, view)
            if r.allocate_policy == RESERVATION_ALLOCATE_POLICY_RESTRICTED:
                # restricted-options may narrow WHICH dims are binding
                # (reservation.go:89-96); default = every reserved dim
                restricted = ext.parse_reservation_restricted_resources(
                    r.meta.annotations
                )
                binding = (
                    set(restricted) & set(r.requests)
                    if restricted is not None
                    else set(r.requests)
                )
                if any(k in binding for k in spill):
                    continue
            if not self.spill_fits_node(r, spill, view):
                continue
            order = _reservation_order(r)
            if order is not None:
                if best_order is None or order < best_order:
                    best_order = order
                    best = r
                continue
            if best_order is not None:
                continue  # an ordered candidate always beats scored ones
            score = _score_reservation(
                pod, r, None if view is None else view.allocated_of(r)
            )
            if score > best_score or (
                score == best_score
                and best is not None
                and r.meta.name < best.meta.name
            ):
                best_score = score
                best = r
        return best

    # ---- vectorized nomination (state-integrity PR satellite) ----

    def _nom_arrays_for(self, cands: List[Reservation], view):
        """Candidate matrices for the vectorized scan, cached on
        (candidate list identity, ledger version[, view version]).
        Resource axis = sorted union of the candidates' declared keys;
        numeric dtype float64 end-to-end so every element op reproduces
        the scalar loop's python-float arithmetic bit-exactly."""
        import numpy as np

        if view is None:
            cache = self._nom_cache
            key = (cands, self._ledger_version)
            if cache is not None and cache[0] is key[0] and cache[1] == key[1]:
                return cache[2]
        else:
            cache = view._nom
            key = (cands, self._ledger_version, view.version)
            if (
                cache is not None
                and cache[0] is key[0]
                and cache[1:3] == key[1:3]
            ):
                return cache[3]
        snap = self.scheduler.snapshot
        keys = sorted({k for r in cands for k in r.requests})
        kpos = {k: i for i, k in enumerate(keys)}
        C, K = len(cands), len(keys)
        req = np.zeros((C, K))
        alloc = np.zeros((C, K))
        declared = np.zeros((C, K), bool)
        restricted = np.zeros((C, K), bool)
        node_idx = np.zeros((C,), np.int64)
        alloc_once = np.zeros((C,), bool)
        blocked = np.zeros((C,), bool)  # allocate_once & has owners
        order = np.full((C,), np.inf)
        has_order = np.zeros((C,), bool)
        names = [r.meta.name for r in cands]
        name_rank = np.empty((C,), np.int64)
        name_rank[sorted(range(C), key=lambda i: names[i])] = np.arange(C)
        #: distinct owner-selector signatures -> candidate rows (owner
        #: matching is string work; most fleets share a handful of
        #: selector shapes, so evaluate each ONCE per pod)
        sigs: Dict[tuple, List[int]] = {}
        for c, r in enumerate(cands):
            alloc_src = (
                r.allocated if view is None else view.allocated_of(r)
            )
            owners_src = (
                r.current_owners if view is None else view.owners_of(r)
            )
            for k, v in r.requests.items():
                req[c, kpos[k]] = float(v)
                declared[c, kpos[k]] = True
            for k, v in alloc_src.items():
                if k in kpos:
                    alloc[c, kpos[k]] = float(v)
            idx = (
                snap.node_id(r.node_name)
                if r.node_name is not None
                else None
            )
            node_idx[c] = -1 if idx is None else int(idx)
            alloc_once[c] = bool(r.allocate_once)
            blocked[c] = bool(r.allocate_once and owners_src)
            o = _reservation_order(r)
            if o is not None:
                order[c] = float(o)
                has_order[c] = True
            if r.allocate_policy == RESERVATION_ALLOCATE_POLICY_RESTRICTED:
                opts = ext.parse_reservation_restricted_resources(
                    r.meta.annotations
                )
                binding = (
                    set(opts) & set(r.requests)
                    if opts is not None
                    else set(r.requests)
                )
                for k in binding:
                    restricted[c, kpos[k]] = True
            sig = tuple(
                (
                    o.namespace,
                    tuple(sorted(o.label_selector.items())),
                )
                for o in r.owners
            )
            sigs.setdefault(sig, []).append(c)
        #: union key -> config-resource column (None = not a node dim)
        cfg_col = {
            k: (
                list(snap.config.resources).index(k)
                if k in snap.config.resources
                else None
            )
            for k in keys
        }
        arrays = {
            "cands": cands, "keys": keys, "kpos": kpos,
            "req": req, "alloc": alloc, "declared": declared,
            "restricted": restricted, "node_idx": node_idx,
            "alloc_once": alloc_once, "blocked": blocked,
            "order": order, "has_order": has_order,
            "names": names, "name_rank": name_rank, "sigs": sigs,
            "cfg_col": cfg_col,
        }
        if view is None:
            self._nom_cache = (cands, self._ledger_version, arrays)
        else:
            view._nom = (
                cands, self._ledger_version, view.version, arrays
            )
        return arrays

    @staticmethod
    def _sig_matches(sig: tuple, pod: Pod) -> bool:
        """`matches_owner` over one de-duplicated selector signature."""
        for ns, items in sig:
            if not items and ns is None:
                continue  # an empty owner matches nothing
            if ns is not None and ns != pod.meta.namespace:
                continue
            if all(pod.meta.labels.get(k) == v for k, v in items):
                return True
        return False

    def _match_vector(
        self, pod: Pod, view: Optional[ResvView] = None
    ) -> Optional[Reservation]:
        import numpy as np

        if ext.is_reservation_ignored(pod):
            return None
        cands = self._candidates() if view is None else view.candidates()
        if not cands:
            return None
        A = self._nom_arrays_for(cands, view)
        C = len(cands)
        snap = self.scheduler.snapshot
        # ---- eligibility over the candidate axis ----
        ok = ~A["blocked"]
        if view is not None:
            # predicted phase transitions (consumed earlier this chain)
            for c, r in enumerate(cands):
                if ok[c] and view.phase_of(r) != ReservationPhase.AVAILABLE:
                    ok[c] = False
        else:
            # a candidate consumed earlier in this same cycle flipped
            # terminal, which bumped the ledger version and rebuilt the
            # arrays — but guard against direct phase pokes too
            for c, r in enumerate(cands):
                if ok[c] and r.phase != ReservationPhase.AVAILABLE:
                    ok[c] = False
        affinity = ext.parse_reservation_affinity(pod.meta.annotations)
        if affinity is not None:
            name = affinity.get("name")
            if name:
                ok &= np.fromiter(
                    (n == name for n in A["names"]), bool, count=C
                )
            else:
                selector = affinity.get("reservationSelector") or {}
                for c, r in enumerate(cands):
                    if ok[c] and not all(
                        r.meta.labels.get(k) == v
                        for k, v in selector.items()
                    ):
                        ok[c] = False
        exact_names = ext.parse_exact_match_reservation_spec(
            pod.meta.annotations
        )
        if exact_names is not None:
            for c, r in enumerate(cands):
                if ok[c] and not ext.exact_match_reservation(
                    pod.spec.requests, r.requests, exact_names
                ):
                    ok[c] = False
        # owner matching, one evaluation per distinct selector signature
        owner_ok = np.zeros((C,), bool)
        for sig, rows in A["sigs"].items():
            if self._sig_matches(sig, pod):
                owner_ok[rows] = True
        ok &= owner_ok
        if not ok.any():
            return None
        # ---- allocate-policy arithmetic, vectorized ----
        # (same element ops as consumed_and_spill: float64 min/max/cmp,
        # so filter decisions are bit-identical to the scalar loop)
        keys, kpos = A["keys"], A["kpos"]
        pod_vec = np.zeros((len(keys),))
        extra_spill: Dict[str, float] = {}
        for k, v in pod.spec.requests.items():
            if k in kpos:
                pod_vec[kpos[k]] = float(v)
            elif float(v) > 1e-6:
                extra_spill[k] = float(v)  # undeclared everywhere
        remaining = A["req"] - A["alloc"]
        credit = np.minimum(
            pod_vec[None, :], np.maximum(remaining, 0.0)
        ) * A["declared"]
        spill = pod_vec[None, :] - credit
        spill[spill <= 1e-6] = 0.0
        # Restricted: no spill on a binding dim
        ok &= ~((spill > 0.0) & A["restricted"]).any(axis=1)
        # ---- node-fit for the spill (live node rows; view deltas) ----
        has_spill = spill.any(axis=1) | bool(extra_spill)
        need = ok & has_spill
        if need.any():
            na = snap.nodes
            idxs = A["node_idx"]
            valid = idxs >= 0
            ok &= valid | ~has_spill
            need &= valid
            if need.any():
                D = len(snap.config.resources)
                spill_cfg = np.zeros((C, D), np.float32)
                for k, col in A["cfg_col"].items():
                    if col is not None:
                        spill_cfg[:, col] += spill[:, kpos[k]].astype(
                            np.float32
                        )
                if extra_spill:
                    extra_vec = snap.config.res_vector(extra_spill)
                    spill_cfg += extra_vec[None, :]
                rows = idxs[need]
                fits = np.zeros((C,), bool)
                fits[need] = na.schedulable[rows] & np.all(
                    na.requested[rows] + spill_cfg[need]
                    <= na.allocatable[rows] + 1e-3,
                    axis=1,
                )
                if view is not None and view.node_req:
                    # patch the few overlaid rows with predicted deltas
                    for c in np.nonzero(need)[0]:
                        delta = view.node_req.get(int(idxs[c]))
                        if delta is None:
                            continue
                        fits[c] = bool(
                            na.schedulable[idxs[c]]
                            and np.all(
                                na.requested[idxs[c]]
                                + delta
                                + spill_cfg[c]
                                <= na.allocatable[idxs[c]] + 1e-3
                            )
                        )
                ok &= fits | ~has_spill
        if not ok.any():
            return None
        # ---- order label dominates; else MostAllocated score ----
        ordered = ok & A["has_order"]
        if ordered.any():
            vals = np.where(ordered, A["order"], np.inf)
            return cands[int(np.argmin(vals))]  # first index on ties
        cap = A["req"]
        pos = A["declared"] & (cap > 0.0)
        denom = pos.sum(axis=1)
        req_tot = pod_vec[None, :] + A["alloc"]
        with np.errstate(divide="ignore", invalid="ignore"):
            term = np.where(
                pos & (req_tot <= cap + 1e-6),
                100.0 * np.minimum(req_tot, cap) / np.where(
                    cap > 0.0, cap, 1.0
                ),
                0.0,
            )
        score = np.where(denom > 0, term.sum(axis=1), 0.0) / np.maximum(
            denom, 1
        )
        score = np.where(ok, score, -np.inf)
        best = score.max()
        tied = np.nonzero(score == best)[0]
        # exact-equality tie-break: lexicographically smallest name
        return cands[int(tied[np.argmin(A["name_rank"][tied])])]

    def begin_cycle(self) -> None:
        """Cache the Available candidate set for one scheduling cycle
        (r1 weak item: the per-pod ``match`` scan re-checked phase and
        node liveness for EVERY reservation on EVERY pod — with a large
        reservation population that was a host hot spot in exactly the
        regime the TPU rebuild wins). Dead-node reservations are failed
        here, once."""
        candidates: List[Reservation] = []
        for r in self._reservations.values():
            if r.phase != ReservationPhase.AVAILABLE or r.node_name is None:
                continue
            if self.scheduler.snapshot.node_id(r.node_name) is None:
                # node removed from the cluster: the ghost hold died with
                # it (remove_node purges assumed pods) — fail the
                # reservation instead of nominating a dead node
                self._set_terminal(r, ReservationPhase.FAILED)
                continue
            candidates.append(r)
        self._cycle_candidates = candidates
        self._cycle_epoch = self.scheduler.snapshot.node_epoch

    def _candidates(self) -> List[Reservation]:
        """The cycle cache, rebuilt whenever the snapshot's node topology
        changed since it was built (node_epoch): a direct ``match()``
        after a node-remove delta must never nominate a dead node, and
        the common path pays zero per-pod re-validation."""
        if (
            self._cycle_candidates is None
            or self._cycle_epoch != self.scheduler.snapshot.node_epoch
        ):
            self.begin_cycle()
        return self._cycle_candidates

    def _preview_candidates(self, view: ResvView) -> List[Reservation]:
        """Pure analog of :meth:`_candidates` for the pipeline preview:
        Available (per the view's predicted phases) reservations on live
        nodes. Dead-node reservations are SKIPPED, never failed — the
        terminal transition belongs to the consuming cycle's
        ``begin_cycle`` (and a removed node bumps ``node_epoch``, which
        discards the speculation before any prediction here matters)."""
        snap = self.scheduler.snapshot
        return [
            r
            for r in self._reservations.values()
            if view.phase_of(r) == ReservationPhase.AVAILABLE
            and r.node_name is not None
            and snap.node_id(r.node_name) is not None
        ]

    def has_available(self) -> bool:
        """Any Available reservation at all — the cheap speculation-gate
        input: with none, the fast path cannot bind and a preview is
        trivially empty (NUMA/device ghost-hold swaps unreachable)."""
        return any(
            r.phase == ReservationPhase.AVAILABLE
            for r in self._reservations.values()
        )

    def is_operating_backed(self, name: str) -> bool:
        return name in self._operating

    def table_view(self, view: Optional[ResvView] = None) -> tuple:
        """Canonical by-value lowering of the reservation ledger —
        phase, node, requests, allocated, owners and the owner-request
        ledger per reservation, name-sorted. This is what the pipeline's
        consume-time validation compares: the dispatch-time table, the
        predicted post-fast-path table (``view`` applies the preview's
        overlays) and the live table after the real fast path ran must
        all line up bit-exactly or the speculation is discarded —
        allocated values are produced by the same float arithmetic on
        both sides, so equality is exact, not approximate."""
        out = []
        for name in sorted(self._reservations):
            r = self._reservations[name]
            if view is None:
                phase, alloc, owners = r.phase, r.allocated, r.current_owners
                ledger = self._owner_requests.get(name, {})
            else:
                phase = view.phase_of(r)
                alloc = view.allocated_of(r)
                owners = view.owners_of(r)
                ledger = view.ledger.get(
                    name, self._owner_requests.get(name, {})
                )
            out.append((
                name,
                phase.value,
                r.node_name,
                bool(r.allocate_once),
                tuple(sorted((k, float(v)) for k, v in r.requests.items())),
                tuple(sorted((k, float(v)) for k, v in alloc.items())),
                tuple(owners),
                tuple(sorted(
                    (uid, tuple(sorted((k, float(v)) for k, v in c.items())))
                    for uid, c in ledger.items()
                )),
            ))
        return tuple(out)

    def preview_allocate(
        self, reservation: Reservation, pod: Pod, view: ResvView
    ) -> List[tuple]:
        """Pure mirror of :meth:`allocate` against the overlay view: the
        predicted ledger mutations land in ``view`` and the predicted
        SNAPSHOT effects (ghost forget, remainder-ghost assume) are
        returned as ``(node_idx, d_requested, d_estimated, d_prod)``
        delta rows for the dispatch to fold into the chained node table.
        Callers must have refused operating-pod-backed reservations and
        NUMA/device-bearing configs already (their ghost-hold swaps are
        host-allocator decisions a pure preview cannot reproduce).
        Divergence between this arithmetic and the real ``allocate`` is
        caught by the consume-time ``table_view`` comparison — the
        predicted post table is built HERE, the actual one by the real
        call, and a kept speculation requires them equal."""
        import numpy as np

        assert reservation.meta.name not in self._operating
        view.version += 1
        view._nom = None
        snap = self.scheduler.snapshot
        node = reservation.node_name
        idx = snap.node_id(node)
        assert idx is not None
        name = reservation.meta.name
        consumed, _spill = self.consumed_and_spill(reservation, pod, view)
        alloc = view._alloc_mut(reservation)
        for k, take in consumed.items():
            alloc[k] = alloc.get(k, 0.0) + take
        view._owners_mut(reservation).append(pod.meta.uid)
        view._ledger_mut(name)[pod.meta.uid] = dict(consumed)
        deltas: List[tuple] = []
        # the full ghost hold is forgotten (allocate's snap.forget_pod)
        hold_uid = _ghost_uid(reservation)
        entry = view.assumed_entry(hold_uid)
        if entry is not None:
            req, est, is_prod = entry
            d_est = np.zeros_like(req) if est is None else -est
            deltas.append((
                idx, -req, d_est, d_est if is_prod else np.zeros_like(req)
            ))
            view.add_node_delta(idx, -req)
        view.assumed[hold_uid] = None
        if reservation.allocate_once:
            view.allocated[name] = dict(reservation.requests)
            view.phase[name] = ReservationPhase.SUCCEEDED
        else:
            ghost = self._remainder_ghost(reservation, view)
            if ghost.spec.requests:
                # assume_pod(ghost, node): request = estimate = the
                # remainder vector, no CPU-bind amplification (ghosts
                # carry no bind annotation), prod band per GHOST_PRIORITY
                vec = snap.config.res_vector(ghost.spec.requests)
                is_prod = (
                    ghost.priority_class == ext.PriorityClass.PROD
                )
                deltas.append((
                    idx, vec, vec, vec if is_prod else np.zeros_like(vec)
                ))
                view.add_node_delta(idx, vec)
                view.assumed[hold_uid] = (vec, vec, is_prod)
        return deltas

    def release_ghost_holds(self, reservation: Reservation) -> None:
        """Release the ghost's per-winner NUMA/device allocations (the
        reservation's reserved cpuset + device minors). Called before an
        owner pod's own Reserve so it can take the freed minors — the
        reference restores reserved device resources into the node state
        for owners the same way (deviceshare Reservation hooks)."""
        node = reservation.node_name
        if node is None:
            return
        uid = self._hold_uid(reservation)
        if getattr(self.scheduler, "devices", None) is not None:
            self.scheduler.devices.release(uid, node)
        if getattr(self.scheduler, "numa", None) is not None:
            self.scheduler.numa.release(uid, node)

    def _remainder_ghost(
        self, reservation: Reservation, view: Optional[ResvView] = None
    ) -> Pod:
        """Ghost pod sized to the reservation's unconsumed remainder."""
        ghost = self._ghost_pod(reservation)
        ghost.spec.requests = {
            k: v
            for k, v in self.remaining(reservation, view).items()
            if v > 1e-6
        }
        return ghost

    def reacquire_ghost_holds(self, reservation: Reservation) -> None:
        """Strict inverse of ``release_ghost_holds`` after a failed owner
        commit: re-take the NUMA/device holds the ghost actually had. A
        partially-consumed reservation holds none (``allocate`` does not
        re-hold device/NUMA remainders — see its docstring), so this is a
        no-op once any owner has allocated. The scheduling cycle is
        single-threaded, so re-taking the just-released capacity (the
        owner's partial allocations were rolled back first) succeeds."""
        node = reservation.node_name
        if node is None or reservation.current_owners:
            return
        ghost = self._remainder_ghost(reservation)
        # re-take under the SAME uid release_ghost_holds released —
        # the operating pod's own uid when the reservation is a pod
        ghost.meta.uid = self._hold_uid(reservation)
        if getattr(self.scheduler, "numa", None) is not None:
            self.scheduler.numa.allocate(ghost, node)
        if getattr(self.scheduler, "devices", None) is not None:
            self.scheduler.devices.allocate(ghost, node)

    def allocate(self, reservation: Reservation, pod: Pod) -> str:
        """Commit a pod against a reservation.

        The full ghost hold is forgotten, the pod is assumed normally by
        the caller, and (unless AllocateOnce) a new ghost hold is assumed
        for the remainder — all through the snapshot's assume/forget API so
        node accounting stays consistent. Device/NUMA remainders are NOT
        re-held: a reservation carrying device minors is consumed whole
        (AllocateOnce semantics, the device-reservation mode the reference
        migration path uses). Returns the node name."""
        node = reservation.node_name
        assert node is not None
        snap = self.scheduler.snapshot
        self.release_ghost_holds(reservation)
        # The owner consumes min(request, remaining) of each dim the
        # reservation DECLARES (Aligned/Restricted alike — the Aligned
        # spill beyond remaining, and any undeclared dim, is the pod's
        # own node charge, headroom-checked by the commit path).
        consumed, _spill = self.consumed_and_spill(reservation, pod)
        self._bump_ledger()
        for k, take in consumed.items():
            reservation.allocated[k] = reservation.allocated.get(k, 0.0) + take
        reservation.current_owners.append(pod.meta.uid)
        # stamp WHICH reservation the pod allocated from (reference
        # SetReservationAllocated at PreBind, reservation.go:121-128)
        pod.meta.annotations[ext.ANNOTATION_RESERVATION_ALLOCATED] = (
            '{"name": "%s"}' % reservation.meta.name
        )
        # the ledger records what was taken FROM the reservation — the
        # drift refund restores exactly this much
        self._owner_requests.setdefault(reservation.meta.name, {})[
            pod.meta.uid
        ] = consumed
        op = self._operating.get(reservation.meta.name)
        if op is not None and snap.is_assumed(op.meta.uid):
            # The RUNNING placeholder's physical footprint does not shrink
            # because a (possibly smaller) owner consumed the reservation —
            # the reference keeps the reserve pod charged and discounts the
            # owner inside the reservation. Keep the node charged
            # max(placeholder, owner): swap the pod's full assume for the
            # remainder the owners do not cover; that remainder frees only
            # when the placeholder pod itself is forgotten/deleted.
            remainder = {
                k: v - reservation.allocated.get(k, 0.0)
                for k, v in reservation.requests.items()
                if v - reservation.allocated.get(k, 0.0) > 1e-6
            }
            snap.forget_pod(op.meta.uid)
            if remainder:
                vec = snap.config.res_vector(remainder)
                snap.assume_pod(op, node, vec, confirmed=True, request=vec)
        else:
            snap.forget_pod(self._hold_uid(reservation))
        if op is not None:
            # record the allocation on the operating pod
            # (AnnotationReservationCurrentOwner, operating_pod.go:36)
            import json as _json

            op.meta.annotations[ext.ANNOTATION_RESERVATION_CURRENT_OWNER] = (
                _json.dumps(
                    {"namespace": pod.meta.namespace, "name": pod.meta.name}
                )
            )
        if reservation.allocate_once:
            reservation.allocated = dict(reservation.requests)
            self._set_terminal(reservation, ReservationPhase.SUCCEEDED)
        else:
            ghost = self._remainder_ghost(reservation)
            if ghost.spec.requests:
                snap.assume_pod(ghost, node)
        return node

    def remove_operating_pod(self, pod_name: str) -> None:
        """Ingest the deletion of a Reservation-operating-mode pod: its
        physical footprint is gone, so its charge (full or remainder) and
        its NUMA/device holds are dropped, and a still-open reservation it
        backed is failed (the pod was the capacity). Live owners keep
        their own assumes — the node charge degrades from
        max(placeholder, owners) to sum(owners) exactly at pod death."""
        op = self._operating.pop(pod_name, None)
        if op is None:
            return
        snap = self.scheduler.snapshot
        r = self._reservations.get(pod_name)
        node = r.node_name if r is not None else op.spec.node_name
        snap.forget_pod(op.meta.uid)
        if node is not None:
            if getattr(self.scheduler, "devices", None) is not None:
                self.scheduler.devices.release(op.meta.uid, node)
            if getattr(self.scheduler, "numa", None) is not None:
                self.scheduler.numa.release(op.meta.uid, node)
        if r is not None and r.phase in (
            ReservationPhase.PENDING,
            ReservationPhase.AVAILABLE,
        ):
            self._set_terminal(r, ReservationPhase.FAILED)
            self._cycle_candidates = None

    def expire_reservation(self, name: str) -> bool:
        """Explicitly fail/expire a reservation, releasing its hold."""
        r = self._reservations.get(name)
        if r is None or r.phase not in (
            ReservationPhase.PENDING,
            ReservationPhase.AVAILABLE,
        ):
            return False
        if r.phase == ReservationPhase.AVAILABLE:
            if r.meta.name in self._operating:
                # pod-backed hold: the placeholder pod is still RUNNING on
                # the node — forgetting its charge (or freeing its cpuset/
                # minors) would advertise phantom capacity the kubelet is
                # still committing. Expiry only stops the reservation from
                # matching; the charge lives until the pod itself goes.
                pass
            else:
                self.release_ghost_holds(r)
                self.scheduler.snapshot.forget_pod(self._hold_uid(r))
        self._set_terminal(r, ReservationPhase.FAILED)
        return True

    def _set_terminal(self, r: Reservation, phase: ReservationPhase) -> None:
        # callers only transition from non-terminal phases, so overwrite —
        # setdefault would keep a GC'd-then-recreated name's old clock
        r.phase = phase
        self._terminal_time[r.meta.name] = self._clock()
        self._bump_ledger()

    def sync(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        """The reservation controller's periodic sweep (reference
        ``plugins/reservation/controller/``): expire TTL'd reservations,
        reconcile owner drift, and garbage-collect terminal ones.

        Owner drift (``controller.go:221-260`` syncStatus): an owner pod
        that vanished (no longer assumed in the snapshot) refunds its
        allocation, and the freed remainder is re-held by the ghost so
        other pods can't steal reserved capacity.

        GC (``garbage_collection.go:38-55``): Failed/Succeeded
        reservations older than ``gc_duration_s`` are deleted.
        Returns {"expired": [...], "drifted": [...], "deleted": [...]}."""
        now = now if now is not None else self._clock()
        report: Dict[str, List[str]] = {
            "expired": self.expire(now),
            "drifted": [],
            "deleted": [],
        }
        snap = self.scheduler.snapshot
        for r in self._reservations.values():
            if r.phase != ReservationPhase.AVAILABLE or not r.current_owners:
                continue
            gone = [u for u in r.current_owners if not snap.is_assumed(u)]
            if not gone:
                continue
            ledger = self._owner_requests.get(r.meta.name, {})
            for uid in gone:
                refund = ledger.pop(uid, {})
                for k, v in refund.items():
                    r.allocated[k] = max(r.allocated.get(k, 0.0) - v, 0.0)
                r.current_owners.remove(uid)
                # the dead owner's exact device/NUMA holds must free too —
                # match() re-offers this capacity, and a stuck minor would
                # fail every future owner's Reserve (the eviction path
                # releases the same four holds)
                if getattr(self.scheduler, "devices", None) is not None:
                    self.scheduler.devices.release(uid, r.node_name)
                if getattr(self.scheduler, "numa", None) is not None:
                    self.scheduler.numa.release(uid, r.node_name)
            # re-hold the freed remainder so it stays reserved
            snap.forget_pod(_ghost_uid(r))  # ghost remainder, never the
            # operating pod itself (its consumption forgot it already)
            ghost = self._remainder_ghost(r)
            if ghost.spec.requests:
                snap.assume_pod(ghost, r.node_name)
            report["drifted"].append(r.meta.name)
            self._cycle_candidates = None
            self._bump_ledger()
        # pod-backed SUCCEEDED reservations: an owner that died before the
        # still-RUNNING placeholder must re-expand the placeholder's charge
        # — without this, owner death leaves the node charged only the
        # remainder while the kubelet still commits the full placeholder
        # (the max(placeholder, owners) invariant, reviewer finding r3)
        # (the placeholder is presumed RUNNING until its delete is
        # ingested via remove_operating_pod — after full consumption it
        # holds no assume, so is_assumed can't be the liveness signal)
        for name, op in list(self._operating.items()):
            r = self._reservations.get(name)
            if (
                r is None
                or r.phase != ReservationPhase.SUCCEEDED
                or r.node_name is None
            ):
                continue
            ledger = self._owner_requests.get(name, {})
            gone = [u for u in ledger if not snap.is_assumed(u)]
            if not gone:
                continue
            for uid in gone:
                ledger.pop(uid, None)
                if uid in r.current_owners:
                    r.current_owners.remove(uid)
            remainder = dict(r.requests)
            for owner_req in ledger.values():
                for k, v in owner_req.items():
                    remainder[k] = remainder.get(k, 0.0) - v
            remainder = {k: v for k, v in remainder.items() if v > 1e-6}
            snap.forget_pod(op.meta.uid)
            if remainder:
                vec = snap.config.res_vector(remainder)
                snap.assume_pod(
                    op, r.node_name, vec, confirmed=True, request=vec
                )
            report["drifted"].append(name)
        for name, t0 in list(self._terminal_time.items()):
            r = self._reservations.get(name)
            if r is None:
                del self._terminal_time[name]
                continue
            if r.phase in (
                ReservationPhase.FAILED,
                ReservationPhase.SUCCEEDED,
            ) and now - t0 > self.gc_duration_s:
                del self._reservations[name]
                del self._terminal_time[name]
                self._owner_requests.pop(name, None)
                self._operating.pop(name, None)
                self._cycle_candidates = None
                self._bump_ledger()
                report["deleted"].append(name)
        return report
