"""ElasticQuota host-side manager: hierarchical quota tree + fair sharing.

Rebuild of the reference's GroupQuotaManager
(``pkg/scheduler/plugins/elasticquota/core/group_quota_manager.go:37-95``)
and RuntimeQuotaCalculator (``core/runtime_quota_calculator.go``): quotas
form trees via the ``quota.scheduling.koordinator.sh/parent`` label; each
parent's runtime is distributed to children as

    runtime = guaranteed(min ∧ request) + weighted fair share of the
              remainder (sharedWeight), capped by max ∧ request

via iterative water-filling (children hitting their cap release surplus to
the rest — the reference's refreshRuntime loop). Admission (used + request
≤ runtime along the chain) runs vectorized inside the solver
(``ops.solver._quota_commit``); this class owns the tree, the runtime
refresh, and durable used accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...api import extension as ext
from ...api.types import ElasticQuota, Pod
from ...core.snapshot import SnapshotConfig

#: maximum quota tree depth lowered to the solver (leaf..root)
MAX_LEVELS = 4
ROOT = ""  # pseudo-parent of tree roots


def quota_name_of(pod: Pod) -> Optional[str]:
    return pod.meta.labels.get(ext.LABEL_QUOTA_NAME)


def water_fill(
    total: np.ndarray,
    guaranteed: np.ndarray,
    caps: np.ndarray,
    weights: np.ndarray,
) -> np.ndarray:
    """Distribute ``total`` [D] among C children: each gets ``guaranteed``
    [C, D] first, the remainder proportionally to ``weights`` [C, D] capped
    by ``caps`` [C, D]. Iterative water-filling, per dim, ≤ C passes
    (each pass saturates at least one child or exhausts the pool)."""
    c, d = guaranteed.shape
    runtime = np.minimum(guaranteed, caps).astype(np.float64)
    remaining = np.maximum(total - runtime.sum(axis=0), 0.0).astype(np.float64)
    for _ in range(c):
        need = np.maximum(caps - runtime, 0.0)
        active = need > 1e-9
        w = np.where(active, np.maximum(weights, 0.0), 0.0)
        wsum = w.sum(axis=0)
        distributable = (remaining > 1e-9) & (wsum > 1e-9)
        if not distributable.any():
            break
        give = np.where(
            distributable[None, :], remaining[None, :] * w / np.maximum(wsum, 1e-9), 0.0
        )
        inc = np.minimum(give, need)
        runtime += inc
        remaining = remaining - inc.sum(axis=0)
    return runtime.astype(np.float32)


@dataclasses.dataclass
class _QuotaNode:
    quota: ElasticQuota
    index: int
    children: List[str] = dataclasses.field(default_factory=list)


class GroupQuotaManager:
    """Quota tree with fair-share runtime refresh and used accounting."""

    def __init__(
        self,
        config: Optional[SnapshotConfig] = None,
        cluster_total: Optional[Mapping[str, float]] = None,
    ):
        self.config = config or SnapshotConfig()
        self._nodes: Dict[str, _QuotaNode] = {}
        self._order: List[str] = []
        self._cluster_total = self.config.res_vector(cluster_total or {})
        d = self.config.dims
        self.runtime = np.zeros((1, d), np.float32)
        self.used = np.zeros((1, d), np.float32)
        self.requests = np.zeros((1, d), np.float32)
        self._dirty = True

    # ---- tree maintenance ----

    def upsert_quota(self, eq: ElasticQuota) -> None:
        name = eq.meta.name
        node = self._nodes.get(name)
        if node is None:
            node = _QuotaNode(quota=eq, index=len(self._order))
            self._nodes[name] = node
            self._order.append(name)
        else:
            old_parent = node.quota.parent
            if old_parent != eq.parent and old_parent in self._nodes:
                self._nodes[old_parent].children.remove(name)
            node.quota = eq
        parent = eq.parent or ROOT
        if parent != ROOT:
            pnode = self._nodes.get(parent)
            if pnode is not None and name not in pnode.children:
                pnode.children.append(name)
        # adopt any pre-registered children pointing at us
        for other, onode in self._nodes.items():
            if (onode.quota.parent or ROOT) == name and other not in node.children:
                node.children.append(other)
        self._dirty = True

    def remove_quota(self, name: str) -> None:
        node = self._nodes.pop(name, None)
        if node is None:
            return
        old_index = {n: self._nodes[n].index for n in self._nodes}
        old_index[name] = node.index
        self._order.remove(name)
        q = max(len(self._order), 1)
        d = self.config.dims
        new_used = np.zeros((q, d), np.float32)
        new_req = np.zeros((q, d), np.float32)
        for new_i, nm in enumerate(self._order):
            n = self._nodes[nm]
            if name in n.children:
                n.children.remove(name)
            oi = old_index[nm]
            if oi < self.used.shape[0]:
                new_used[new_i] = self.used[oi]
            if oi < self.requests.shape[0]:
                new_req[new_i] = self.requests[oi]
            n.index = new_i
        self.used, self.requests = new_used, new_req
        self._dirty = True

    def set_cluster_total(self, total: Mapping[str, float]) -> None:
        self._cluster_total = self.config.res_vector(total)
        self._dirty = True

    def index_of(self, name: str) -> Optional[int]:
        node = self._nodes.get(name)
        return node.index if node else None

    def chain_of(self, name: Optional[str]) -> List[int]:
        """Leaf-to-root index path for a pod's quota label (≤ MAX_LEVELS)."""
        chain: List[int] = []
        while name and name in self._nodes and len(chain) < MAX_LEVELS:
            node = self._nodes[name]
            chain.append(node.index)
            name = node.quota.parent or None
        return chain

    @property
    def quota_count(self) -> int:
        return len(self._order)

    # ---- accounting ----

    def _ensure_capacity(self) -> None:
        q = max(self.quota_count, 1)
        d = self.config.dims
        for attr in ("used", "requests", "runtime"):
            arr = getattr(self, attr)
            if arr.shape[0] < q:
                grown = np.zeros((q, d), np.float32)
                grown[: arr.shape[0]] = arr
                setattr(self, attr, grown)

    def has_headroom(self, quota_name: str, requests: Mapping[str, float]) -> bool:
        """used + request ≤ runtime along the whole chain (host-side mirror
        of the solver's admission for bypass paths like reservations)."""
        self._ensure_capacity()
        if self._dirty:
            self.refresh_runtime()
        vec = self.config.res_vector(requests)
        for idx in self.chain_of(quota_name):
            if np.any(self.used[idx] + vec > self.runtime[idx] + 1e-3):
                return False
        return True

    def charge(self, quota_name: str, requests: Mapping[str, float]) -> None:
        self._ensure_capacity()
        vec = self.config.res_vector(requests)
        for idx in self.chain_of(quota_name):
            self.used[idx] += vec

    def refund(self, quota_name: str, requests: Mapping[str, float]) -> None:
        self._ensure_capacity()
        vec = self.config.res_vector(requests)
        for idx in self.chain_of(quota_name):
            self.used[idx] -= vec

    def set_leaf_requests(self, by_leaf: Mapping[str, np.ndarray]) -> None:
        """Aggregate desired request per quota (pending + admitted), rolled
        up the tree — drives the fair-sharing split like the reference's
        request propagation (``group_quota_manager.go`` updateGroupDeltaReq)."""
        q = max(self.quota_count, 1)
        d = self.config.dims
        req = np.zeros((q, d), np.float32)
        for leaf, vec in by_leaf.items():
            for idx in self.chain_of(leaf):
                req[idx] += vec
        self.requests = req
        self._dirty = True

    # ---- runtime refresh (water-filling down the tree) ----

    def refresh_runtime(self) -> np.ndarray:
        q = max(self.quota_count, 1)
        d = self.config.dims
        runtime = np.zeros((q, d), np.float32)
        self._ensure_capacity()

        roots = [
            n for n in self._order if (self._nodes[n].quota.parent or ROOT) == ROOT
        ]
        self._fill_level(roots, self._cluster_total, runtime)
        self.runtime = runtime
        self._dirty = False
        return runtime

    def _fill_level(
        self, names: Sequence[str], total: np.ndarray, runtime: np.ndarray
    ) -> None:
        if not names:
            return
        idxs = [self._nodes[n].index for n in names]
        mins = np.stack(
            [self.config.res_vector(self._nodes[n].quota.min) for n in names]
        )
        maxs = np.stack(
            [self.config.res_vector(self._nodes[n].quota.max) for n in names]
        )
        maxs = np.where(maxs <= 0, np.inf, maxs)  # absent max = unbounded
        weights = np.stack(
            [
                self.config.res_vector(self._nodes[n].quota.shared_weight)
                for n in names
            ]
        )
        # absent sharedWeight defaults to max (reference getSharedWeight)
        weights = np.where(weights <= 0, np.where(np.isinf(maxs), 1.0, maxs), weights)
        requests = self.requests[idxs]
        guaranteed = np.minimum(mins, requests)
        caps = np.minimum(maxs, requests)
        shares = water_fill(total, guaranteed, caps, weights)
        for row, n in enumerate(names):
            runtime[self._nodes[n].index] = shares[row]
            kids = self._nodes[n].children
            if kids:
                self._fill_level(kids, shares[row], runtime)

    # ---- solver lowering ----

    def quota_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(runtime [Q, D], used [Q, D]) for ops.solver.QuotaState."""
        if self._dirty:
            self.refresh_runtime()
        if self.quota_count == 0:
            d = self.config.dims
            return np.full((1, d), np.inf, np.float32), np.zeros((1, d), np.float32)
        return self.runtime, self.used

    def chains_for_pods(self, pods: Sequence[Pod], p_bucket: int) -> np.ndarray:
        chains = np.full((p_bucket, MAX_LEVELS), -1, np.int32)
        for i, pod in enumerate(pods):
            for level, idx in enumerate(self.chain_of(quota_name_of(pod))):
                chains[i, level] = idx
        return chains
