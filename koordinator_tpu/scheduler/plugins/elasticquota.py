"""ElasticQuota host-side manager: hierarchical quota tree + fair sharing.

Rebuild of the reference's GroupQuotaManager
(``pkg/scheduler/plugins/elasticquota/core/group_quota_manager.go:37-95``)
and RuntimeQuotaCalculator (``core/runtime_quota_calculator.go``): quotas
form trees via the ``quota.scheduling.koordinator.sh/parent`` label; each
parent's runtime is distributed to children as

    runtime = guaranteed(min ∧ request) + weighted fair share of the
              remainder (sharedWeight), capped by max ∧ request

via iterative water-filling (children hitting their cap release surplus to
the rest — the reference's refreshRuntime loop). Admission (used + request
≤ runtime along the chain) runs vectorized inside the solver
(``ops.solver._quota_commit``); this class owns the tree, the runtime
refresh, and durable used accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ...api import extension as ext
from ...api.types import ElasticQuota, Pod
from ...core.snapshot import SnapshotConfig

#: maximum quota tree depth lowered to the solver (leaf..root)
MAX_LEVELS = 4
ROOT = ""  # pseudo-parent of tree roots


def quota_name_of(pod: Pod) -> Optional[str]:
    return pod.meta.labels.get(ext.LABEL_QUOTA_NAME)


def scale_mins_over_root(
    mins: np.ndarray,
    scale_enabled: np.ndarray,
    total: np.ndarray,
) -> np.ndarray:
    """Proportionally shrink sibling min quotas when they oversubscribe the
    parent's capacity (reference
    ``core/scale_minquota_when_over_root_res.go:123-184``): on each dim where
    Σ children-min > total, scale-disabled children keep their original min
    and scale-enabled children split ``max(total - Σ disabled-min, 0)``
    proportionally to their original min.

    ``mins`` [C, D], ``scale_enabled`` [C] bool, ``total`` [D] → scaled [C, D].
    """
    mins = np.asarray(mins, np.float32)
    en = np.asarray(scale_enabled, bool)[:, None]
    need = mins.sum(axis=0) > np.asarray(total, np.float32) + 1e-6  # [D]
    if not need.any():
        return mins
    disabled_sum = np.where(en, 0.0, mins).sum(axis=0)
    enabled_sum = np.where(en, mins, 0.0).sum(axis=0)
    avail = np.maximum(total - disabled_sum, 0.0)
    factor = np.where(enabled_sum > 1e-9, avail / np.maximum(enabled_sum, 1e-9), 0.0)
    return np.where(need[None, :] & en, mins * factor, mins).astype(np.float32)


def water_fill(
    total: np.ndarray,
    guaranteed: np.ndarray,
    caps: np.ndarray,
    weights: np.ndarray,
) -> np.ndarray:
    """Distribute ``total`` [D] among C children with the reference's
    ``quotaTree.redistribution`` / ``iterationForRedistribution`` semantics
    exactly: every child starts at ``guaranteed`` [C, D] (= min(min,
    limited-request)); children still wanting more (cap > guaranteed) split
    the remainder by shared weight with each round's delta ROUNDED to an
    integer (``int64(w·total/totalW + 0.5)``); a child hitting its cap
    (= limited request, min(max, request)) returns its excess to the next
    round, which runs over the still-unsatisfied set only. Verified against
    ``runtime_quota_calculator_test.go`` IterationAdjustQuota (case 1:
    weights 40/60/50/80, requests 5/20/40/70, mins 10/15/20/15, total 100
    → 5/20/35/40 — continuous water-filling would give 35.38/39.62)."""
    c, d = guaranteed.shape
    out = np.minimum(guaranteed, caps).astype(np.float64)
    caps64 = caps.astype(np.float64)
    for dim in range(d):
        runtime = out[:, dim].copy()
        cap = caps64[:, dim]
        w = np.maximum(weights[:, dim].astype(np.float64), 0.0)
        adjust = cap > runtime
        to_part = float(total[dim]) - runtime.sum()
        while to_part > 0 and adjust.any():
            tw = w[adjust].sum()
            if tw <= 0:
                break
            delta = np.where(
                adjust, np.floor(w * to_part / tw + 0.5), 0.0
            )
            runtime = runtime + delta
            over = np.maximum(runtime - cap, 0.0)
            to_part = float(over.sum())
            runtime = np.minimum(runtime, cap)
            adjust = adjust & (runtime < cap)
        out[:, dim] = runtime
    return out.astype(np.float32)


@dataclasses.dataclass
class _QuotaNode:
    quota: ElasticQuota
    index: int
    children: List[str] = dataclasses.field(default_factory=list)


class GroupQuotaManager:
    """Quota tree with fair-share runtime refresh and used accounting."""

    def __init__(
        self,
        config: Optional[SnapshotConfig] = None,
        cluster_total: Optional[Mapping[str, float]] = None,
        tree_id: str = "",
        scale_min_enabled: bool = False,
        enable_preemption: bool = True,
        disable_default_quota_preemption: bool = True,
    ):
        self.config = config or SnapshotConfig()
        self.tree_id = tree_id
        #: gate for min-quota scaling when Σ sibling mins > parent capacity
        #: (reference group_quota_manager.go:52 scaleMinQuotaEnabled)
        self.scale_min_enabled = scale_min_enabled
        #: batch-failure PostFilter preemption (reference preempt.go); the
        #: reference plugin always registers PostFilter — the config
        #: decode can still switch it off per deployment
        self.enable_preemption = enable_preemption
        #: never victimize pods in the default quota (reference
        #: ``DisableDefaultQuotaPreemption``, defaults true in v1beta3)
        self.disable_default_quota_preemption = disable_default_quota_preemption
        self._nodes: Dict[str, _QuotaNode] = {}
        self._order: List[str] = []
        #: leaf quota name → {pod uid: Pod} of admitted pods (reference
        #: quota_info.go:550 GetPodThatIsAssigned)
        self._assigned: Dict[str, Dict[str, "Pod"]] = {}
        self._cluster_total = self.config.res_vector(cluster_total or {})
        d = self.config.dims
        self.runtime = np.zeros((1, d), np.float32)
        self.used = np.zeros((1, d), np.float32)
        self.requests = np.zeros((1, d), np.float32)
        #: uncapped Σ of children's requests per quota (the reference's
        #: ChildRequest; ``requests`` holds the max-capped propagation)
        self.child_requests = np.zeros((1, d), np.float32)
        #: non-preemptible pods' admitted usage, tracked separately: such
        #: pods must fit inside quota MIN, not runtime (reference
        #: ``quota_info.go:49-56`` + ``plugin.go:252-262`` PreFilter)
        self.nonpre_used = np.zeros((1, d), np.float32)
        #: non-preemptible pods' rolled-up requests (status stamping)
        self.nonpre_requests = np.zeros((1, d), np.float32)
        self._dirty = True
        #: bumped whenever the SOLVER-VISIBLE tables (runtime / used /
        #: nonpre_used / mins) actually change — the scheduler keys its
        #: device-resident QuotaState upload off it, so a cycle whose
        #: quota accounting didn't move re-uses the resident copy
        self.state_version = 0
        #: bumped ONLY on tree mutations (upsert/remove) — unlike
        #: ``state_version`` it is untouched by per-cycle charges, so
        #: the pipeline's speculative solves use it to prove the quota
        #: chains they lowered (leaf-to-root index paths) still describe
        #: the live tree at consume time (open-the-gates PR)
        self.tree_version = 0
        #: memoized leaf-to-root index paths; rebuilt on tree mutations
        #: (chain_of was a visible slice of the per-winner commit loop)
        self._chain_cache: Dict[str, List[int]] = {}
        #: name -> lowered [MAX_LEVELS] chain row (chains_for_names)
        self._chain_row_cache: Dict[str, np.ndarray] = {}

    # ---- tree maintenance ----

    def upsert_quota(self, eq: ElasticQuota) -> None:
        name = eq.meta.name
        # label protocol: allow-lent-resource=false pins the full min
        # (quotaNode.AllowLentResource; the typed field wins when the
        # label is absent)
        if eq.meta.labels.get(ext.LABEL_QUOTA_ALLOW_LENT) == "false":
            eq.allow_lent_resource = False
        # wire spelling of the competition weight (AnnotationSharedWeight,
        # ``elastic_quota.go:95-105`` GetSharedWeight): a valid non-zero
        # JSON resource list overrides; otherwise the typed field (and
        # ultimately max) stands
        wire_weight = ext.parse_quota_shared_weight(eq.meta.annotations)
        if wire_weight is not None:
            eq.shared_weight = wire_weight
        node = self._nodes.get(name)
        if node is None:
            node = _QuotaNode(quota=eq, index=len(self._order))
            self._nodes[name] = node
            self._order.append(name)
        else:
            old_parent = node.quota.parent
            if old_parent != eq.parent and old_parent in self._nodes:
                self._nodes[old_parent].children.remove(name)
            node.quota = eq
        parent = eq.parent or ROOT
        if parent != ROOT:
            pnode = self._nodes.get(parent)
            if pnode is not None and name not in pnode.children:
                pnode.children.append(name)
        # adopt any pre-registered children pointing at us
        for other, onode in self._nodes.items():
            if (onode.quota.parent or ROOT) == name and other not in node.children:
                node.children.append(other)
        self._dirty = True
        self.state_version += 1
        self.tree_version += 1
        self._chain_cache.clear()
        self._chain_row_cache.clear()

    def remove_quota(self, name: str) -> None:
        node = self._nodes.pop(name, None)
        if node is None:
            return
        old_index = {n: self._nodes[n].index for n in self._nodes}
        old_index[name] = node.index
        self._order.remove(name)
        q = max(len(self._order), 1)
        d = self.config.dims
        new_used = np.zeros((q, d), np.float32)
        new_req = np.zeros((q, d), np.float32)
        new_child = np.zeros((q, d), np.float32)
        new_nonpre = np.zeros((q, d), np.float32)
        new_nonpre_req = np.zeros((q, d), np.float32)
        for new_i, nm in enumerate(self._order):
            n = self._nodes[nm]
            if name in n.children:
                n.children.remove(name)
            oi = old_index[nm]
            if oi < self.used.shape[0]:
                new_used[new_i] = self.used[oi]
            if oi < self.requests.shape[0]:
                new_req[new_i] = self.requests[oi]
            if oi < self.child_requests.shape[0]:
                new_child[new_i] = self.child_requests[oi]
            if oi < self.nonpre_used.shape[0]:
                new_nonpre[new_i] = self.nonpre_used[oi]
            if oi < self.nonpre_requests.shape[0]:
                new_nonpre_req[new_i] = self.nonpre_requests[oi]
            n.index = new_i
        self._chain_cache.clear()
        self._chain_row_cache.clear()
        self.used, self.requests = new_used, new_req
        self.child_requests = new_child
        self.nonpre_used = new_nonpre
        self.nonpre_requests = new_nonpre_req
        self._dirty = True
        self.state_version += 1
        self.tree_version += 1

    def set_cluster_total(self, total: Mapping[str, float]) -> None:
        """Explicit capacity budget (the multi-tree handler gives each tree
        its slice this way). Disables snapshot auto-sync."""
        self._cluster_total = self.config.res_vector(total)
        self._explicit_total = True
        self._dirty = True

    def sync_cluster_total(self, snapshot) -> None:
        """Track the cluster's aggregate allocatable as the fair-sharing
        budget (the reference GroupQuotaManager recomputes its total from
        node add/update/delete events, ``group_quota_manager.go``). No-op
        once an explicit total was set (multi-tree budgets own it then)."""
        if getattr(self, "_explicit_total", False):
            return
        total = snapshot.nodes.allocatable.sum(axis=0).astype(np.float32)
        if not np.array_equal(total, self._cluster_total):
            self._cluster_total = total
            self._dirty = True

    def update_cluster_total(self, delta: np.ndarray) -> None:
        """Shift capacity by a delta vector (multi-tree rebalancing —
        reference quota_handler.go:324 UpdateClusterTotalResource)."""
        self._cluster_total = np.maximum(self._cluster_total + delta, 0.0).astype(
            np.float32
        )
        self._dirty = True

    @property
    def cluster_total(self) -> np.ndarray:
        return self._cluster_total

    def index_of(self, name: str) -> Optional[int]:
        node = self._nodes.get(name)
        return node.index if node else None

    def chain_of(self, name: Optional[str]) -> List[int]:
        """Leaf-to-root index path for a pod's quota label (≤ MAX_LEVELS)."""
        if not name:
            return []
        cached = self._chain_cache.get(name)
        if cached is not None:
            return cached
        chain: List[int] = []
        key = name
        while name and name in self._nodes and len(chain) < MAX_LEVELS:
            node = self._nodes[name]
            chain.append(node.index)
            name = node.quota.parent or None
        if key in self._nodes:
            self._chain_cache[key] = chain
        return chain

    @property
    def quota_count(self) -> int:
        return len(self._order)

    # ---- accounting ----

    def _ensure_capacity(self) -> None:
        q = max(self.quota_count, 1)
        d = self.config.dims
        for attr in ("used", "requests", "runtime", "child_requests", "nonpre_used", "nonpre_requests"):
            arr = getattr(self, attr)
            if arr.shape[0] < q:
                grown = np.zeros((q, d), np.float32)
                grown[: arr.shape[0]] = arr
                setattr(self, attr, grown)
                self.state_version += 1

    def headroom_in(
        self,
        quota_name: str,
        vec: np.ndarray,
        non_preemptible: bool,
        used: np.ndarray,
        nonpre: np.ndarray,
        runtime: np.ndarray,
    ) -> bool:
        """The chain-walk admission arithmetic of :meth:`has_headroom`
        against CALLER-SUPPLIED ledgers — the single source of truth
        shared by the live check and the pipeline's pure fast-path
        preview (open the last gates PR). A drift between the two would
        make predicted fast-path binds silently diverge from real ones
        (every reservation speculation discarding with no failing test),
        so there must be exactly ONE copy of this arithmetic."""
        chain = self.chain_of(quota_name)
        for idx in chain:
            if idx < used.shape[0] and np.any(
                used[idx] + vec > runtime[idx] + 1e-3
            ):
                return False
        if non_preemptible and chain:
            leaf_min = self.config.res_vector(
                self._nodes[quota_name].quota.min
            )
            if np.any(nonpre[chain[0]] + vec > leaf_min + 1e-3):
                return False
        return True

    def charge_in(
        self,
        quota_name: str,
        vec: np.ndarray,
        non_preemptible: bool,
        used: np.ndarray,
        nonpre: np.ndarray,
    ) -> bool:
        """The chain-walk charge arithmetic of :meth:`charge` against
        caller-supplied ledgers (shared with the preview — same rule as
        :meth:`headroom_in`). Returns whether anything was charged."""
        chain = self.chain_of(quota_name)
        for idx in chain:
            if idx < used.shape[0]:
                used[idx] += vec
        if non_preemptible and chain:
            # leaf-only ledger: admission checks min at the LEAF
            # (plugin.go:252-262); parents roll up at stamping time
            nonpre[chain[0]] += vec
        return bool(chain)

    def has_headroom(
        self,
        quota_name: str,
        requests: Mapping[str, float],
        non_preemptible: bool = False,
    ) -> bool:
        """used + request ≤ runtime along the whole chain (host-side mirror
        of the solver's admission for bypass paths like reservations); a
        non-preemptible pod additionally fits nonPreemptibleUsed + request
        inside the LEAF's min (plugin.go:252-262)."""
        self._ensure_capacity()
        if self._dirty:
            self.refresh_runtime()
        return self.headroom_in(
            quota_name,
            self.config.res_vector(requests),
            non_preemptible,
            self.used,
            self.nonpre_used,
            self.runtime,
        )

    def charge(
        self,
        quota_name: str,
        requests: Mapping[str, float],
        vec: Optional[np.ndarray] = None,
        non_preemptible: bool = False,
    ) -> None:
        self._ensure_capacity()
        if vec is None:
            vec = self.config.res_vector(requests)
        if self.charge_in(
            quota_name, vec, non_preemptible, self.used, self.nonpre_used
        ):
            self.state_version += 1

    def refund(
        self,
        quota_name: str,
        requests: Mapping[str, float],
        non_preemptible: bool = False,
    ) -> None:
        self._ensure_capacity()
        vec = self.config.res_vector(requests)
        chain = self.chain_of(quota_name)
        for idx in chain:
            self.used[idx] -= vec
        if non_preemptible and chain:
            self.nonpre_used[chain[0]] = np.maximum(
                self.nonpre_used[chain[0]] - vec, 0.0
            )
        if chain:
            self.state_version += 1

    def reset_usage(self) -> None:
        """Zero all used charges and assigned-pod records (full-resync
        path: the world state is being replaced wholesale)."""
        self.used[:] = 0.0
        self.nonpre_used[:] = 0.0
        self._assigned.clear()
        self._dirty = True
        self.state_version += 1

    def assign_pod(
        self,
        quota_name: str,
        pod: "Pod",
        vec: Optional[np.ndarray] = None,
    ) -> None:
        """Charge the chain and remember the pod at its leaf quota so the
        overuse-revoke controller can pick eviction victims. ``vec`` is the
        pod's already-lowered request row (skips a per-winner res_vector)."""
        self.charge(
            quota_name,
            pod.spec.requests,
            vec=vec,
            non_preemptible=is_pod_non_preemptible(pod),
        )
        self.record_assigned(quota_name, pod)

    def record_assigned(self, quota_name: str, pod: "Pod") -> None:
        """Remember a pod at its leaf without charging (the batched commit
        charges once per leaf via ``charge`` with a summed vector)."""
        self._assigned.setdefault(quota_name, {})[pod.meta.uid] = pod

    def name_of_index(self, idx: int) -> Optional[str]:
        """Quota name for a lowered chain index (inverse of index_of)."""
        return self._order[idx] if 0 <= idx < len(self._order) else None

    def charge_rows(self, chains: np.ndarray, vecs: np.ndarray) -> None:
        """Vectorized charge for a batch of pods: ``chains`` [B, L] are
        lowered leaf-to-root index paths (−1 padding), ``vecs`` [B, D]
        the request rows. One sort+reduceat scatter replaces B·L
        per-level ``used[idx] += vec`` updates (the per-pod chain walk
        was a visible slice of the quota scenario's commit)."""
        if chains.size == 0:
            return
        self._ensure_capacity()
        levels = chains.shape[1]
        flat = chains.reshape(-1)
        sel = flat >= 0
        if not sel.any():
            return
        idxs = flat[sel]
        rows = np.repeat(vecs, levels, axis=0)[sel]
        perm = np.argsort(idxs, kind="stable")
        si = idxs[perm]
        sr = rows[perm]
        starts = np.nonzero(np.r_[True, si[1:] != si[:-1]])[0]
        sums = np.add.reduceat(sr, starts, axis=0)
        heads = si[starts]
        q = self.used.shape[0]
        # shadow indices (≥ Q, from the extended solver table) route to
        # the non-preemptible ledger; real indices to used
        real = heads < q
        if real.any():
            self.used[heads[real]] += sums[real]
        if (~real).any():
            self.nonpre_used[heads[~real] - q] += sums[~real]
        self.state_version += 1

    def unassign_pod(self, quota_name: str, pod: "Pod") -> None:
        if self._assigned.get(quota_name, {}).pop(pod.meta.uid, None) is not None:
            self.refund(
                quota_name,
                pod.spec.requests,
                non_preemptible=is_pod_non_preemptible(pod),
            )

    def pods_assigned(self, quota_name: str) -> List["Pod"]:
        return list(self._assigned.get(quota_name, {}).values())

    def all_quota_names(self) -> List[str]:
        return list(self._order)

    def headroom_clears(self, pod: "Pod") -> bool:
        """Whether the pod's quota chain has headroom for its request
        (used + req ≤ runtime at every level). True also for pods with no
        (known) quota. Callers use this to tell quota-caused scheduling
        failures from node-fit ones — when a sampled node window was
        active and the chain clears, a failure is (possibly transient)
        node fit, and quota preemption would be premature
        (upstream preemption runs only after a full feasibility scan)."""
        leaf = quota_name_of(pod)
        if leaf is None or self.index_of(leaf) is None:
            return True
        self.runtime_and_used_of(leaf)  # refresh runtime if dirty
        req = self.config.res_vector(pod.spec.requests)
        for idx in self.chain_of(leaf):
            if np.any(self.used[idx] + req > self.runtime[idx] + 1e-3):
                return False
        return True

    def runtime_and_used_of(self, quota_name: str) -> Tuple[np.ndarray, np.ndarray]:
        self._ensure_capacity()
        if self._dirty:
            self.refresh_runtime()
        idx = self._nodes[quota_name].index
        return self.runtime[idx], self.used[idx]

    def set_leaf_requests(self, by_leaf: Mapping[str, np.ndarray]) -> None:
        """Aggregate desired request per quota (pending + admitted), rolled
        up the tree — drives the fair-sharing split like the reference's
        request propagation (``group_quota_manager.go:196-224``
        recursiveUpdateGroupTreeWithDeltaRequest). What travels upward is
        each quota's **limitRequest = min(request, max)**: a child
        demanding over its own max must not inflate its parent's share of
        the grandparent's pool. ``child_requests`` keeps the uncapped sum
        (the reference's ChildRequest annotation)."""
        req, child_req = self._propagate_requests(by_leaf)
        self.requests = req
        self.child_requests = child_req
        self._dirty = True

    def _propagate_requests(
        self, by_leaf: Mapping[str, np.ndarray]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The pure propagation behind :meth:`set_leaf_requests` —
        shared verbatim with the pipeline's speculative PREVIEW
        (:meth:`preview_arrays_extended`), which must reproduce the
        mutating path bit-exactly without touching manager state."""
        q = max(self.quota_count, 1)
        d = self.config.dims
        req = np.zeros((q, d), np.float32)
        child_req = np.zeros((q, d), np.float32)

        def visit(name: str) -> np.ndarray:
            node = self._nodes[name]
            idx = node.index
            # a quota's direct pod demand (the reference's SelfRequest) —
            # pods may target non-leaf quotas too, so every level reads
            # its own by_leaf entry on top of the children's propagation
            vec = by_leaf.get(name)
            cr = (
                np.asarray(vec, np.float32)
                if vec is not None
                else np.zeros(d, np.float32)
            )
            for c in node.children:
                cr = cr + visit(c)
            child_req[idx] = cr
            r = cr
            if not node.quota.allow_lent_resource:
                # request never drops below min: the unlent guarantee is
                # always demanded from the parent (reference :208-221)
                r = np.maximum(r, self.config.res_vector(node.quota.min))
            req[idx] = r
            maxv = self.config.res_vector(node.quota.max)
            maxv = np.where(maxv <= 0, np.inf, maxv)
            return np.minimum(r, maxv).astype(np.float32)

        for n in self._order:
            if (self._nodes[n].quota.parent or ROOT) == ROOT:
                visit(n)
        return req, child_req

    # ---- runtime refresh (water-filling down the tree) ----

    def refresh_runtime(self) -> np.ndarray:
        self._ensure_capacity()
        runtime = self._compute_runtime(self.requests, self._cluster_total)
        if runtime.shape != self.runtime.shape or not np.array_equal(
            runtime, self.runtime
        ):
            # only a VALUE change invalidates the device-resident quota
            # table — steady-state refreshes (same demand, same capacity)
            # keep the resident copy valid
            self.state_version += 1
        self.runtime = runtime
        self._dirty = False
        return runtime

    def _compute_runtime(
        self, requests: np.ndarray, total: np.ndarray
    ) -> np.ndarray:
        """The pure water-fill behind :meth:`refresh_runtime`, shared
        with the speculative preview (same code, same rounding — the
        preview's bit-exactness against the later real refresh is what
        lets a kept speculation claim decision identity)."""
        q = max(self.quota_count, 1)
        d = self.config.dims
        runtime = np.zeros((q, d), np.float32)
        roots = [
            n for n in self._order if (self._nodes[n].quota.parent or ROOT) == ROOT
        ]
        self._fill_level(roots, total, runtime, requests)
        return runtime

    def preview_arrays_extended(
        self, by_leaf: Mapping[str, np.ndarray], total: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """PURE preview of :meth:`quota_arrays_extended` as a future
        cycle carrying ``by_leaf`` pending demand would see it — no
        manager state is touched (open-the-gates PR: the pipeline's
        speculative dispatch runs while the PREVIOUS cycle's PostFilter
        still reads the live requests/runtime, so the real mutating
        propagation must wait for consume time). Returns
        ``(runtime_ext, used_ext)`` with the same shadow-row doubling as
        the real lowering; the consuming cycle re-runs the mutating path
        and keeps the speculation only when the tables match bit-exactly."""
        self._ensure_capacity()
        req, _child = self._propagate_requests(by_leaf)
        runtime = self._compute_runtime(req, np.asarray(total, np.float32))
        if self.quota_count == 0:
            d = self.config.dims
            return (
                np.full((1, d), np.inf, np.float32),
                np.zeros((1, d), np.float32),
            )
        return (
            np.concatenate([runtime, self.mins_array()]),
            np.concatenate([self.used, self.nonpre_used[: runtime.shape[0]]]),
        )

    def effective_cluster_total(self, snapshot) -> np.ndarray:
        """The fair-sharing budget :meth:`sync_cluster_total` WOULD adopt
        for ``snapshot`` — computed without mutating (preview side)."""
        if getattr(self, "_explicit_total", False):
            return self._cluster_total
        return snapshot.nodes.allocatable.sum(axis=0).astype(np.float32)

    def _fill_level(
        self,
        names: Sequence[str],
        total: np.ndarray,
        runtime: np.ndarray,
        requests: Optional[np.ndarray] = None,
    ) -> None:
        if not names:
            return
        idxs = [self._nodes[n].index for n in names]
        mins = np.stack(
            [self.config.res_vector(self._nodes[n].quota.min) for n in names]
        )
        maxs = np.stack(
            [self.config.res_vector(self._nodes[n].quota.max) for n in names]
        )
        maxs = np.where(maxs <= 0, np.inf, maxs)  # absent max = unbounded
        weights = np.stack(
            [
                self.config.res_vector(self._nodes[n].quota.shared_weight)
                for n in names
            ]
        )
        # absent sharedWeight defaults to max (reference getSharedWeight)
        weights = np.where(weights <= 0, np.where(np.isinf(maxs), 1.0, maxs), weights)
        if self.scale_min_enabled:
            mins = scale_mins_over_root(
                mins, np.ones(len(names), bool), total
            )
        if requests is None:
            requests = self.requests
        level_requests = requests[idxs]
        guaranteed = np.minimum(mins, level_requests)
        # allow-lent-resource=false: the quota's UNUSED min is never lent
        # to siblings — the full min stays reserved regardless of demand
        # (reference quotaNode.AllowLentResource in the redistribution)
        lent_ok = np.asarray(
            [self._nodes[n].quota.allow_lent_resource for n in names], bool
        )
        guaranteed = np.where(lent_ok[:, None], guaranteed, mins)
        caps = np.maximum(np.minimum(maxs, level_requests), guaranteed)
        shares = water_fill(total, guaranteed, caps, weights)
        for row, n in enumerate(names):
            runtime[self._nodes[n].index] = shares[row]
            kids = self._nodes[n].children
            if kids:
                self._fill_level(kids, shares[row], runtime, requests)

    # ---- solver lowering ----

    def quota_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(runtime [Q, D], used [Q, D]) for ops.solver.QuotaState."""
        if self._dirty:
            self.refresh_runtime()
        if self.quota_count == 0:
            d = self.config.dims
            return np.full((1, d), np.inf, np.float32), np.zeros((1, d), np.float32)
        return self.runtime, self.used

    def mins_array(self) -> np.ndarray:
        """[Q, D] min vectors in index order (0 where unset)."""
        self._ensure_capacity()
        q = max(self.quota_count, 1)
        d = self.config.dims
        out = np.zeros((q, d), np.float32)
        for name in self._order:
            node = self._nodes[name]
            out[node.index] = self.config.res_vector(node.quota.min)
        return out

    def quota_arrays_extended(self) -> Tuple[np.ndarray, np.ndarray]:
        """Doubled quota table for the solver: rows 0..Q-1 are the real
        quotas (runtime/used); rows Q..2Q-1 are each quota's SHADOW whose
        runtime is the quota's MIN and whose used is the non-preemptible
        ledger. A non-preemptible pod's chain gains its leaf's shadow
        index, so the solver's ordinary cumulative chain admission
        enforces ``nonPreemptibleUsed + req ≤ min`` in-batch — the
        reference's PreFilter check (``plugin.go:252-262``) with no extra
        device pass."""
        runtime, used = self.quota_arrays()
        if self.quota_count == 0:
            return runtime, used
        return (
            np.concatenate([runtime, self.mins_array()]),
            np.concatenate([used, self.nonpre_used[: runtime.shape[0]]]),
        )

    def guaranteed_allocated(self) -> Tuple[np.ndarray, np.ndarray]:
        """Bottom-up guaranteed/allocated pass (reference
        ``elasticquota/core/quota_info.go:62-67`` +
        ``group_quota_manager.go:1350-1352``): a leaf's allocated is its
        admitted pod usage; every quota's guaranteed = max(allocated, min);
        a parent's allocated = Σ children's guaranteed."""
        self._ensure_capacity()
        if self._dirty:
            self.refresh_runtime()
        q = max(self.quota_count, 1)
        d = self.config.dims
        allocated = np.zeros((q, d), np.float32)
        guaranteed = np.zeros((q, d), np.float32)

        def visit(name: str) -> np.ndarray:
            node = self._nodes[name]
            idx = node.index
            if node.children:
                alloc = np.zeros(d, np.float32)
                child_used = np.zeros(d, np.float32)
                for child in node.children:
                    alloc = alloc + visit(child)
                    child_used += self.used[self._nodes[child].index]
                # a parent's own DIRECT pod usage (pods labeled with the
                # parent itself — this tree supports them) counts too:
                # used[parent] is the chain rollup, so self-used is the
                # difference vs the children's rolled-up used
                alloc = alloc + np.maximum(self.used[idx] - child_used, 0.0)
            else:
                alloc = self.used[idx].copy()
            allocated[idx] = alloc
            guaranteed[idx] = np.maximum(
                alloc, self.config.res_vector(node.quota.min)
            )
            return guaranteed[idx]

        for n in self._order:
            if (self._nodes[n].quota.parent or ROOT) == ROOT:
                visit(n)
        return guaranteed, allocated

    def sync_status(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """The quota controller's status sync (reference
        ``elasticquota/controller.go:160-180`` Start → syncHandler,
        updateElasticQuotaStatusIfChanged): stamps runtime / request /
        child-request / guaranteed / allocated annotations onto every
        quota object and returns {name: {"runtime": .., "request": ..,
        "used": .., ...}} for callers that publish status elsewhere."""
        import json as _json

        if self._dirty:
            self.refresh_runtime()
        res = self.config.resources
        report: Dict[str, Dict[str, Dict[str, float]]] = {}
        guaranteed, allocated = self.guaranteed_allocated()

        def table(row: np.ndarray) -> Dict[str, float]:
            return {
                res[d]: float(row[d]) for d in range(len(res)) if row[d] > 0
            }

        for name in self._order:
            node = self._nodes[name]
            idx = node.index
            # uncapped Σ of children's demand (AnnotationChildRequest) vs
            # the max-capped ``request`` — distinct when a child demands
            # over its own max
            child_req = (
                self.child_requests[idx]
                if idx < self.child_requests.shape[0]
                else self.requests[idx]
            )
            summary = {
                "runtime": table(self.runtime[idx]),
                "request": table(self.requests[idx]),
                "used": table(self.used[idx]),
                "childRequest": table(child_req),
                "guaranteed": table(guaranteed[idx]),
                "allocated": table(allocated[idx]),
            }
            report[name] = summary
            ann = node.quota.meta.annotations
            ann[ext.ANNOTATION_QUOTA_RUNTIME] = _json.dumps(
                summary["runtime"]
            )
            ann[ext.ANNOTATION_QUOTA_REQUEST] = _json.dumps(
                summary["request"]
            )
            ann[ext.ANNOTATION_QUOTA_CHILD_REQUEST] = _json.dumps(
                summary["childRequest"]
            )
            ann[ext.ANNOTATION_QUOTA_GUARANTEED] = _json.dumps(
                summary["guaranteed"]
            )
            ann[ext.ANNOTATION_QUOTA_ALLOCATED] = _json.dumps(
                summary["allocated"]
            )
            # non-preemptible ledger (AnnotationNonPreemptibleUsed /
            # ...Request, quota_info.go:49-56): leaf values are direct;
            # parents roll their subtree up
            np_used = self._rollup(self.nonpre_used, name)
            np_req = self._rollup(self.nonpre_requests, name)
            summary["nonPreemptibleUsed"] = table(np_used)
            summary["nonPreemptibleRequest"] = table(np_req)
            ann[ext.ANNOTATION_QUOTA_NON_PREEMPTIBLE_USED] = _json.dumps(
                summary["nonPreemptibleUsed"]
            )
            ann[ext.ANNOTATION_QUOTA_NON_PREEMPTIBLE_REQUEST] = _json.dumps(
                summary["nonPreemptibleRequest"]
            )
        return report

    def _rollup(self, leaf_array: np.ndarray, name: str) -> np.ndarray:
        """Subtree sum of a leaf-tracked ledger."""
        node = self._nodes[name]
        total = leaf_array[node.index].copy()
        for child in node.children:
            total += self._rollup(leaf_array, child)
        return total

    def chains_for_pods(self, pods: Sequence[Pod], p_bucket: int) -> np.ndarray:
        return self.chains_for_names(
            [quota_name_of(p) for p in pods], p_bucket
        )

    def chains_for_names(
        self, names: Sequence[Optional[str]], p_bucket: int
    ) -> np.ndarray:
        """Lowered chain rows from pre-collected quota labels. Clusters
        have few distinct quotas, so rows are built once per distinct
        name (memoized alongside the index-path cache) and scattered —
        the per-pod ``chain_of`` walk was a visible slice of large quota
        batches. Rows are MAX_LEVELS+1 wide: the extra column is ALWAYS
        free for a non-preemptible pod's shadow-leaf index, so the MIN
        bound can never silently go unenforced on a full-depth chain."""
        chains = np.full((p_bucket, MAX_LEVELS + 1), -1, np.int32)
        cache = self._chain_row_cache
        groups: Dict[str, List[int]] = {}
        for i, nm in enumerate(names):
            if nm is None:
                continue
            lst = groups.get(nm)
            if lst is None:
                groups[nm] = [i]
            else:
                lst.append(i)
        for nm, idxs in groups.items():
            row = cache.get(nm)
            if row is None:
                row = np.full((MAX_LEVELS + 1,), -1, np.int32)
                for level, idx in enumerate(self.chain_of(nm)[:MAX_LEVELS]):
                    row[level] = idx
                cache[nm] = row
            chains[idxs] = row
        return chains


# ---------------------------------------------------------------------------
# Overuse revoke (reference quota_overuse_revoke.go)
# ---------------------------------------------------------------------------


def is_pod_non_preemptible(pod: Pod) -> bool:
    """Reference ``apis/extension/elastic_quota.go:85-87`` (quota
    preemptible label) + ``preemption.go:47-56`` (the scheduling-domain
    disable-preemptible opt-out honored by every preemption path)."""
    if pod.meta.labels.get(ext.LABEL_PREEMPTIBLE) == "false":
        return True
    return not ext.is_pod_preemptible(pod)


@dataclasses.dataclass
class _OveruseMonitor:
    """Per-quota debounce: used > runtime must persist for
    ``delay_evict_time`` before eviction triggers (reference
    QuotaOverUsedGroupMonitor, quota_overuse_revoke.go:61-90)."""

    manager: GroupQuotaManager
    quota_name: str
    delay_evict_time: float
    last_under_used: float = 0.0

    def check(self, now: float) -> bool:
        if self.quota_name not in self.manager._nodes:
            return False
        runtime, used = self.manager.runtime_and_used_of(self.quota_name)
        if np.all(used <= runtime + 1e-6):
            self.last_under_used = now
            return False
        if now - self.last_under_used > self.delay_evict_time:
            self.last_under_used = now
            return True
        return False


class ElasticQuotaPreemptor:
    """PostFilter analog of the reference's cross-pod preemption
    (``pkg/scheduler/plugins/elasticquota/preempt.go``): when a batch
    leaves a quota-labeled pod unschedulable, find the minimal set of
    lower-priority pods of the *same quota* (``canPreempt``:283-304)
    whose eviction both frees node capacity for the pod and clears its
    quota headroom, using the reference's remove-all-then-reprieve flow
    (``SelectVictimsOnNode``:111-221: strip every eligible victim, check
    fit, then reprieve most-important-first while the pod still fits and
    the quota check still passes).
    """

    def __init__(
        self,
        scheduler: "BatchScheduler",
        manager: GroupQuotaManager,
    ):
        self.scheduler = scheduler
        self.manager = manager
        #: per-cycle candidate cache (one preemptor instance per cycle):
        #: leaf → [(victim, preemptible, vleaf, priority, req_vec)] — the
        #: label parsing + res_vector walk over every assigned pod was
        #: re-run per failed pod and grew with cluster occupancy.
        #: Bound-ness is NOT cached (bound_node_of is re-checked live, so
        #: immediate-mode evictions between calls stay correct).
        self._cand_cache: Dict[str, list] = {}

    def _leaf_candidates(self, leaf: str) -> list:
        cached = self._cand_cache.get(leaf)
        if cached is None:
            cfg = self.manager.config
            vec_cache: Dict[tuple, np.ndarray] = {}
            cached = []
            for v in self.manager.pods_assigned(leaf):
                key = tuple(v.spec.requests.items())
                vec = vec_cache.get(key)
                if vec is None:
                    vec = cfg.res_vector(v.spec.requests)
                    vec_cache[key] = vec
                cached.append(
                    (
                        v,
                        not is_pod_non_preemptible(v),
                        quota_name_of(v) or ext.DEFAULT_QUOTA_NAME,
                        v.spec.priority or 0,
                        vec,
                    )
                )
            self._cand_cache[leaf] = cached
        return cached

    def _devices_clear(
        self, pod: Pod, node: str, victims: List[Pod]
    ) -> bool:
        """Coarse device feasibility: the pod's GPU/RDMA demand must fit
        in the node's free devices plus everything the victims hold.
        (Fragmentation-exact allocation is re-checked at the retry's
        Reserve; this gate stops evictions that cannot possibly help.)"""
        dm = self.scheduler.devices
        whole, share = ext.parse_gpu_request(pod.spec.requests)
        rdma = ext.parse_rdma_request(pod.spec.requests)
        fpga = ext.parse_fpga_request(pod.spec.requests)
        if whole == 0 and share <= 0 and rdma == 0 and fpga == 0:
            return True
        if dm is None:
            return False
        st = dm.node(node)
        if st is None:
            return False
        from .deviceshare import FULL

        victim_uids = {v.meta.uid for v in victims}
        free_full = sum(1 for f in st.gpu_free if f >= FULL - 1e-6)
        victim_full = sum(
            1
            for uid in victim_uids
            for pick in st.owners.get(uid, [])
            if pick[1] >= FULL - 1e-6
        )
        if whole + (1 if share > 0 else 0) > free_full + victim_full:
            return False
        free_rdma = sum(1 for f in st.rdma_free if f >= FULL - 1e-6)
        victim_rdma = sum(
            len(st.rdma_owners.get(uid, [])) for uid in victim_uids
        )
        if rdma > free_rdma + victim_rdma:
            return False
        free_fpga = sum(1 for f in st.fpga_free if f >= FULL - 1e-6)
        victim_fpga = sum(
            len(st.fpga_owners.get(uid, [])) for uid in victim_uids
        )
        return fpga <= free_fpga + victim_fpga

    def select_victims(
        self, pod: Pod
    ) -> Optional[Tuple[str, List[Pod]]]:
        """(node_name, victims) for the cheapest feasible preemption, or
        None. Nodes are tried in ascending victim count (minimal
        disruption), mirroring the reference preemption evaluator's
        fewest-victims candidate ranking. Candidate nodes must pass the
        pod's own node constraints and a coarse device-feasibility gate —
        evicting running workloads must never happen when the preemptor
        cannot possibly land afterwards."""
        leaf = quota_name_of(pod)
        if leaf is None or self.manager.index_of(leaf) is None:
            return None
        snap = self.scheduler.snapshot
        cfg = self.manager.config
        req = cfg.res_vector(pod.spec.requests)

        # The chain check "used − freed + req ≤ runtime at every level"
        # collapses to ONE per-dim bound: freed ≥ max over levels of
        # (used + req − runtime). Computing it once here replaces a
        # per-victim per-level scan that dominated the latency-stream
        # cycle's PostFilter cost.
        mgr = self.manager
        mgr.runtime_and_used_of(leaf)  # refresh runtime if dirty
        chain = list(mgr.chain_of(leaf))
        if not chain:
            return None
        # (an unbounded runtime level yields −inf need — never binding)
        quota_needed = np.max(
            [mgr.used[i] + req - mgr.runtime[i] for i in chain], axis=0
        )

        pod_prio = pod.spec.priority or 0
        skip_default = (
            self.manager.disable_default_quota_preemption
        )
        by_node: Dict[str, List[Tuple[Pod, np.ndarray]]] = {}
        freed_all = np.zeros_like(req)
        for victim, preemptible, vleaf, vprio, vec in self._leaf_candidates(
            leaf
        ):
            # canPreempt: preemptible victim, strictly lower priority,
            # same quota, default-quota opt-out — over precomputed fields
            if (
                not preemptible
                or vprio >= pod_prio
                or vleaf != leaf
                or (skip_default and vleaf == ext.DEFAULT_QUOTA_NAME)
            ):
                continue
            node = self.scheduler.bound_node_of(victim.meta.uid)
            if node is None:
                continue
            by_node.setdefault(node, []).append((victim, vec))
            freed_all = freed_all + vec
        # even evicting EVERY eligible victim cannot clear the chain →
        # no node can succeed, skip the per-node scan entirely
        if by_node and np.any(freed_all < quota_needed - 1e-3):
            return None

        best: Optional[Tuple[str, List[Pod]]] = None
        na = snap.nodes
        for node in sorted(by_node, key=lambda n: len(by_node[n])):
            idx = snap.node_id(node)
            if idx is None:
                continue
            if not self.scheduler.node_allowed(pod, node):
                continue
            victims = [v for v, _vec in by_node[node]]
            if not self._devices_clear(pod, node, victims):
                continue
            vecs = [vec for _v, vec in by_node[node]]
            freed = np.sum(vecs, axis=0)
            # node fit collapses the same way: freed ≥ requested + req −
            # allocatable, per dim
            node_needed = na.requested[idx] + req - na.allocatable[idx]
            needed = np.maximum(quota_needed, node_needed)
            # step 1: all eligible victims gone — does the pod fit, and
            # does the quota chain clear?
            if np.any(freed < needed - 1e-3):
                continue
            # step 2: reprieve most-important-first while both still hold
            order = sorted(
                range(len(victims)),
                key=lambda i: (-(victims[i].spec.priority or 0), i),
            )
            final: List[Pod] = []
            for i in order:
                trial = freed - vecs[i]
                if np.all(trial >= needed - 1e-3):
                    freed = trial  # reprieved
                else:
                    final.append(victims[i])
            if final and (best is None or len(final) < len(best[1])):
                best = (node, final)
        return best


class QuotaOverUsedRevokeController:
    """Evicts pods from quotas whose used stays above runtime (fair share
    shrank under them) — reference QuotaOverUsedRevokeController
    (``quota_overuse_revoke.go:149-272``). Victim selection
    (``getToRevokePodList`` :92-147): walk assigned pods least-important
    first, skipping non-preemptible, subtracting requests until
    used ≤ runtime; then try to re-admit from most-important down, keeping
    only pods that no longer fit on the revoke list.

    Defaults mirror v1beta3: delay 120 s, cycle 1 s
    (``pkg/scheduler/apis/config/v1beta3/defaults.go:58-59``).
    """

    def __init__(
        self,
        managers_fn,
        evict_fn,
        delay_evict_time: float = 120.0,
        revoke_pod_interval: float = 1.0,
        monitor_all_quotas: bool = True,
        now_fn=None,
    ):
        import time as _time

        self._managers_fn = managers_fn
        self._evict_fn = evict_fn
        self.delay_evict_time = delay_evict_time
        self.revoke_pod_interval = revoke_pod_interval
        self.monitor_all_quotas = monitor_all_quotas
        self._now = now_fn or _time.monotonic
        self._monitors: Dict[str, _OveruseMonitor] = {}
        self._last_cycle = -float("inf")

    def sync_quotas(self) -> None:
        """Track monitor set against live quotas (syncQuota :215-240)."""
        now = self._now()
        alive = set()
        for mgr in self._managers_fn():
            for name in mgr.all_quota_names():
                if name in (ext.SYSTEM_QUOTA_NAME, ext.ROOT_QUOTA_NAME):
                    continue
                alive.add(name)
                if name not in self._monitors:
                    self._monitors[name] = _OveruseMonitor(
                        manager=mgr,
                        quota_name=name,
                        delay_evict_time=self.delay_evict_time,
                        last_under_used=now,
                    )
        for name in list(self._monitors):
            if name not in alive:
                del self._monitors[name]

    def pods_to_revoke(self, quota_name: str) -> List[Pod]:
        mon = self._monitors.get(quota_name)
        if mon is None:
            return []
        mgr = mon.manager
        runtime, used = mgr.runtime_and_used_of(quota_name)
        used = used.copy()
        cfg = mgr.config

        # least important first: lowest priority, later-assigned breaking ties
        pods = mgr.pods_assigned(quota_name)
        order = sorted(
            range(len(pods)),
            key=lambda i: ((pods[i].spec.priority or 0), -i),
        )
        try_revoke: List[Pod] = []
        for i in order:
            if np.all(used <= runtime + 1e-6):
                break
            pod = pods[i]
            if is_pod_non_preemptible(pod):
                continue
            used -= cfg.res_vector(pod.spec.requests)
            try_revoke.append(pod)

        if not np.all(used <= runtime + 1e-6):
            return try_revoke  # still over: revoke everything we could

        # re-admit from most important down (:131-141)
        revoke: List[Pod] = []
        for pod in reversed(try_revoke):
            vec = cfg.res_vector(pod.spec.requests)
            used += vec
            if not np.all(used <= runtime + 1e-6):
                used -= vec
                revoke.append(pod)
        return revoke

    def step(self) -> List[Pod]:
        """One controller cycle; returns the pods handed to the evictor."""
        if not self.monitor_all_quotas:
            return []
        now = self._now()
        if now - self._last_cycle < self.revoke_pod_interval:
            return []
        self._last_cycle = now
        self.sync_quotas()
        revoked: List[Pod] = []
        for name, mon in list(self._monitors.items()):
            if not mon.check(now):
                continue
            for pod in self.pods_to_revoke(name):
                self._evict_fn(pod)
                leaf = quota_name_of(pod) or name
                mon.manager.unassign_pod(leaf, pod)
                revoked.append(pod)
        return revoked


# ---------------------------------------------------------------------------
# Multi-tree handling (reference quota_handler.go)
# ---------------------------------------------------------------------------


class QuotaTreeHandler:
    """Routes quotas into per-tree GroupQuotaManagers keyed by the
    ``tree-id`` label (reference ``quota_handler.go:34-63``
    GetOrCreateGroupQuotaManagerForTree). A tree's root quota carries the
    tree's capacity in its total-resource annotation; registering it moves
    that capacity out of the default tree unless ignore-default-tree is set
    (``handlerQuotaWhenRoot`` :303-327)."""

    def __init__(
        self,
        config: Optional[SnapshotConfig] = None,
        cluster_total: Optional[Mapping[str, float]] = None,
        scale_min_enabled: bool = False,
    ):
        self.config = config or SnapshotConfig()
        self.scale_min_enabled = scale_min_enabled
        self.default_manager = GroupQuotaManager(
            self.config, cluster_total, scale_min_enabled=scale_min_enabled
        )
        self._tree_managers: Dict[str, GroupQuotaManager] = {}
        self._quota_to_tree: Dict[str, str] = {}
        self._tree_totals: Dict[str, np.ndarray] = {}
        #: capacity each tree ACTUALLY took from the default tree — the
        #: give-back source of truth, so clamped deductions and later
        #: ignore-default-tree / total-resource flips never mint capacity
        self._tree_deducted: Dict[str, np.ndarray] = {}

    def manager_for_tree(self, tree_id: str) -> GroupQuotaManager:
        if not tree_id:
            return self.default_manager
        mgr = self._tree_managers.get(tree_id)
        if mgr is None:
            mgr = GroupQuotaManager(
                self.config, tree_id=tree_id, scale_min_enabled=self.scale_min_enabled
            )
            self._tree_managers[tree_id] = mgr
        return mgr

    def manager_for_quota(self, quota_name: str) -> GroupQuotaManager:
        return self.manager_for_tree(self._quota_to_tree.get(quota_name, ""))

    def manager_for_pod(self, pod: Pod) -> GroupQuotaManager:
        return self.manager_for_quota(quota_name_of(pod) or "")

    def managers(self) -> List[GroupQuotaManager]:
        return [self.default_manager, *self._tree_managers.values()]

    def on_quota_upsert(self, eq: ElasticQuota) -> None:
        name = eq.meta.name
        old_tree = self._quota_to_tree.get(name)
        if old_tree is not None and old_tree != eq.tree_id:
            # the reference forbids moving a quota between trees
            # (quota_handler.go:74); be defensive and migrate cleanly instead
            # of leaving a stale double registration behind
            old_mgr = (
                self._tree_managers.get(old_tree) if old_tree else self.default_manager
            )
            if old_mgr is not None:
                old_mgr.remove_quota(name)
        mgr = self.manager_for_tree(eq.tree_id)
        self._quota_to_tree[name] = eq.tree_id
        self._handle_root(eq, mgr, is_delete=False)
        mgr.upsert_quota(eq)

    def on_quota_delete(self, eq: ElasticQuota) -> None:
        self._quota_to_tree.pop(eq.meta.name, None)
        mgr = (
            self._tree_managers.get(eq.tree_id) if eq.tree_id else self.default_manager
        )
        if mgr is None:
            return
        mgr.remove_quota(eq.meta.name)
        self._handle_root(eq, mgr, is_delete=True)

    def _take_from_default(self, tree_id: str, target: np.ndarray) -> None:
        """Reconcile the tree's default-tree deduction toward ``target``,
        bounded by what the default tree can actually give (or has actually
        taken) — capacity is conserved even when totals oversubscribe."""
        deducted = self._tree_deducted.get(
            tree_id, np.zeros(self.config.dims, np.float32)
        )
        want = target - deducted
        if not np.any(want != 0):
            return
        before = self.default_manager.cluster_total.copy()
        self.default_manager.update_cluster_total(-want)
        applied = before - self.default_manager.cluster_total
        self._tree_deducted[tree_id] = (deducted + applied).astype(np.float32)

    def _handle_root(
        self, eq: ElasticQuota, mgr: GroupQuotaManager, is_delete: bool
    ) -> None:
        if not eq.is_root or not eq.tree_id:
            return
        tree = eq.tree_id
        if is_delete:
            # give back exactly what this tree took, regardless of current
            # annotations on the delete event
            self._take_from_default(tree, np.zeros(self.config.dims, np.float32))
            self._tree_totals.pop(tree, None)
            self._tree_deducted.pop(tree, None)
            live = self._tree_managers.get(tree)
            if live is not None:
                if live.quota_count == 0:
                    self._tree_managers.pop(tree, None)
                else:
                    # children still registered: keep their accounting alive
                    # but the tree no longer has capacity to hand out
                    live.set_cluster_total({})
            return
        if not eq.total_resource:
            return
        new_total = self.config.res_vector(eq.total_resource)
        self._tree_totals[tree] = new_total
        mgr.set_cluster_total(eq.total_resource)
        target = (
            np.zeros_like(new_total) if eq.ignore_default_tree else new_total
        )
        self._take_from_default(tree, target)
