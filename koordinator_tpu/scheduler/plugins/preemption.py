"""Priority-based preemption (the reservation plugin's PostFilter).

Rebuild of ``pkg/scheduler/plugins/reservation/preemption.go:105-250``:
when a pod fails scheduling, candidate nodes are evaluated by the
kube DefaultPreemption algorithm with Koordinator's non-preemptible
extension — remove ALL lower-priority preemptible pods from the node,
check the incoming pod fits, then reprieve victims most-important-first
while it still fits. Reserve (ghost) pods flow through the same path, so
reservations can preempt too, exactly like the reference delegating the
preemption evaluator through the reservation plugin. Gated by
``ReservationArgs.EnablePreemption`` (default false,
``apis/config/v1beta3/defaults.go:52``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...api.types import Pod
from .elasticquota import is_pod_non_preemptible


def _more_important(pod: Pod) -> Tuple[int, str]:
    """Reference ``util.MoreImportantPod`` sort key: higher priority
    first; name as the stable tiebreak (creation time analog)."""
    return (-(pod.spec.priority or 0), pod.meta.uid)


class PriorityPreemptor:
    """Select minimal lower-priority victim sets per node."""

    def __init__(self, scheduler: "BatchScheduler"):
        self.scheduler = scheduler

    def select_victims(
        self, pod: Pod
    ) -> Optional[Tuple[str, List[Pod]]]:
        """(node, victims) for the cheapest feasible priority preemption,
        or None. Mirrors SelectVictimsOnNode: victims must be strictly
        lower priority AND preemptible; candidate nodes are ranked by
        fewest victims (the preemption evaluator's candidate ranking)."""
        sched = self.scheduler
        snap = sched.snapshot
        prio = pod.spec.priority or 0
        req = snap.config.res_vector(pod.spec.requests)

        by_node: Dict[str, List[Pod]] = {}
        for uid, node in sched._bound_nodes.items():
            if uid not in snap._assumed:
                continue
            victim = sched._bound_pods.get(uid)
            if victim is None:
                continue
            if (victim.spec.priority or 0) >= prio:
                continue
            if is_pod_non_preemptible(victim):
                continue
            by_node.setdefault(node, []).append(victim)

        best: Optional[Tuple[str, List[Pod]]] = None
        for node, potential in by_node.items():
            if not sched.node_allowed(pod, node):
                continue
            idx = snap.node_id(node)
            if idx is None or not snap.nodes.schedulable[idx]:
                continue
            freed = np.zeros_like(req)
            for v in potential:
                ap = snap._assumed.get(v.meta.uid)
                if ap is not None:
                    freed = freed + ap.request
            headroom = (
                snap.nodes.allocatable[idx]
                - snap.nodes.requested[idx]
                + freed
            )
            if not np.all(req <= headroom + 1e-3):
                continue  # does not fit even with every victim gone
            # reprieve as many as possible, most important first
            victims: List[Pod] = []
            room = headroom
            for v in sorted(potential, key=_more_important):
                ap = snap._assumed.get(v.meta.uid)
                charge = ap.request if ap is not None else 0.0
                if np.all(req <= room - charge + 1e-3):
                    room = room - charge  # reprieved: stays on the node
                else:
                    victims.append(v)
            if not victims:
                continue  # pod actually fits without evicting (race)
            if best is None or len(victims) < len(best[1]):
                best = (node, victims)
        return best
