"""Coscheduling (gang) host-side manager.

Rebuild of the reference Coscheduling plugin's control-plane half
(``pkg/scheduler/plugins/coscheduling/``): the PodGroupManager tracks gangs
(PodGroup CRD or ``pod-group.scheduling.sigs.k8s.io`` labels), gates pods at
PreEnqueue until minMember members exist (``core/core.go:183-263``), keeps
gang members adjacent in the pending queue so they land in the same solver
batch (the NextPod semantics, ``core/core.go:135-176``), and enforces
all-or-nothing at Permit (``core/core.go:346-465``).

The data-plane half — rejecting under-filled gangs and rolling their
capacity back — runs inside the solver (``ops.solver.enforce_gangs``).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from ...api import extension as ext
from ...api.types import Pod, PodGroup


def gang_key_of(pod: Pod) -> Optional[str]:
    # memoized on the pod: gang membership is fixed at pod creation
    # (the reference parses it once at gang creation, gang.go:128-132)
    # and this accessor runs several times per pod per cycle on the hot
    # commit path
    try:
        return pod._gang_key
    except AttributeError:
        pass
    gang = pod.meta.annotations.get(
        ext.ANNOTATION_GANG_NAME
    ) or pod.meta.labels.get(ext.LABEL_GANG_NAME)
    key = None if not gang else f"{pod.meta.namespace}/{gang}"
    pod._gang_key = key
    return key


def gang_group_of(pod: Pod, own_key: str) -> frozenset:
    """The gang group this pod's gang belongs to: the gang-groups
    annotation lists gang keys ("ns/name") that Permit treats atomically
    (reference ``apis/extension/coscheduling.go`` AnnotationGangGroups).
    Always includes the pod's own gang."""
    raw = pod.meta.annotations.get(ext.ANNOTATION_GANG_GROUPS)
    keys = {own_key}
    if raw:
        try:
            for item in json.loads(raw):
                keys.add(str(item))
        except (ValueError, TypeError):
            pass
    return frozenset(keys)


def explicit_match_policy(annotations: Mapping[str, str]) -> Optional[str]:
    """The match-policy annotation value if present and valid, else None —
    an *absent* annotation must not reset a gang whose policy was already
    declared (by the PodGroup CRD or another member)."""
    policy = annotations.get(
        ext.ANNOTATION_GANG_MATCH_POLICY
    ) or annotations.get(ext.ANNOTATION_ALIAS_GANG_MATCH_POLICY)
    if policy in (
        ext.GANG_MATCH_ONLY_WAITING,
        ext.GANG_MATCH_WAITING_AND_RUNNING,
        ext.GANG_MATCH_ONCE_SATISFIED,
    ):
        return policy
    return None


def match_policy_of(pod: Pod) -> str:
    """Gang match policy from the pod annotation (or its sig-scheduling
    alias), default once-satisfied (reference
    ``apis/extension/coscheduling.go:86-93`` GetGangMatchPolicy)."""
    return (
        explicit_match_policy(pod.meta.annotations)
        or ext.GANG_MATCH_ONCE_SATISFIED
    )


@dataclasses.dataclass
class _GangState:
    #: None = minMember unknown (label-only gang without min-available):
    #: all-or-nothing over whichever members are present in the batch.
    min_member: Optional[int]
    create_time: float
    schedule_timeout_s: float
    #: uids of pending members currently known (rebuilt every cycle)
    pending: Dict[str, Pod] = dataclasses.field(default_factory=dict)
    #: uids of members already bound
    bound: int = 0
    #: which member states count toward satisfaction
    match_policy: str = ext.GANG_MATCH_ONCE_SATISFIED
    #: whether the policy was explicitly declared (CRD or first declaring
    #: member) — once declared, later member annotations cannot flip it
    policy_declared: bool = False
    #: failure handling (AnnotationGangMode): Strict rolls the gang group
    #: back on a member failure, NonStrict keeps placed members. Parsed
    #: once at gang creation (CRD or first member), like match_policy.
    mode: str = ext.GANG_MODE_STRICT
    mode_declared: bool = False
    #: declared total children (AnnotationGangTotalNum, ≥ minMember when
    #: both set; None = defaults to minMember per gang.go:114-125)
    total_num: Optional[int] = None
    #: sticky once-satisfied flag (reference ``gang.go:435-459``
    #: setResourceSatisfied, set by Permit allow and addBoundPod)
    satisfied: bool = False

    def effective_min(self, fallback: int) -> int:
        return self.min_member if self.min_member is not None else fallback

    @property
    def bound_credit(self) -> int:
        """Bound members counting toward satisfaction: the only-waiting
        policy counts waiting (this batch's placements) alone
        (``gang.go:492-494`` — satisfaction from WaitingForBindChildren
        only)."""
        return 0 if self.match_policy == ext.GANG_MATCH_ONLY_WAITING else self.bound

    @property
    def once_satisfied(self) -> bool:
        return (
            self.match_policy == ext.GANG_MATCH_ONCE_SATISFIED and self.satisfied
        )


class _MinMemberView:
    """Read-through ``Mapping``-shaped view over live gang state (only
    ``get``/``__getitem__``/``__contains__`` are needed by build_pods)."""

    __slots__ = ("_gangs",)

    def __init__(self, gangs: Dict[str, _GangState]):
        self._gangs = gangs

    def get(self, key, default=None):
        s = self._gangs.get(key)
        if s is None:
            return default
        if s.once_satisfied:
            return 0
        if s.min_member is None:
            return default
        return max(s.min_member - s.bound_credit, 0)

    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key):
        return self.get(key) is not None

    def __bool__(self):
        return True


class _NonStrictView:
    """Read-through view for declared gang modes (see _MinMemberView)."""

    __slots__ = ("_gangs",)

    def __init__(self, gangs: Dict[str, _GangState]):
        self._gangs = gangs

    def get(self, key, default=None):
        s = self._gangs.get(key)
        if s is None or not s.mode_declared:
            return default
        return s.mode == ext.GANG_MODE_NONSTRICT

    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key):
        return self.get(key) is not None

    def __bool__(self):
        return True


class PodGroupManager:
    """Tracks gangs and decides scheduling eligibility."""

    def __init__(self, default_timeout_s: float = 600.0):
        self._gangs: Dict[str, _GangState] = {}
        self.default_timeout_s = default_timeout_s

    def upsert_pod_group(self, pg: PodGroup) -> None:
        key = f"{pg.meta.namespace}/{pg.meta.name}"
        state = self._gangs.get(key)
        if state is None:
            state = _GangState(
                min_member=pg.min_member,
                create_time=time.time(),
                schedule_timeout_s=pg.schedule_timeout_s,
            )
            self._gangs[key] = state
        else:
            state.min_member = pg.min_member
            state.schedule_timeout_s = pg.schedule_timeout_s
        # the PodGroup CRD's own annotation declares the policy for the
        # whole gang with final authority (reference GangFromPodGroupCrd);
        # once declared, member annotations are ignored
        explicit = explicit_match_policy(pg.meta.annotations)
        if explicit is not None:
            state.match_policy = explicit
            state.policy_declared = True
        if ext.ANNOTATION_GANG_MODE in pg.meta.annotations:
            state.mode = ext.gang_mode_of(pg.meta.annotations)
            state.mode_declared = True

    def _gang_for_pod(self, key: str, pod: Pod) -> _GangState:
        state = self._gangs.get(key)
        if state is None:
            # native annotation protocol first (gang.go:100-175
            # tryInitByPodConfig): min-available, waiting-time (Go
            # duration; illegal → default), total-number (clamped to
            # ≥ minMember)
            min_member = ext.gang_min_available_of(pod)
            wait = ext.parse_duration_s(
                pod.meta.annotations.get(ext.ANNOTATION_GANG_WAIT_TIME)
            )
            total: Optional[int] = None
            raw_total = pod.meta.annotations.get(
                ext.ANNOTATION_GANG_TOTAL_NUM
            )
            if raw_total is not None:
                try:
                    total = int(raw_total)
                except ValueError:
                    total = None
            if total is not None and min_member is not None:
                total = max(total, min_member)
            state = _GangState(
                min_member=min_member,
                create_time=time.time(),
                schedule_timeout_s=(
                    wait if wait is not None else self.default_timeout_s
                ),
                total_num=total,
            )
            self._gangs[key] = state
        # the FIRST member to register pins the gang's policy (its explicit
        # annotation, else the once-satisfied default) — the reference
        # parses the policy once at gang creation (from the CRD or the
        # first pod), so a differently-annotated straggler can never flip
        # an established gang's policy mid-lifecycle (last-writer-wins was
        # an advisor finding); the CRD annotation retains authority via
        # upsert_pod_group.
        if not state.policy_declared:
            state.match_policy = match_policy_of(pod)
            state.policy_declared = True
        if not state.mode_declared:
            state.mode = ext.gang_mode_of(pod.meta.annotations)
            state.mode_declared = True
        return state

    def begin_cycle(self, pending: Sequence[Pod]) -> None:
        """Rebuild gang pending membership from the live pending set so
        deleted/ghost members don't count forever, then register the
        current pods."""
        for state in self._gangs.values():
            state.pending.clear()
        for pod in pending:
            self.add_pending_pod(pod)

    def add_pending_pod(self, pod: Pod) -> None:
        key = gang_key_of(pod)
        if key is None:
            return
        self._gang_for_pod(key, pod).pending[pod.meta.uid] = pod

    def remove_pod(self, pod: Pod, bound: bool) -> None:
        key = gang_key_of(pod)
        if key is None:
            return
        state = self._gangs.get(key)
        if state is None:
            return
        state.pending.pop(pod.meta.uid, None)
        if bound:
            # PostBind (core/core.go:429-441 addBoundPod): record the bound
            # member and mark the gang once-satisfied — any bind implies
            # Permit already allowed the whole gang (gang.go:456-459)
            state.bound += 1
            state.satisfied = True

    @property
    def has_gangs(self) -> bool:
        """Whether any gang state exists — callers combine this with their
        own batch-lowered gang signal to skip Permit entirely (see
        ``permit``'s internal bypass, which stays the source of truth for
        correctness when called)."""
        return bool(self._gangs)

    def pre_enqueue(self, pod: Pod, now: Optional[float] = None) -> Tuple[bool, str]:
        """Gate: a gang pod may enter scheduling only once the gang has at
        least minMember known members (pending + bound), reference
        ``core/core.go:183-263``. A gang stuck past its schedule timeout is
        gated for one cycle and its clock reset (the reference's Permit
        timeout rejects the gang group and re-queues it with backoff)."""
        key = gang_key_of(pod)
        if key is None:
            return True, ""
        state = self._gang_for_pod(key, pod)
        return self._gate(key, state, pod, now if now is not None else time.time())

    def _gate(
        self, key: str, state: _GangState, pod: Pod, now: float
    ) -> Tuple[bool, str]:
        """Per-member eligibility against already-resolved gang state."""
        # once-satisfied gangs pass directly (core/core.go:199-201):
        # stragglers and restarted members schedule individually
        if state.once_satisfied:
            return True, ""
        if (
            state.bound_credit < state.effective_min(len(state.pending))
            and now - state.create_time > state.schedule_timeout_s
        ):
            state.create_time = now
            # the scheduler stamps the timeout annotation on the member
            # (AnnotationGangTimeout, coscheduling.go:48-50) so operators
            # and controllers can see WHY the gang is cycling
            pod.meta.annotations[ext.ANNOTATION_GANG_TIMEOUT] = "true"
            return False, f"gang {key} timed out; backing off one cycle"
        total = len(state.pending) + state.bound_credit
        need = state.effective_min(total)
        if total < need:
            return False, f"gang {key} has {total}/{need} members"
        return True, ""

    def batch_gangs_warm(self, batch: Sequence[Pod]) -> bool:
        """Whether every gang-labeled pod in ``batch`` belongs to a WARM
        gang — the cross-cycle pipeline's ``batch_gangs`` gate (open the
        speculation gates PR). Warm means the gang's satisfaction verdict
        is derivable from the batch alone and a speculative prepare is
        harmless:

        * a known once-satisfied gang (stragglers schedule individually);
        * a gang — known or first-seen — whose minMember is met by this
          batch's members plus already-bound credit;
        * and, for known gangs, NOT currently past its schedule timeout
          (the timeout branch of ``_gate`` mutates state and stamps the
          member, which a discarded speculation must never double-run).

        Read-only: unlike ``begin_and_order`` this registers nothing, so
        the PUMP thread can evaluate it before deciding whether the
        prepare worker may touch the batch. Cold gangs (members missing)
        simply keep the gate closed — the serial cycle gates them like
        before."""
        members: Dict[str, int] = {}
        first: Dict[str, Pod] = {}
        for pod in batch:
            key = gang_key_of(pod)
            if key is None:
                continue
            members[key] = members.get(key, 0) + 1
            first.setdefault(key, pod)
        if not members:
            return True
        now = time.time()
        for key, count in members.items():
            state = self._gangs.get(key)
            if state is None:
                # first sight of the gang: warm iff the batch itself
                # carries min-available (else unknowable) and meets it
                mm = ext.gang_min_available_of(first[key])
                if mm is None or count < mm:
                    return False
                continue
            if state.once_satisfied:
                continue
            need = state.effective_min(count)
            if (
                state.bound_credit < need
                and now - state.create_time > state.schedule_timeout_s
            ):
                return False
            if count + state.bound_credit < need:
                return False
        return True

    def gang_view(self, batch: Sequence[Pod]) -> tuple:
        """Frozen per-gang lowering inputs for ``batch``, exactly as
        ``build_pods`` would read them through the live
        :meth:`min_member_map` / :meth:`nonstrict_map` views: one
        ``(key, outstanding_min, nonstrict)`` triple per distinct gang.
        The pipeline stamps this on a speculative solve at lowering time
        and re-derives it at consume — a mid-pipeline change (a member
        bound by the trailing commit shrinking the outstanding min, a
        mode declaration arriving) makes the views diverge and the
        speculation is discarded instead of consumed with stale gang
        rows."""
        mm = _MinMemberView(self._gangs)
        ns = _NonStrictView(self._gangs)
        seen = []
        done = set()
        for pod in batch:
            key = gang_key_of(pod)
            if key is None or key in done:
                continue
            done.add(key)
            seen.append((key, mm.get(key), ns.get(key)))
        return tuple(seen)

    def min_member_map(self) -> "Mapping[str, int]":
        """Per-gang minMember still outstanding for the solver: already
        bound members reduce the requirement, so stragglers joining a
        satisfied gang schedule individually. Gangs with unknown minMember
        are omitted (build_pods falls back to batch member count).

        Returns a LIVE read-through view — materializing a dict over
        every known gang per chunk was a measured slice of the
        device-gang commit wall, and the view keeps cross-chunk gangs
        seeing bound-credit updates mid-drain."""
        return _MinMemberView(self._gangs)

    def nonstrict_map(self) -> "Mapping[str, bool]":
        """Per-gang NonStrict flag for the solver lowering — only gangs
        whose mode has been declared (CRD / first member); others resolve
        from the batch's own pod annotations in build_pods. Live
        read-through view (see :meth:`min_member_map`)."""
        return _NonStrictView(self._gangs)

    def begin_and_order(self, pending: Sequence[Pod]) -> List[Pod]:
        """Fused :meth:`begin_cycle` + :meth:`order_pending`: one pass
        resolves each pod's gang key and state exactly once (the two
        separate passes re-ran ``_gang_for_pod`` per member and were a
        measured slice of the device-gang cycle's host wall)."""
        for state in self._gangs.values():
            state.pending.clear()
        keys: List[Optional[str]] = []
        states: Dict[str, _GangState] = {}
        first_arrival: Dict[str, int] = {}
        gang_prio: Dict[str, int] = {}
        floor = -(1 << 62)
        for i, pod in enumerate(pending):
            key = gang_key_of(pod)
            keys.append(key)
            if key is None:
                continue
            st = states.get(key)
            if st is None:
                st = self._gang_for_pod(key, pod)
                states[key] = st
                first_arrival[key] = i
            st.pending[pod.meta.uid] = pod
            prio = pod.spec.priority or 0
            if prio > gang_prio.get(key, floor):
                gang_prio[key] = prio
        now = time.time()
        decorated = []
        for i, pod in enumerate(pending):
            key = keys[i]
            prio = pod.spec.priority or 0
            if key is None:
                decorated.append((-prio, i, "", i, pod))
                continue
            ok, _ = self._gate(key, states[key], pod, now)
            if ok:
                decorated.append(
                    (-gang_prio.get(key, prio), first_arrival[key], key, i, pod)
                )
        decorated.sort(key=lambda t: t[:4])
        return [t[4] for t in decorated]

    def order_pending(self, pods: Sequence[Pod]) -> List[Pod]:
        """NextPod semantics: keep gang members adjacent, ordered by the
        gang's highest member priority, so whole gangs land in one solver
        batch (``core/core.go:135-176``). Re-registering the pending set
        is idempotent, so this simply delegates to the fused pass."""
        return self.begin_and_order(pods)

    def permit(
        self, results: Iterable[Tuple[Pod, Optional[str]]]
    ) -> Tuple[List[Tuple[Pod, str]], List[Pod]]:
        """All-or-nothing Permit over one batch's commit results: gangs with
        fewer than minMember surviving placements are rejected whole, and a
        gang linked into a gang *group* (the gang-groups annotation,
        reference ``core/core.go:346-465`` AllowGangGroup) passes only when
        every gang in its group passes — one failing gang rejects the
        whole group's placements."""
        results = list(results)
        if not self._gangs and not any(
            gang_key_of(p) is not None for p, _ in results
        ):
            # no gang state and no gang-labeled pod in the batch: the
            # per-pod gang bookkeeping is pure overhead (hot commit path)
            return (
                [(p, n) for p, n in results if n is not None],
                [p for p, n in results if n is None],
            )
        placed_per_gang: Dict[str, int] = {}
        members_per_gang: Dict[str, int] = {}
        groups_of_gang: Dict[str, frozenset] = {}
        mode_of_gang: Dict[str, str] = {}
        for pod, node in results:
            key = gang_key_of(pod)
            if key is None:
                continue
            members_per_gang[key] = members_per_gang.get(key, 0) + 1
            if node is not None:
                placed_per_gang[key] = placed_per_gang.get(key, 0) + 1
            if key not in groups_of_gang:
                groups_of_gang[key] = gang_group_of(pod, key)
            if key not in mode_of_gang:
                state = self._gangs.get(key)
                mode_of_gang[key] = (
                    state.mode
                    if state is not None and state.mode_declared
                    else ext.gang_mode_of(pod.meta.annotations)
                )

        def gang_passes(key: str) -> bool:
            state = self._gangs.get(key)
            if state is not None and state.once_satisfied:
                # core/core.go:393: a once-satisfied gang's members pass
                # Permit individually
                return True
            fallback = members_per_gang.get(key, 0)
            need = state.effective_min(fallback) if state else fallback
            have = placed_per_gang.get(key, 0) + (
                state.bound_credit if state else 0
            )
            return have >= need

        gang_ok = {key: gang_passes(key) for key in members_per_gang}
        # Only a *Strict* failing gang rejects — and it rejects its whole
        # gang group. A NonStrict gang's partial placement keeps its
        # placed members and never cascades to the group (the reference's
        # rejectGangGroupById runs only in Strict mode,
        # core/core.go:333,394).
        strict_fail = {
            key: not gang_ok[key]
            and mode_of_gang.get(key) != ext.GANG_MODE_NONSTRICT
            for key in members_per_gang
        }
        group_ok: Dict[str, bool] = {}
        for key in members_per_gang:
            # every linked gang that appears in this batch must be free of
            # Strict failures; linked gangs absent gate via PreEnqueue
            group_ok[key] = not any(
                strict_fail.get(linked, False)
                for linked in groups_of_gang.get(key, frozenset({key}))
            )

        allowed: List[Tuple[Pod, str]] = []
        rejected: List[Pod] = []
        for pod, node in results:
            key = gang_key_of(pod)
            if node is None:
                rejected.append(pod)
                continue
            if key is not None and not group_ok.get(key, True):
                rejected.append(pod)
                continue
            allowed.append((pod, node))
        return allowed, rejected
